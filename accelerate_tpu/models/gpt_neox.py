"""GPT-NeoX causal LM: parallel-residual transformer with partial rotary
position embeddings.

GPT-NeoX-20B and GPT-J-6B are rows of the reference's big-model-inference
benchmark (reference ``benchmarks/big_model_inference/README.md:31-34``);
this family makes both instantiable by name. The two published
architectures share the block (parallel residual ``x + attn(...) + mlp(...)``,
rotary applied to the first ``rotary_dim`` dims of each head, GELU MLP,
untied LM head); they differ only in whether the attention and MLP
branches read separate LayerNorms (NeoX) or one shared LayerNorm (GPT-J,
``shared_layernorm=True``) and whether the QKV/output projections carry
biases (NeoX yes, GPT-J no). Same TPU-first recipe as :mod:`.gpt2`:
layer-stacked params + ``lax.scan``, flash attention routing, partition
rules for tp/fsdp.

HF-name conversion covers the ``gpt_neox`` naming scheme (fused QKV stored
``[heads, 3, head_dim]``-interleaved, rotate-half rotary — the same
rotation this module computes). GPT-J *checkpoints* use rotate-every-two
rotary ordering; loading one requires an even/odd permutation of the
q/k projection columns, applied in :func:`convert_hf_gptj_state_dict`.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..modules import Model, ModelOutput
from ..ops.attention import attention
from ..ops.fp8 import dense
from ..ops.layers import (
    apply_rope,
    cached_attention,
    cross_entropy_loss,
    rope_frequencies,
    write_kv_cache,
)
from ..parallel.pipeline import remat_wrap
from .gpt2 import layer_norm
from .llama import _constrain, residual_spec


@dataclass
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    rotary_pct: float = 0.25  # fraction of head_dim that rotates
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5
    #: False (e.g. StableLM-style NeoX checkpoints): sequential residual
    #: ``x += attn(ln1(x)); x += mlp(ln2(x))`` instead of the parallel sum
    use_parallel_residual: bool = True
    #: GPT-J: one LayerNorm feeds both the attn and MLP branches
    shared_layernorm: bool = False
    #: GPT-J: no biases on the q/k/v and attn-output projections
    attention_bias: bool = True
    #: MLP GELU flavor: None resolves by family — GPT-NeoX checkpoints use
    #: exact (erf) GELU (HF ``hidden_act="gelu"``) while GPT-J uses the tanh
    #: approximation (``gelu_new``); True/False force tanh/exact.
    gelu_approximate: bool | None = None
    remat: bool | str = False  # False | True | jax.checkpoint_policies name
    #: GPipe microbatch count when the mesh has a pp axis > 1 (0 = auto)
    pipeline_microbatches: int = 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def rotary_dim(self) -> int:
        # published configs always produce an even rotary_dim
        return int(self.head_dim * self.rotary_pct)

    @classmethod
    def tiny(cls, vocab_size=256, hidden_size=64, layers=2, heads=4, seq=128, **kw):
        return cls(
            vocab_size=vocab_size,
            hidden_size=hidden_size,
            intermediate_size=4 * hidden_size,
            num_hidden_layers=layers,
            num_attention_heads=heads,
            max_position_embeddings=seq,
            **kw,
        )

    @classmethod
    def neox_20b(cls):
        return cls(
            vocab_size=50432, hidden_size=6144, intermediate_size=24576,
            num_hidden_layers=44, num_attention_heads=64, rotary_pct=0.25,
        )

    @classmethod
    def pythia_1_4b(cls):
        return cls(
            vocab_size=50304, hidden_size=2048, intermediate_size=8192,
            num_hidden_layers=24, num_attention_heads=16, rotary_pct=0.25,
        )

    @classmethod
    def gptj_6b(cls):
        return cls(
            vocab_size=50400, hidden_size=4096, intermediate_size=16384,
            num_hidden_layers=28, num_attention_heads=16,
            rotary_pct=0.25,  # rotary_dim 64 of head_dim 256
            shared_layernorm=True, attention_bias=False,
        )


GPT_NEOX_PARTITION_RULES = [
    (r"wte", P("tp", "fsdp")),
    (r"layers\.w_qkv", P(None, "fsdp", "tp")),
    (r"layers\.b_qkv", P(None, "tp")),
    (r"layers\.w_proj", P(None, "tp", "fsdp")),
    (r"layers\.w_fc", P(None, "fsdp", "tp")),
    (r"layers\.b_fc", P(None, "tp")),
    (r"layers\.w_out", P(None, "tp", "fsdp")),
    (r"layers\.(ln1|ln2)_(g|b)", P()),
    (r"layers\.(b_proj|b_out)", P()),
    (r"ln_f_(g|b)", P()),
    (r"lm_head_b", P("tp")),  # before lm_head: rules match by first search hit
    (r"lm_head", P(None, "tp")),
]


def init_gpt_neox_params(key: jax.Array, config: GPTNeoXConfig, dtype=jnp.float32):
    c = config
    h, ff, L = c.hidden_size, c.intermediate_size, c.num_hidden_layers
    keys = jax.random.split(key, 8)

    def w(k, *shape):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * 0.02).astype(dtype)

    params = {
        "wte": w(keys[0], c.vocab_size, h),
        "layers": {
            "ln1_g": jnp.ones((L, h), dtype), "ln1_b": jnp.zeros((L, h), dtype),
            "w_qkv": w(keys[1], L, h, 3 * h),
            "w_proj": w(keys[2], L, h, h),
            "w_fc": w(keys[3], L, h, ff),
            "b_fc": jnp.zeros((L, ff), dtype),
            "w_out": w(keys[4], L, ff, h),
            "b_out": jnp.zeros((L, h), dtype),
        },
        "ln_f_g": jnp.ones((h,), dtype),
        "ln_f_b": jnp.zeros((h,), dtype),
        "lm_head": w(keys[5], h, c.vocab_size),  # untied (NeoX embed_out)
    }
    if not c.shared_layernorm:
        params["layers"]["ln2_g"] = jnp.ones((L, h), dtype)
        params["layers"]["ln2_b"] = jnp.zeros((L, h), dtype)
    if c.attention_bias:
        params["layers"]["b_qkv"] = jnp.zeros((L, 3 * h), dtype)
        params["layers"]["b_proj"] = jnp.zeros((L, h), dtype)
    else:
        params["lm_head_b"] = jnp.zeros((c.vocab_size,), dtype)  # GPT-J head bias
    return params


def _gelu(c: GPTNeoXConfig, x):
    """Family-resolved GELU: exact erf for NeoX, tanh for GPT-J (which is
    identified by its shared LayerNorm) unless ``gelu_approximate`` forces
    one. The tanh/erf gap is ~1e-3 at |x|≈2 — above checkpoint-parity
    tolerance, so the flavor must match the published architecture."""
    approx = c.gelu_approximate
    if approx is None:
        approx = c.shared_layernorm  # GPT-J
    return jax.nn.gelu(x, approximate=approx)


def _partial_rope(x, cos, sin, positions, rotary_dim):
    """Rotate the first ``rotary_dim`` dims of each head, pass the rest."""
    x_rot, x_pass = x[..., :rotary_dim], x[..., rotary_dim:]
    return jnp.concatenate([apply_rope(x_rot, cos, sin, positions), x_pass], axis=-1)


def gpt_neox_layer_apply(
    config: GPTNeoXConfig, layer, x, attention_mask, rope, positions,
    return_kv: bool = False,
):
    """One parallel-residual block on UNstacked layer params (shared by the
    scan body and the streaming executor): both branches read the *input*
    hidden state, so ``x + attn(ln1(x)) + mlp(ln2(x))`` — one residual add,
    not two sequential ones. ``return_kv`` additionally returns this
    block's (K, V) so prefill caches reuse them."""
    c = config
    cos, sin = rope
    nh, hd = c.num_attention_heads, c.head_dim
    b, s, h = x.shape
    y = layer_norm(x, layer["ln1_g"], layer["ln1_b"], c.layer_norm_eps)
    qkv = dense(y, layer["w_qkv"])
    if c.attention_bias:
        qkv = qkv + layer["b_qkv"]
    q, k, v = (z.reshape(b, s, nh, hd) for z in jnp.split(qkv, 3, axis=-1))
    q = _partial_rope(q, cos, sin, positions, c.rotary_dim)
    k = _partial_rope(k, cos, sin, positions, c.rotary_dim)
    q = _constrain(q, P(("dp", "fsdp"), "cp", "tp", None))
    k = _constrain(k, P(("dp", "fsdp"), "cp", "tp", None))
    attn = attention(q, k, v, segment_mask=attention_mask, causal=True)
    attn_out = dense(attn.reshape(b, s, h), layer["w_proj"])
    if c.attention_bias:
        attn_out = attn_out + layer["b_proj"]
    if not c.use_parallel_residual:
        x = x + attn_out
        attn_out = 0.0  # folded in already; the final add below is mlp-only
    if c.shared_layernorm:
        y2 = y  # GPT-J: the MLP branch reads the same normed input
    else:
        y2 = layer_norm(x, layer["ln2_g"], layer["ln2_b"], c.layer_norm_eps)
    mlp_out = dense(
        _gelu(c, dense(y2, layer["w_fc"]) + layer["b_fc"]), layer["w_out"]
    ) + layer["b_out"]
    x = x + attn_out + mlp_out
    x = _constrain(x, residual_spec())
    if return_kv:
        return x, (k, v)
    return x


def gpt_neox_apply(
    config: GPTNeoXConfig,
    params,
    input_ids: jax.Array,
    attention_mask: jax.Array | None = None,
    labels: jax.Array | None = None,
    positions: jax.Array | None = None,
    use_cache: bool = False,
    kv_cache=None,  # {"k","v"}: [L, b, max_cache, nh, hd] (decode step)
    cache_index: jax.Array | None = None,  # [b] per-row write position
    max_cache_len: int | None = None,
):
    c = config
    b, s = input_ids.shape
    if s > c.max_position_embeddings:
        raise ValueError(
            f"sequence length {s} exceeds max_position_embeddings "
            f"{c.max_position_embeddings}: the RoPE table gather would "
            "silently clamp, producing wrong logits"
        )
    from ..parallel.pipeline import active_pipeline_mesh, pipeline_layer_stack

    pp_mesh = active_pipeline_mesh()
    if kv_cache is not None:
        return _gpt_neox_decode_step(c, params, input_ids, kv_cache, cache_index)
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    cos, sin = rope_frequencies(c.rotary_dim, c.max_position_embeddings, c.rope_theta)

    x = params["wte"][input_ids]
    x = _constrain(x, residual_spec())

    caches = None
    if use_cache:
        max_cache = int(max_cache_len or c.max_position_embeddings)
        if not (s <= max_cache <= c.max_position_embeddings):
            raise ValueError(
                f"max_cache_len {max_cache} must be in [{s} (prompt length), "
                f"{c.max_position_embeddings} (max_position_embeddings)]"
            )

        from ..parallel.pipeline import prefill_layer_stack

        pad = ((0, 0), (0, max_cache - s), (0, 0), (0, 0))

        def prefill_layer(layer, h, pos_b, mask_b):
            out, (k, v) = gpt_neox_layer_apply(
                c, layer, h, mask_b, (cos, sin), pos_b, return_kv=True
            )
            return out, (jnp.pad(k, pad), jnp.pad(v, pad))

        x, caches = prefill_layer_stack(
            prefill_layer, params["layers"], x,
            (c.num_hidden_layers, b, max_cache, c.num_attention_heads, c.head_dim),
            positions=positions, mask=attention_mask,
        )
    elif pp_mesh is not None:
        x = pipeline_layer_stack(
            lambda layer, h, pos_mb, mask_mb: gpt_neox_layer_apply(
                c, layer, h, mask_mb, (cos, sin), pos_mb
            ),
            params["layers"], x,
            mesh=pp_mesh,
            remat=c.remat,
            positions=positions,
            mask=attention_mask,
            num_microbatches=c.pipeline_microbatches,
        )
    else:
        def body(x, layer):
            return gpt_neox_layer_apply(
                c, layer, x, attention_mask, (cos, sin), positions
            ), None

        body_fn = remat_wrap(body, c.remat)
        x, _ = jax.lax.scan(body_fn, x, params["layers"])

    x = layer_norm(x, params["ln_f_g"], params["ln_f_b"], c.layer_norm_eps)
    logits = dense(x, params["lm_head"])
    if "lm_head_b" in params:
        logits = logits + params["lm_head_b"]
    logits = _constrain(logits, P(("dp", "fsdp"), "cp", "tp"))

    out = ModelOutput(logits=logits)
    if caches is not None:
        out["kv_cache"] = caches
    if labels is not None:
        out["loss"] = cross_entropy_loss(logits[:, :-1, :], labels[:, 1:])
    return out


def _gpt_neox_decode_layer(c, layer, x, k_cache_l, v_cache_l, idx, rope, pp_manual=False):
    """One cached decode block on UNstacked layer params: the parallel
    residual with partial rotary at each row's cache position
    (``pp_manual``: see :func:`accelerate_tpu.ops.layers.write_kv_cache`)."""
    cos, sin = rope
    b, s, _ = x.shape
    nh, hd = c.num_attention_heads, c.head_dim
    positions = idx[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]  # [b, s]
    y = layer_norm(x, layer["ln1_g"], layer["ln1_b"], c.layer_norm_eps)
    qkv = dense(y, layer["w_qkv"])
    if c.attention_bias:
        qkv = qkv + layer["b_qkv"]
    q, k, v = (z.reshape(b, s, nh, hd) for z in jnp.split(qkv, 3, axis=-1))
    q = _partial_rope(q, cos, sin, positions, c.rotary_dim)
    k = _partial_rope(k, cos, sin, positions, c.rotary_dim)
    if pp_manual:
        q = _constrain(q, P())
    k_cache_l, v_cache_l = write_kv_cache(
        k_cache_l, v_cache_l, k, v, idx, pin_replicated=pp_manual
    )
    attn = cached_attention(q, k_cache_l, v_cache_l, idx)
    attn_out = dense(attn.reshape(b, s, nh * hd), layer["w_proj"])
    if c.attention_bias:
        attn_out = attn_out + layer["b_proj"]
    if not c.use_parallel_residual:
        x = x + attn_out
        attn_out = 0.0  # folded in already; the final add below is mlp-only
    y2 = y if c.shared_layernorm else layer_norm(
        x, layer["ln2_g"], layer["ln2_b"], c.layer_norm_eps
    )
    mlp_out = dense(
        _gelu(c, dense(y2, layer["w_fc"]) + layer["b_fc"]), layer["w_out"]
    ) + layer["b_out"]
    return x + attn_out + mlp_out, k_cache_l, v_cache_l


def _gpt_neox_decode_step(c, params, input_ids, kv_cache, cache_index):
    """One cached decode step: s == 1 token per row appended at
    ``cache_index[b]``; the layer loop is owned by
    :func:`parallel.pipeline.decode_stack`."""
    from ..parallel.pipeline import decode_stack

    b, s = input_ids.shape
    idx = jnp.asarray(cache_index, jnp.int32).reshape(b)
    cos, sin = rope_frequencies(c.rotary_dim, c.max_position_embeddings, c.rope_theta)
    x = params["wte"][input_ids]

    x, kv = decode_stack(
        lambda layer, h, kc_l, vc_l, idx_b, pp_manual: _gpt_neox_decode_layer(
            c, layer, h, kc_l, vc_l, idx_b, (cos, sin), pp_manual=pp_manual
        ),
        params["layers"], kv_cache, x, broadcast=(idx,),
    )
    x = layer_norm(x, params["ln_f_g"], params["ln_f_b"], c.layer_norm_eps)
    logits = dense(x, params["lm_head"])
    if "lm_head_b" in params:
        logits = logits + params["lm_head_b"]
    return ModelOutput(logits=logits, kv_cache=kv)


def _layer_keys(config: GPTNeoXConfig):
    keys = ["ln1_g", "ln1_b", "w_qkv", "w_proj", "w_fc", "b_fc", "w_out", "b_out"]
    if not config.shared_layernorm:
        keys += ["ln2_g", "ln2_b"]
    if config.attention_bias:
        keys += ["b_qkv", "b_proj"]
    return keys


def gpt_neox_segments(config: GPTNeoXConfig):
    """Streaming plan (offload/pipeline executors): embed → L× layer →
    final-norm+head (mirrors ``gpt2_segments``)."""
    layer_keys = _layer_keys(config)

    def plan(input_ids=None, attention_mask=None, positions=None, labels=None, **kw):
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        rope = rope_frequencies(
            config.rotary_dim, config.max_position_embeddings, config.rope_theta
        )

        def init():
            return {
                "ids": jnp.asarray(input_ids),
                "mask": None if attention_mask is None else jnp.asarray(attention_mask),
                "pos": positions,
            }

        def embed_fn(seg, carry):
            return {**carry, "x": seg["wte"][carry["ids"]]}

        def layer_fn(seg, carry):
            layer = {k: seg[f"layers.{k}"] for k in layer_keys}
            return {
                **carry,
                "x": gpt_neox_layer_apply(
                    config, layer, carry["x"], carry["mask"], rope, carry["pos"]
                ),
            }

        head_leaves = ["ln_f_g", "ln_f_b", "lm_head"]
        if not config.attention_bias:
            head_leaves.append("lm_head_b")

        def head_fn(seg, carry):
            x = layer_norm(carry["x"], seg["ln_f_g"], seg["ln_f_b"], config.layer_norm_eps)
            logits = dense(x, seg["lm_head"])
            if "lm_head_b" in seg:
                logits = logits + seg["lm_head_b"]
            return {**carry, "logits": logits}

        steps = [("embed", ["wte"], embed_fn)]
        for i in range(config.num_hidden_layers):
            steps.append(
                (("layer", i), [(f"layers.{k}", i) for k in layer_keys], layer_fn)
            )
        steps.append(("head", head_leaves, head_fn))

        def finalize(carry):
            out = ModelOutput(logits=carry["logits"])
            if labels is not None:
                out["loss"] = cross_entropy_loss(
                    carry["logits"][:, :-1, :], jnp.asarray(labels)[:, 1:]
                )
            return out

        return {"init": init, "steps": steps, "finalize": finalize}

    return plan


def convert_hf_gpt_neox_state_dict(flat: dict, config: GPTNeoXConfig) -> dict:
    """HF-transformers GPT-NeoX naming → this model's stacked layout.

    HF fuses QKV as ``[3*h, h]`` with rows interleaved per head
    ``[head0-q, head0-k, head0-v, head1-q, ...]``; ours splits Q|K|V
    contiguously on the output dim, so the rows are de-interleaved before
    the transpose. HF rotary is rotate-half over the first ``rotary_dim``
    dims — identical to :func:`apply_rope` — so no column permutation."""
    c = config
    L, nh, hd, h = c.num_hidden_layers, c.num_attention_heads, c.head_dim, c.hidden_size

    def get(name):
        for prefix in ("gpt_neox.", ""):
            if prefix + name in flat:
                return np.asarray(flat[prefix + name])
        raise KeyError(name)

    def split_qkv_w(w_hf):  # [3h, h] interleaved → [h, 3h] contiguous
        w = w_hf.reshape(nh, 3, hd, h)
        return np.concatenate(
            [w[:, j].reshape(nh * hd, h).T for j in range(3)], axis=1
        )

    def split_qkv_b(b_hf):  # [3h] interleaved → [3h] contiguous
        b = b_hf.reshape(nh, 3, hd)
        return np.concatenate([b[:, j].reshape(nh * hd) for j in range(3)])

    def stack(fmt, f=lambda a: a):
        return np.stack([f(get(fmt.format(i))) for i in range(L)])

    layers = {
        "ln1_g": stack("layers.{}.input_layernorm.weight"),
        "ln1_b": stack("layers.{}.input_layernorm.bias"),
        "w_qkv": stack("layers.{}.attention.query_key_value.weight", split_qkv_w),
        "b_qkv": stack("layers.{}.attention.query_key_value.bias", split_qkv_b),
        "w_proj": stack("layers.{}.attention.dense.weight", lambda a: a.T),
        "b_proj": stack("layers.{}.attention.dense.bias"),
        "ln2_g": stack("layers.{}.post_attention_layernorm.weight"),
        "ln2_b": stack("layers.{}.post_attention_layernorm.bias"),
        "w_fc": stack("layers.{}.mlp.dense_h_to_4h.weight", lambda a: a.T),
        "b_fc": stack("layers.{}.mlp.dense_h_to_4h.bias"),
        "w_out": stack("layers.{}.mlp.dense_4h_to_h.weight", lambda a: a.T),
        "b_out": stack("layers.{}.mlp.dense_4h_to_h.bias"),
    }
    return {
        "wte": get("embed_in.weight"),
        "layers": layers,
        "ln_f_g": get("final_layer_norm.weight"),
        "ln_f_b": get("final_layer_norm.bias"),
        "lm_head": np.asarray(flat["embed_out.weight"]).T,
    }


def convert_hf_gptj_state_dict(flat: dict, config: GPTNeoXConfig) -> dict:
    """HF-transformers GPT-J naming → this model's stacked layout
    (``shared_layernorm=True``, ``attention_bias=False`` config).

    GPT-J checkpoints use rotate-every-two rotary ordering (pairs
    ``(x0,x1),(x2,x3),...``) while :func:`apply_rope` rotates halves
    (``(x_i, x_{i+rd/2})``); permuting the q/k projection columns within
    the rotary span — even columns first, then odd — makes the two
    orderings compute identical attention scores."""
    c = config
    L, rd, h = c.num_hidden_layers, c.rotary_dim, c.hidden_size
    nh, hd = c.num_attention_heads, c.head_dim
    # even/odd permutation within each head's rotary span
    perm_head = np.concatenate(
        [np.arange(0, rd, 2), np.arange(1, rd, 2), np.arange(rd, hd)]
    )
    perm = np.concatenate([perm_head + i * hd for i in range(nh)])

    def get(name):
        for prefix in ("transformer.", ""):
            if prefix + name in flat:
                return np.asarray(flat[prefix + name])
        raise KeyError(name)

    def stack(fmt, f=lambda a: a):
        return np.stack([f(get(fmt.format(i))) for i in range(L)])

    def qk(w_hf):  # [h, h] HF [out,in] → ours [in,out], rotary-permuted
        return w_hf.T[:, perm]

    return {
        "wte": get("wte.weight"),
        "layers": {
            "ln1_g": stack("h.{}.ln_1.weight"),
            "ln1_b": stack("h.{}.ln_1.bias"),
            "w_qkv": np.concatenate(
                [
                    stack("h.{}.attn.q_proj.weight", qk),
                    stack("h.{}.attn.k_proj.weight", qk),
                    stack("h.{}.attn.v_proj.weight", lambda a: a.T),
                ],
                axis=2,
            ),
            "w_proj": stack("h.{}.attn.out_proj.weight", lambda a: a.T),
            "w_fc": stack("h.{}.mlp.fc_in.weight", lambda a: a.T),
            "b_fc": stack("h.{}.mlp.fc_in.bias"),
            "w_out": stack("h.{}.mlp.fc_out.weight", lambda a: a.T),
            "b_out": stack("h.{}.mlp.fc_out.bias"),
        },
        "ln_f_g": get("ln_f.weight"),
        "ln_f_b": get("ln_f.bias"),
        "lm_head": np.asarray(flat["lm_head.weight"]).T,
        "lm_head_b": np.asarray(flat["lm_head.bias"]),
    }


class GPTNeoXForCausalLM:
    @staticmethod
    def from_config(config: GPTNeoXConfig, seed: int = 0, dtype=jnp.float32) -> Model:
        import dataclasses as _dc

        from ..big_modeling import is_empty_init
        from .gpt2 import _flatten

        # private copy: apply_fn closes over it (see GPT2LMHeadModel)
        config = _dc.replace(config)

        if is_empty_init():
            params = jax.eval_shape(
                lambda k: init_gpt_neox_params(k, config, dtype=dtype),
                jax.random.key(0),
            )
        else:
            params = init_gpt_neox_params(jax.random.key(seed), config, dtype=dtype)

        def apply_fn(p, **kwargs):
            return gpt_neox_apply(config, p, **kwargs)

        convert = (
            convert_hf_gptj_state_dict if config.shared_layernorm
            else convert_hf_gpt_neox_state_dict
        )
        model = Model(
            apply_fn, params,
            partition_rules=GPT_NEOX_PARTITION_RULES,
            name="GPTNeoXForCausalLM",
        )
        model.config = config
        model.supports_kv_cache = True
        model.stacked_params_prefix = "layers"
        model.segments = gpt_neox_segments(config)
        model.tied_parameters = []
        model.convert_state_dict = lambda flat: _flatten(convert(flat, config))
        return model
