"""ResNet image classifier, TPU-first (the reference's canonical CV model:
``create_model("resnet50d", ...)`` at ``/root/reference/examples/cv_example.py:121``).

Implements the "-d" variant faithfully (deep 3×3 stem, stride on the 3×3
bottleneck conv, average-pool shortcut downsampling — the timm resnet50d
architecture), as pure functions over an explicit parameter pytree:

* **NHWC layout + HWIO kernels** — the layouts XLA:TPU tiles onto the MXU
  without transposes; convolutions lower to ``lax.conv_general_dilated``.
* **BatchNorm normalises with the current batch's statistics** in both
  train and eval (functional purity: no running-stats side channel; eval
  parity with torch's running averages is traded for a pure step — the
  train-throughput BASELINE row this model serves is unaffected).
* **partition rules** — kernels shard input channels on ``fsdp`` and
  output channels on ``tp``; activations pin batch to ``('dp','fsdp')``.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..modules import Model, ModelOutput
from ..ops.layers import cross_entropy_loss
from .llama import _constrain


@dataclass
class ResNetConfig:
    depths: tuple = (3, 4, 6, 3)  # resnet50
    base_width: int = 64
    num_classes: int = 1000
    in_channels: int = 3
    bn_eps: float = 1e-5
    #: False | True | a jax.checkpoint_policies name (remat per stage)
    remat: bool | str = False

    @classmethod
    def resnet50d(cls, num_classes: int = 1000):
        return cls(num_classes=num_classes)

    @classmethod
    def tiny(cls, num_classes: int = 3):
        return cls(depths=(1, 1), base_width=8, num_classes=num_classes)


RESNET_PARTITION_RULES = [
    (r"conv", P(None, None, "fsdp", "tp")),  # HWIO kernels
    (r"(gamma|beta)", P()),
    (r"fc\.w", P("fsdp", "tp")),
    (r"fc\.b", P()),
]


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn(x, gamma, beta, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(0, 1, 2), keepdims=True)
    var = jnp.var(x32, axis=(0, 1, 2), keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (out * gamma + beta).astype(x.dtype)


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return (
        jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
        * np.sqrt(2.0 / fan_in)
    ).astype(jnp.float32)


def _bn_init(c):
    return jnp.ones((c,), jnp.float32), jnp.zeros((c,), jnp.float32)


def init_resnet_params(key, config: ResNetConfig):
    c = config
    keys = iter(jax.random.split(key, 256))
    w = c.base_width
    params = {
        # resnet-d deep stem: three 3x3 convs (32, 32, 64 for width 64)
        "stem": {
            "conv1": _conv_init(next(keys), 3, 3, c.in_channels, w // 2),
            "conv2": _conv_init(next(keys), 3, 3, w // 2, w // 2),
            "conv3": _conv_init(next(keys), 3, 3, w // 2, w),
        },
        "stages": [],
    }
    for name, ch in (("g1", w // 2), ("g2", w // 2), ("g3", w)):
        params["stem"][f"{name}_gamma"], params["stem"][f"{name}_beta"] = _bn_init(ch)

    cin = w
    for i, depth in enumerate(c.depths):
        planes = w * (2**i)
        cout = planes * 4
        blocks = []
        for b in range(depth):
            stride = 2 if (b == 0 and i > 0) else 1
            block = {
                "conv1": _conv_init(next(keys), 1, 1, cin, planes),
                "conv2": _conv_init(next(keys), 3, 3, planes, planes),
                "conv3": _conv_init(next(keys), 1, 1, planes, cout),
            }
            for j, ch in (("1", planes), ("2", planes), ("3", cout)):
                block[f"g{j}_gamma"], block[f"g{j}_beta"] = _bn_init(ch)
            if cin != cout:
                block["conv_proj"] = _conv_init(next(keys), 1, 1, cin, cout)
                block["gp_gamma"], block["gp_beta"] = _bn_init(cout)
            blocks.append(block)
            cin = cout
        params["stages"].append(blocks)
    params["fc"] = {
        "w": (
            jax.random.normal(next(keys), (cin, c.num_classes), jnp.float32)
            * np.sqrt(1.0 / cin)
        ),
        "b": jnp.zeros((c.num_classes,), jnp.float32),
    }
    return params


def _bottleneck_d(config, block, x, stride):
    """resnet-d bottleneck: stride lives on the 3×3; the shortcut
    downsamples with avg-pool + 1×1 (never a strided 1×1)."""
    c = config
    y = _conv(x, block["conv1"])
    y = jax.nn.relu(_bn(y, block["g1_gamma"], block["g1_beta"], c.bn_eps))
    y = _conv(y, block["conv2"], stride=stride)
    y = jax.nn.relu(_bn(y, block["g2_gamma"], block["g2_beta"], c.bn_eps))
    y = _conv(y, block["conv3"])
    y = _bn(y, block["g3_gamma"], block["g3_beta"], c.bn_eps)

    shortcut = x
    if stride > 1:
        shortcut = jax.lax.reduce_window(
            shortcut, 0.0, jax.lax.add, (1, stride, stride, 1),
            (1, stride, stride, 1), "SAME",
        ) / (stride * stride)
    if "conv_proj" in block:
        shortcut = _conv(shortcut, block["conv_proj"])
        shortcut = _bn(shortcut, block["gp_gamma"], block["gp_beta"], c.bn_eps)
    out = jax.nn.relu(y + shortcut)
    return _constrain(out, P(("dp", "fsdp"), None, None, "tp"))


def to_nhwc(pixel_values, in_channels: int):
    """Normalise image input to NHWC: append a channel dim to grayscale
    ``[b, h, w]`` and accept torch's NCHW layout (shared by every image
    model in the zoo)."""
    x = jnp.asarray(pixel_values)
    if x.ndim == 3:
        x = x[..., None]
    if x.shape[-1] != in_channels and x.shape[1] == in_channels:
        x = jnp.moveaxis(x, 1, -1)
    return x


def resnet_apply(config: ResNetConfig, params, pixel_values=None, labels=None, **kw):
    c = config
    x = to_nhwc(pixel_values, c.in_channels)
    s = params["stem"]
    x = _conv(x, s["conv1"], stride=2)
    x = jax.nn.relu(_bn(x, s["g1_gamma"], s["g1_beta"], c.bn_eps))
    x = _conv(x, s["conv2"])
    x = jax.nn.relu(_bn(x, s["g2_gamma"], s["g2_beta"], c.bn_eps))
    x = _conv(x, s["conv3"])
    x = jax.nn.relu(_bn(x, s["g3_gamma"], s["g3_beta"], c.bn_eps))
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )

    def run_stage(x, blocks, stage_idx):
        for b, block in enumerate(blocks):
            stride = 2 if (b == 0 and stage_idx > 0) else 1
            x = _bottleneck_d(c, block, x, stride)
        return x

    for i, blocks in enumerate(params["stages"]):
        stage = lambda x, blocks=blocks, i=i: run_stage(x, blocks, i)
        if c.remat:
            policy = None
            if isinstance(c.remat, str):
                policy = getattr(jax.checkpoint_policies, c.remat)
            stage = jax.checkpoint(stage, policy=policy)
        x = stage(x)

    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # global average pool
    logits = x @ params["fc"]["w"] + params["fc"]["b"]
    out = ModelOutput(logits=logits)
    if labels is not None:
        out["loss"] = cross_entropy_loss(logits[:, None, :], jnp.asarray(labels)[:, None])
    return out


class ResNetForImageClassification:
    """Factory mirroring the timm entry point the reference's cv example
    brings to ``prepare()`` (``cv_example.py:121``)."""

    @staticmethod
    def from_config(config: ResNetConfig, seed: int = 0) -> Model:
        import dataclasses as _dc

        from ..big_modeling import is_empty_init

        config = _dc.replace(config)

        def make_params(key):
            return init_resnet_params(key, config)

        if is_empty_init():
            params = jax.eval_shape(make_params, jax.random.PRNGKey(seed))
        else:
            params = make_params(jax.random.PRNGKey(seed))

        def apply_fn(p, pixel_values=None, labels=None, **kw):
            return resnet_apply(config, p, pixel_values=pixel_values, labels=labels, **kw)

        model = Model(
            apply_fn, params,
            partition_rules=RESNET_PARTITION_RULES,
            name="ResNetForImageClassification",
        )
        model.config = config
        return model
