"""Llama-family causal LM, TPU-first.

The flagship model (BASELINE config #3: Llama-2-7B FSDP finetune). Design,
per the scaling-book recipe rather than the reference's torch model zoo
(the reference itself ships no models — it wraps ``transformers``):

* **layer-stacked params + ``lax.scan``** — every block's weights carry a
  leading ``[n_layers]`` dim and one scan body applies the stack. Compile
  time is O(1) in depth and XLA sees one fused block program.
* **explicit partition rules** — q/k/v/gate/up project *out* along ``tp``,
  o/down project *in* along ``tp`` (one psum per block, rides ICI);
  everything else shards its largest dim on ``fsdp`` (ZeRO-3-style).
* **activation sharding constraints** — hidden states pinned to
  ``P(('dp','fsdp'), 'cp', None)`` so sequence/context parallelism composes.
* bf16 matmuls / fp32 norms+softmax; ``jax.checkpoint`` on the block for
  rematerialised backward.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..modules import Model, ModelOutput
from ..ops.attention import attention
from ..parallel.pipeline import remat_wrap
from ..ops.fp8 import dense
from ..ops.layers import (
    apply_rope,
    cached_attention,
    cross_entropy_loss,
    fused_cross_entropy,
    rms_norm,
    rope_cached_attention_block,
    rope_frequencies,
    shift_labels,
)


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    #: False | True (full recompute) | a jax.checkpoint_policies name
    remat: bool | str = True
    #: GPipe microbatch count when the mesh has a pp axis > 1
    #: (0 = auto: smallest batch divisor >= number of stages)
    pipeline_microbatches: int = 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def llama2_7b(cls):
        return cls()

    @classmethod
    def flagship_700m(cls, max_position_embeddings: int = 1024, remat: bool | str = False):
        """The ~700M bench flagship slice (hidden 1536, 12 heads × 128,
        ff 4h, 16 layers) — the largest credible-aspect-ratio shape whose
        fp32 adam state fits one v5e chip (sweep: benchmarks/sweep_mfu.py).
        Single source of truth for bench.py, benchmarks/serve_bench.py and
        the serve CLI's ``--preset flagship`` so they measure one model."""
        return cls(
            vocab_size=32000,
            hidden_size=1536,
            intermediate_size=6144,
            num_hidden_layers=16,
            num_attention_heads=12,
            num_key_value_heads=12,
            max_position_embeddings=max_position_embeddings,
            remat=remat,
        )

    @classmethod
    def tiny(cls, vocab_size=256, hidden_size=64, layers=2, heads=4, seq=128):
        return cls(
            vocab_size=vocab_size,
            hidden_size=hidden_size,
            intermediate_size=hidden_size * 3,
            num_hidden_layers=layers,
            num_attention_heads=heads,
            num_key_value_heads=heads,
            max_position_embeddings=seq,
            remat=False,
        )


#: path-regex → PartitionSpec. Layer-stacked leaves have a leading [layers]
#: dim (never sharded — it's the scan axis).
LLAMA_PARTITION_RULES = [
    (r"embed_tokens", P("tp", "fsdp")),
    (r"layers\.(wq|wk|wv)", P(None, "fsdp", "tp")),
    (r"layers\.wo", P(None, "tp", "fsdp")),
    (r"layers\.(w_gate|w_up)", P(None, "fsdp", "tp")),
    (r"layers\.w_down", P(None, "tp", "fsdp")),
    (r"norm", P()),
    (r"lm_head", P("fsdp", "tp")),
]


def init_llama_params(key: jax.Array, config: LlamaConfig, dtype=jnp.float32):
    """Initialise the layer-stacked parameter pytree."""
    c = config
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    h, ff, nh, nkv, hd = (
        c.hidden_size,
        c.intermediate_size,
        c.num_attention_heads,
        c.num_key_value_heads,
        c.head_dim,
    )
    L = c.num_hidden_layers

    def norm_init(*shape):
        return jnp.ones(shape, dtype=dtype)

    def dense_init(key, *shape, in_dim):
        scale = 1.0 / np.sqrt(in_dim)
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(dtype)

    ks = jax.random.split(k_layers, 8)
    params = {
        "embed_tokens": (
            jax.random.normal(k_embed, (c.vocab_size, h), dtype=jnp.float32) * 0.02
        ).astype(dtype),
        "layers": {
            "wq": dense_init(ks[0], L, h, nh * hd, in_dim=h),
            "wk": dense_init(ks[1], L, h, nkv * hd, in_dim=h),
            "wv": dense_init(ks[2], L, h, nkv * hd, in_dim=h),
            "wo": dense_init(ks[3], L, nh * hd, h, in_dim=nh * hd),
            "w_gate": dense_init(ks[4], L, h, ff, in_dim=h),
            "w_up": dense_init(ks[5], L, h, ff, in_dim=h),
            "w_down": dense_init(ks[6], L, ff, h, in_dim=ff),
            "attn_norm": norm_init(L, h),
            "mlp_norm": norm_init(L, h),
        },
        "norm": norm_init(h),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = dense_init(k_head, h, c.vocab_size, in_dim=h)
    return params


def llama_layer_apply(
    config: LlamaConfig, layer, x, cos, sin, positions, attention_mask,
    return_kv: bool = False,
):
    """One transformer block on UNstacked layer params — shared by the
    training scan body and the streaming (offload) executor.
    ``return_kv`` additionally returns the (rotated K, V) this block just
    computed, so the prefill cache reuses them instead of re-projecting."""
    c = config
    nh, nkv, hd = c.num_attention_heads, c.num_key_value_heads, c.head_dim
    b, s, h = x.shape
    # attention
    y = rms_norm(x, layer["attn_norm"], c.rms_norm_eps)
    q = dense(y, layer["wq"]).reshape(b, s, nh, hd)
    k = dense(y, layer["wk"]).reshape(b, s, nkv, hd)
    v = dense(y, layer["wv"]).reshape(b, s, nkv, hd)
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    q = _constrain(q, P(("dp", "fsdp"), "cp", "tp", None))
    k = _constrain(k, P(("dp", "fsdp"), "cp", "tp", None))
    attn = attention(q, k, v, segment_mask=attention_mask, causal=True)
    x = x + dense(attn.reshape(b, s, nh * hd), layer["wo"])
    x = _constrain(x, residual_spec())
    # mlp (SwiGLU)
    y = rms_norm(x, layer["mlp_norm"], c.rms_norm_eps)
    gated = jax.nn.silu(dense(y, layer["w_gate"])) * dense(y, layer["w_up"])
    x = x + dense(gated, layer["w_down"])
    x = _constrain(x, residual_spec())
    if return_kv:
        return x, (k, v)
    return x


def _block(config: LlamaConfig, cos, sin, positions, attention_mask):
    """One transformer block as a scan body over stacked layer params."""

    def body(x, layer):
        return llama_layer_apply(config, layer, x, cos, sin, positions, attention_mask), None

    return remat_wrap(body, config.remat)


def _constrain(x, spec):
    """Sharding constraint that is a no-op outside a mesh context where the
    axes don't exist (keeps the model runnable on a bare single device)."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except Exception:
        return x


def residual_spec() -> P:
    """Spec for norm/residual-region activations ``[b, s, h]``: batch over
    dp/fsdp, sequence over cp — and ALSO over tp under Megatron-style
    sequence parallelism (``MegatronLMPlugin(sequence_parallelism=True)``
    with tp>1; reference forwards the flag to Megatron at
    ``utils/dataclasses.py:1916-1919,2112``, where LayerNorm/dropout
    activations shard along sequence within the TP group). Between the
    matmul regions (which are head/ff-sharded on tp, full-sequence) GSPMD
    inserts the all-gather in / reduce-scatter out that Megatron's fused
    kernels code by hand, and per-device activation bytes in the norm
    regions shrink by the tp extent."""
    from ..ops.attention import get_attention_context

    if get_attention_context().megatron_sp:
        return P(("dp", "fsdp"), ("cp", "tp"), None)
    return P(("dp", "fsdp"), "cp", None)


def _pipeline_mesh():
    from ..parallel.pipeline import active_pipeline_mesh

    return active_pipeline_mesh()


def _pipeline_stack(c, layers, x, cos, sin, positions, attention_mask, mesh):
    """Run the transformer stack as a GPipe pipeline over the pp axis
    (layer-stacked params split into contiguous stages)."""
    from ..parallel.pipeline import pipeline_layer_stack

    return pipeline_layer_stack(
        lambda layer, h, pos_mb, mask_mb, cos_b, sin_b: llama_layer_apply(
            c, layer, h, cos_b, sin_b, pos_mb, mask_mb
        ),
        layers, x,
        mesh=mesh,
        remat=c.remat,
        positions=positions,
        mask=attention_mask,
        rope=(cos, sin),
        num_microbatches=c.pipeline_microbatches,
    )


def llama_apply(
    config: LlamaConfig,
    params,
    input_ids: jax.Array,  # [b, s] int32
    attention_mask: jax.Array | None = None,  # [b, s] 1=real
    labels: jax.Array | None = None,  # [b, s]; -100 ignored
    positions: jax.Array | None = None,
    use_cache: bool = False,
    kv_cache=None,  # {"k","v"}: [L, b, max_cache, n_kv, hd] (decode step)
    cache_index: jax.Array | None = None,  # [b] per-row write position
    max_cache_len: int | None = None,
    paged_kv=None,  # {"k","v"}: [L, num_blocks, block_size, n_kv, hd]
    block_tables: jax.Array | None = None,  # [b, max_blocks] pool block ids
    cache_positions: jax.Array | None = None,  # [b] first new token position
    paged_write_mask: jax.Array | None = None,  # [b, s] real-token mask
):
    """Forward pass; four modes:

    * training/eval (default) — full causal attention;
    * **prefill** (``use_cache=True``) — same, plus the per-layer K/V
      written into a ``[L, b, max_cache_len, n_kv, hd]`` cache returned as
      ``out.kv_cache``;
    * **decode** (``kv_cache=`` + ``cache_index=``) — ``input_ids`` is one
      token per row; K/V append at each row's own position (ragged-batch
      safe) and attention runs token-vs-cache in O(max_cache) — the KV-cache
      inference path (the reference gets this from transformers' generate);
    * **paged decode/prefill-chunk** (``paged_kv=`` + ``block_tables=`` +
      ``cache_positions=``) — the serving engine's block-paged cache path
      (``supports_paged_kv``): K/V scatter through each slot's block table
      into a shared pool, attention against the gathered logical prefix.
      One compiled ``[num_slots, 1]`` program serves every decode iteration
      for the lifetime of the engine; ``s > 1`` with a ``paged_write_mask``
      is a chunked-prefill slice of one prompt.
    """
    c = config
    b, s = input_ids.shape
    if s > c.max_position_embeddings:
        raise ValueError(
            f"sequence length {s} exceeds max_position_embeddings "
            f"{c.max_position_embeddings}: RoPE position tables would "
            "silently clamp, producing wrong logits"
        )
    cos, sin = rope_frequencies(c.head_dim, c.max_position_embeddings, c.rope_theta)

    # over a pp>1 mesh, prefill/decode run through the stage-local-cache
    # pipeline engine (parallel.pipeline.pipeline_cached_stack via the
    # prefill_stack/decode_stack drivers), so stage-split weights and
    # caches stay put instead of the plain scans all-gathering them
    if paged_kv is not None:
        return _llama_paged_step(
            c, params, input_ids, paged_kv, block_tables, cache_positions,
            paged_write_mask, cos, sin,
        )
    if kv_cache is not None:
        return _llama_decode_step(c, params, input_ids, kv_cache, cache_index, cos, sin)

    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))

    x = params["embed_tokens"][input_ids]
    x = _constrain(x, residual_spec())

    if use_cache:
        max_cache = int(max_cache_len or c.max_position_embeddings)
        if not (s <= max_cache <= c.max_position_embeddings):
            raise ValueError(
                f"max_cache_len {max_cache} must be in [{s} (prompt length), "
                f"{c.max_position_embeddings} (max_position_embeddings)] — "
                "above it RoPE tables would silently clamp"
            )

        from ..parallel.pipeline import prefill_layer_stack

        pad = ((0, 0), (0, max_cache - s), (0, 0), (0, 0))

        def prefill_layer(layer, h, pos_b, mask_b, cos_b, sin_b):
            out, (k, v) = llama_layer_apply(
                c, layer, h, cos_b, sin_b, pos_b, mask_b, return_kv=True
            )
            return out, (jnp.pad(k, pad), jnp.pad(v, pad))

        x, caches = prefill_layer_stack(
            prefill_layer, params["layers"], x,
            (c.num_hidden_layers, b, max_cache, c.num_key_value_heads, c.head_dim),
            positions=positions, mask=attention_mask, rope=(cos, sin),
        )
    else:
        pp_mesh = _pipeline_mesh()
        if pp_mesh is not None:
            x = _pipeline_stack(c, params["layers"], x, cos, sin, positions,
                                attention_mask, pp_mesh)
        else:
            body = _block(c, cos, sin, positions, attention_mask)
            x, _ = jax.lax.scan(body, x, params["layers"])

    x = rms_norm(x, params["norm"], c.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T
    logits = dense(x, head)
    logits = _constrain(logits, P(("dp", "fsdp"), "cp", "tp"))

    out = ModelOutput(logits=logits)
    if use_cache:
        out["kv_cache"] = caches
    if labels is not None:
        # predict token t+1 from prefix ≤ t. The loss is computed straight
        # from the pre-head hidden states (NOT from `logits` above): when a
        # training step only forces `loss`, XLA dead-code-eliminates the
        # full [b, s, vocab] logits buffer and the fused path holds one
        # sequence chunk of logits at a time — the memory headroom is what
        # lets the bench run larger per-chip batches. Under cp the sequence
        # dim is sharded, so chunking it would cut across shards; the plain
        # whole-sequence loss stays on that path.
        from ..ops.attention import get_attention_context

        ctx_mesh = get_attention_context().mesh
        cp_active = ctx_mesh is not None and dict(ctx_mesh.shape).get("cp", 1) > 1
        if cp_active:
            out["loss"] = cross_entropy_loss(logits[:, :-1, :], labels[:, 1:])
        else:
            out["loss"] = fused_cross_entropy(x, head, shift_labels(labels), dense_fn=dense)
    return out


def _llama_decode_layer(c, layer, x, k_cache_l, v_cache_l, cos, sin, idx, pp_manual=False):
    """One cached decode block on UNstacked layer params: the shared
    rope/cache attention sub-block + llama's SwiGLU MLP."""
    x, k_cache_l, v_cache_l = rope_cached_attention_block(
        layer, x, k_cache_l, v_cache_l, cos, sin, idx,
        c.num_attention_heads, c.num_key_value_heads, c.head_dim,
        c.rms_norm_eps, pp_manual=pp_manual,
    )
    y = rms_norm(x, layer["mlp_norm"], c.rms_norm_eps)
    gated = jax.nn.silu(dense(y, layer["w_gate"])) * dense(y, layer["w_up"])
    x = x + dense(gated, layer["w_down"])
    return x, k_cache_l, v_cache_l


def _llama_decode_step(c, params, input_ids, kv_cache, cache_index, cos, sin):
    """One cached decode step: s == 1 token per row, appended at
    ``cache_index[b]``; attention is q(1) against the cache prefix. The
    layer loop (plain scan vs pp stage pipeline) is owned by
    :func:`parallel.pipeline.decode_stack`."""
    from ..parallel.pipeline import decode_stack

    b, s = input_ids.shape
    idx = jnp.asarray(cache_index, jnp.int32).reshape(b)
    x = params["embed_tokens"][input_ids]

    x, kv = decode_stack(
        lambda layer, h, kc_l, vc_l, idx_b, cos_b, sin_b, pp_manual: _llama_decode_layer(
            c, layer, h, kc_l, vc_l, cos_b, sin_b, idx_b, pp_manual=pp_manual
        ),
        params["layers"], kv_cache, x, broadcast=(idx, cos, sin),
    )
    x = rms_norm(x, params["norm"], c.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T
    logits = dense(x, head)
    return ModelOutput(logits=logits, kv_cache=kv)


def _llama_paged_step(
    c, params, input_ids, paged_kv, block_tables, cache_positions,
    paged_write_mask, cos, sin,
):
    """One step against the block-paged KV pool: ``s == 1`` token per slot
    (the engine's single compiled decode program) or an ``s``-token prefill
    chunk of one prompt. K/V land in pool blocks through each slot's block
    table (:func:`ops.layers.write_paged_kv` — quantize-on-scatter when
    ``paged_kv`` carries ``k_scale``/``v_scale`` arrays, the engine's
    ``kv_dtype`` policy); attention is the fused block-table walk
    (:mod:`ops.paged_attention`), never a materialised span gather. The
    layer loop is a plain scan — the serving engine is a single-host path
    (no pp stage pipeline)."""
    from ..ops.layers import rope_paged_attention_block

    b, s = input_ids.shape
    idx = jnp.asarray(cache_positions, jnp.int32).reshape(b)
    x = params["embed_tokens"][input_ids]
    quantized = "k_scale" in paged_kv

    def body(x, layer_pages):
        if quantized:
            layer, kp_l, vp_l, ks_l, vs_l = layer_pages
        else:
            (layer, kp_l, vp_l), ks_l, vs_l = layer_pages, None, None
        out = rope_paged_attention_block(
            layer, x, kp_l, vp_l, cos, sin, block_tables, idx,
            c.num_attention_heads, c.num_key_value_heads, c.head_dim,
            c.rms_norm_eps, write_mask=paged_write_mask,
            k_scale_l=ks_l, v_scale_l=vs_l,
        )
        x, pages = out[0], out[1:]
        y = rms_norm(x, layer["mlp_norm"], c.rms_norm_eps)
        gated = jax.nn.silu(dense(y, layer["w_gate"])) * dense(y, layer["w_up"])
        x = x + dense(gated, layer["w_down"])
        return x, pages

    xs = (params["layers"], paged_kv["k"], paged_kv["v"])
    if quantized:
        xs = xs + (paged_kv["k_scale"], paged_kv["v_scale"])
    x, pages = jax.lax.scan(body, x, xs)
    x = rms_norm(x, params["norm"], c.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed_tokens"].T
    logits = dense(x, head)
    out_pages = {"k": pages[0], "v": pages[1]}
    if quantized:
        out_pages["k_scale"], out_pages["v_scale"] = pages[2], pages[3]
    return ModelOutput(logits=logits, paged_kv=out_pages)


def llama_early_exit_apply(config: LlamaConfig, draft_layers: int):
    """Early-exit draft for speculative decoding: an apply fn running only
    the target's first ``draft_layers`` transformer blocks, closed with the
    target's own final norm + head — the cheapest draft that shares the
    target's representation space (the bench ``spec`` mode's construction,
    here as a reusable factory the serving engine arms via
    ``EngineConfig(draft="early_exit:N")``).

    The returned fn takes the FULL model's params and slices the stacked
    layer leaves **in-trace** (``a[:draft_layers]``), so no persistent
    draft copy of the weights exists — the slice is a transient buffer of
    the compiled program (shard-check prices it as the ``draft_params``
    tier). Because the draft's layers are byte-identical to the target's
    prefix, its K/V at any cached position equal the target's for those
    layers: the serving engine exploits this by pointing the draft at the
    first ``draft_layers`` layers of the target's own paged pool — no
    separate draft cache, and prefix sharing / CoW / swap maintain the
    draft state for free."""
    if not 1 <= draft_layers < config.num_hidden_layers:
        raise ValueError(
            f"early-exit draft needs 1 <= layers < {config.num_hidden_layers} "
            f"(the target's depth), got {draft_layers}"
        )
    import dataclasses as _dc

    draft_config = _dc.replace(config, num_hidden_layers=draft_layers)

    def early_exit_apply(params, **kw):
        draft_params = {
            "embed_tokens": params["embed_tokens"],
            "layers": jax.tree.map(lambda a: a[:draft_layers], params["layers"]),
            "norm": params["norm"],
        }
        if "lm_head" in params:
            draft_params["lm_head"] = params["lm_head"]
        return llama_apply(draft_config, draft_params, **kw)

    return early_exit_apply


_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "attn_norm", "mlp_norm")


def llama_segments(config: LlamaConfig):
    """Streaming plan for :class:`accelerate_tpu.big_modeling.DispatchedModel`:
    embed → L× layer (one compiled fn reused) → norm+head. Layer params are
    addressed as ``("layers.wq", i)`` slices of the stacked leaves so
    host/disk tiers stream one layer at a time."""

    def plan(input_ids=None, attention_mask=None, positions=None, labels=None, **kw):
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        cos, sin = rope_frequencies(config.head_dim, config.max_position_embeddings, config.rope_theta)

        def init():
            return {
                "ids": jnp.asarray(input_ids),
                "mask": None if attention_mask is None else jnp.asarray(attention_mask),
                "pos": positions,
            }

        def embed_fn(seg, carry):
            x = seg["embed_tokens"][carry["ids"]]
            return {**carry, "x": x}

        def layer_fn(seg, carry):
            layer = {k: seg[f"layers.{k}"] for k in _LAYER_KEYS}
            x = llama_layer_apply(
                config, layer, carry["x"], cos, sin, carry["pos"], carry["mask"]
            )
            return {**carry, "x": x}

        def head_fn(seg, carry):
            x = rms_norm(carry["x"], seg["norm"], config.rms_norm_eps)
            head = seg.get("lm_head")
            if head is None:
                head = seg["embed_tokens"].T
            # dense(): quantized heads take the int8-GEMM / fused-LUT path
            return {**carry, "logits": dense(x, head)}

        steps = [("embed", ["embed_tokens"], embed_fn)]
        for i in range(config.num_hidden_layers):
            steps.append(
                (("layer", i), [(f"layers.{k}", i) for k in _LAYER_KEYS], layer_fn)
            )
        head_paths = ["norm"] + ([] if config.tie_word_embeddings else ["lm_head"])
        if config.tie_word_embeddings:
            head_paths.append("embed_tokens")
        steps.append(("head", head_paths, head_fn))

        def finalize(carry):
            out = ModelOutput(logits=carry["logits"])
            if labels is not None:
                out["loss"] = cross_entropy_loss(
                    carry["logits"][:, :-1, :], jnp.asarray(labels)[:, 1:]
                )
            return out

        return {"init": init, "steps": steps, "finalize": finalize}

    return plan


def convert_hf_llama_state_dict(flat: dict, config: LlamaConfig) -> dict:
    """HF-transformers llama naming → this model's stacked layout.
    torch ``nn.Linear`` stores ``[out, in]``; ours are ``[in, out]`` —
    hence the transposes. Enables loading Llama-2 checkpoints directly
    (reference users get this via transformers; SURVEY §7 pins keeping
    torch-format checkpoint compatibility)."""
    import numpy as np

    L = config.num_hidden_layers

    def get(name):
        for prefix in ("model.", ""):
            if prefix + name in flat:
                return np.asarray(flat[prefix + name])
        raise KeyError(name)

    mapping = {
        "wq": "self_attn.q_proj.weight",
        "wk": "self_attn.k_proj.weight",
        "wv": "self_attn.v_proj.weight",
        "wo": "self_attn.o_proj.weight",
        "w_gate": "mlp.gate_proj.weight",
        "w_up": "mlp.up_proj.weight",
        "w_down": "mlp.down_proj.weight",
        "attn_norm": "input_layernorm.weight",
        "mlp_norm": "post_attention_layernorm.weight",
    }
    out = {"embed_tokens": get("embed_tokens.weight"), "norm": get("norm.weight")}
    for ours, theirs in mapping.items():
        per_layer = [get(f"layers.{i}.{theirs}") for i in range(L)]
        stacked = np.stack(per_layer)
        if "norm" not in ours:
            stacked = stacked.swapaxes(-1, -2)  # torch [out,in] → ours [in,out]
        out[f"layers.{ours}"] = stacked
    if not config.tie_word_embeddings:
        out["lm_head"] = np.asarray(flat["lm_head.weight"]).T
    return out


class LlamaForCausalLM:
    """Factory mirroring the transformers entry point the reference's users
    bring to ``prepare()``."""

    @staticmethod
    def from_config(config: LlamaConfig, seed: int = 0, dtype=jnp.float32) -> Model:
        import dataclasses as _dc

        from ..big_modeling import is_empty_init

        # private copy: apply_fn closes over it, so per-model knob
        # changes (e.g. prepare() wiring activation_checkpointing
        # into remat) cannot leak into other models built from the
        # same config object
        config = _dc.replace(config)

        def make_params(key):
            return init_llama_params(key, config, dtype=dtype)

        if is_empty_init():
            params = jax.eval_shape(make_params, jax.random.PRNGKey(seed))
        else:
            params = make_params(jax.random.PRNGKey(seed))

        def apply_fn(p, input_ids=None, attention_mask=None, labels=None, positions=None, **kw):
            return llama_apply(config, p, input_ids, attention_mask, labels, positions, **kw)

        model = Model(
            apply_fn,
            params,
            partition_rules=LLAMA_PARTITION_RULES,
            name="LlamaForCausalLM",
        )
        model.config = config
        model.segments = llama_segments(config)
        model.stacked_params_prefix = "layers"
        model.supports_kv_cache = True
        model.supports_paged_kv = True  # serving engine's block-paged decode
        # speculative decoding's early-exit draft factory (EngineConfig(
        # spec_k=..., draft="early_exit:N")): first-N-layers apply over the
        # FULL params, sliced in-trace
        model.early_exit_apply = lambda n: llama_early_exit_apply(config, n)
        model.convert_state_dict = lambda flat: convert_hf_llama_state_dict(flat, config)
        # tied embeddings are a single leaf in this functional design (no
        # separate lm_head param exists), so no tie group is declared
        return model
