"""Pipeline-parallel inference: the ``prepare_pippy`` equivalent.

Reference: ``/root/reference/src/accelerate/inference.py:31-184`` — PiPPy
splits a torch module at layer boundaries, builds one ``PipelineStage`` per
process and runs a GPipe schedule (rank 0 feeds microbatches, the last rank
holds the output).

TPU-native design: models already expose a **segment plan**
(``model.segments`` — the same plan the streaming offload executor uses, see
``big_modeling.py``), so stage construction is a *partition of the segment
list*: contiguous groups balanced by parameter bytes, one group per device.
Each stage's params are committed to its device; one jitted fn per stage
runs that group's segments back-to-back. GPipe microbatching falls out of
XLA's async dispatch — microbatch m on stage s and microbatch m+1 on stage
s-1 execute concurrently because dispatch never blocks; device-to-device
carries ride ``jax.device_put``.

Single-host scope (one process drives all local chips) — the multi-host
scale-out path on TPU is GSPMD sharding, not pipeline stages (SURVEY §2.2:
"PP is the lowest-priority strategy on TPU").
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .big_modeling import _ppart
from .logging import get_logger
from .modules import Model, ModelOutput

logger = get_logger(__name__)


def find_pippy_batch_size(args, kwargs):
    """(Reference ``find_pippy_batch_size`` ``inference.py:58``.)"""
    for value in list(args or ()) + list((kwargs or {}).values()):
        for leaf in jax.tree.leaves(value):
            if hasattr(leaf, "ndim") and leaf.ndim >= 1:
                return leaf.shape[0]
    return None


def _param_bytes(leaf) -> int:
    size = int(np.prod(leaf.shape)) if leaf.shape else 1
    return size * jnp.dtype(leaf.dtype).itemsize


def generate_stage_map(steps, flat_params, num_stages: int) -> list[int]:
    """Balanced contiguous partition of segment steps into ``num_stages``
    groups by parameter bytes (reference ``generate_device_map``
    ``inference.py:31`` does the same via ``infer_auto_device_map``).
    Returns the first step index of each stage."""
    weights = []
    for name, paths, _fn in steps:
        w = 0
        for entry in paths:
            p = entry[0] if isinstance(entry, tuple) else entry
            leaf = flat_params.get(p)
            if leaf is not None:
                w += _param_bytes(leaf) // (
                    leaf.shape[0] if isinstance(entry, tuple) and leaf.shape else 1
                )
        weights.append(max(w, 1))
    total = sum(weights)
    target = total / num_stages
    bounds = [0]
    acc = 0
    for i, w in enumerate(weights):
        acc += w
        if acc >= target * len(bounds) and len(bounds) < num_stages and i + 1 < len(steps):
            bounds.append(i + 1)
    while len(bounds) < num_stages:  # degenerate: fewer steps than stages
        bounds.append(len(steps))
    return bounds


class PipelinedModel:
    """Callable over pipeline stages; mirrors the wrapped-forward contract
    of the reference (``model.forward`` swapped, ``inference.py:165-180``)."""

    def __init__(self, model: Model, num_chunks: int, devices, split_points):
        self._model = model
        self.num_chunks = num_chunks
        self.devices = list(devices)
        self.hf_split_points = split_points  # reference-compatible attr
        self._stage_params: list[dict] = []
        self._stage_fns: list = []
        self._stage_steps: list = []

    # -- stage construction (called by prepare_pippy) -----------------------

    def _build(self, plan_factory, flat_params, bounds):
        self._plan_factory = plan_factory
        self._bounds = bounds
        # params per stage, committed to the stage's device
        steps = self._example_plan["steps"]
        for s in range(len(self.devices)):
            lo = bounds[s]
            hi = bounds[s + 1] if s + 1 < len(bounds) else len(steps)
            needed = {}
            for name, paths, _fn in steps[lo:hi]:
                for entry in paths:
                    # a (path, i) entry addresses layer i of a stacked leaf —
                    # only that slice lives on this stage's device
                    p, idx = entry if isinstance(entry, tuple) else (entry, None)
                    key = p if idx is None else f"{p}.{idx}"
                    if key not in needed:
                        value = flat_params[p] if idx is None else flat_params[p][idx]
                        needed[key] = jax.device_put(value, self.devices[s])
            self._stage_params.append(needed)
            self._stage_steps.append((lo, hi))
            self._stage_fns.append(None)

    def _stage_fn(self, s, steps):
        if self._stage_fns[s] is None:
            lo, hi = self._stage_steps[s]
            fns = [fn for _, _, fn in steps[lo:hi]]
            paths_per = [paths for _, paths, _ in steps[lo:hi]]

            def run_stage(stage_params, carry):
                for fn, paths in zip(fns, paths_per):
                    seg = {}
                    for entry in paths:
                        p, idx = entry if isinstance(entry, tuple) else (entry, None)
                        seg[p] = stage_params[p if idx is None else f"{p}.{idx}"]
                    carry = fn(seg, carry)
                return carry

            self._stage_fns[s] = jax.jit(run_stage)
        return self._stage_fns[s]

    # -- forward -------------------------------------------------------------

    def __call__(self, *args, **kwargs):
        batch = find_pippy_batch_size(args, kwargs)
        if batch is None:
            raise ValueError("Could not find batch size from args or kwargs")
        chunks = min(self.num_chunks, batch)
        # equal-sized microbatches with a RAGGED tail chunk (no wraparound
        # padding): every chunk holds only real rows, so each chunk's own
        # reductions (a loss mean in finalize) cover real rows exactly —
        # the reference's pad-and-discard semantics
        # (`/root/reference/src/accelerate/inference.py:99-122`) without
        # padded rows ever existing. At most two program shapes compile
        # (mb and the tail remainder).
        mb = int(math.ceil(batch / chunks))

        outputs = []
        reals = []
        for m in range(chunks):
            lo, hi = m * mb, min(batch, (m + 1) * mb)
            if lo >= hi:
                break
            mb_args = jax.tree.map(lambda x: _slice0(x, slice(lo, hi), batch), args)
            mb_kwargs = jax.tree.map(lambda x: _slice0(x, slice(lo, hi), batch), kwargs)
            plan = self._plan_factory(*mb_args, **mb_kwargs)
            steps = plan["steps"]
            carry = plan["init"]()
            for s in range(len(self.devices)):
                carry = jax.device_put(carry, self.devices[s])
                carry = self._stage_fn(s, steps)(self._stage_params[s], carry)
            outputs.append(plan["finalize"](carry))
            reals.append(hi - lo)
        # scalars (a loss) average over chunks weighted by rows; each
        # chunk's scalar covers exactly its rows, so the weighted mean
        # equals the full-batch mean.
        weights = jnp.asarray(reals, jnp.float32)
        weights = weights / jnp.sum(weights)

        def _merge(*xs):
            if jnp.ndim(xs[0]):
                return jnp.concatenate(xs, axis=0)
            return jnp.sum(jnp.stack(xs) * weights)

        out = jax.tree.map(_merge, *outputs)  # ModelOutput is a registered pytree
        return out

    forward = __call__

    def unwrap(self):
        return self._model


def _slice0(x, sl, padded_batch):
    if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[0] == padded_batch:
        return x[sl]
    return x


def prepare_pippy(
    model: Model,
    split_points: str | list = "auto",
    no_split_module_classes=None,
    example_args: tuple = (),
    example_kwargs: dict | None = None,
    num_chunks: int | None = None,
    gather_output: bool = False,
    devices=None,
):
    """Wrap ``model`` for pipeline-parallel inference (reference
    ``prepare_pippy`` ``inference.py:124``; same signature, plus ``devices``
    to pin the stage list).

    ``split_points='auto'`` balances the model's segment plan across the
    devices by parameter bytes; pass a list of segment names to split
    explicitly. ``gather_output`` is accepted for parity — on a single host
    every returned ``jax.Array`` is already addressable from the caller.
    """
    segments = getattr(model, "segments", None)
    if segments is None:
        raise ValueError(
            "prepare_pippy needs a model with a segment plan (model.segments); "
            "zoo models provide one"
        )
    devices = list(devices) if devices is not None else jax.local_devices()
    example_kwargs = example_kwargs or {}
    if num_chunks is None:
        num_chunks = len(devices)

    plan = segments(*example_args, **example_kwargs) if callable(segments) else segments
    steps = plan["steps"]

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(model.params)[0]:
        key = ".".join(_ppart(p) for p in path)
        flat[key] = leaf

    if split_points == "auto":
        bounds = generate_stage_map(steps, flat, len(devices))
    else:
        names = [n if isinstance(n, str) else n[0] for n, _, _ in steps]
        bounds = [0] + [names.index(sp) for sp in split_points]
    # dedup + drop empty trailing stages BEFORE counting against devices
    bounds = sorted({b for b in bounds if b < len(steps)})
    if len(bounds) > len(devices):
        raise ValueError(f"{len(bounds)} stages but only {len(devices)} devices")
    split_names = []
    for b in bounds[1:]:
        n = steps[b][0]
        split_names.append(n if isinstance(n, str) else n[0])

    wrapped = PipelinedModel(model, num_chunks, devices[: len(bounds)], split_names)
    wrapped._example_plan = plan
    wrapped._build(segments if callable(segments) else (lambda *a, **k: segments), flat, bounds)
    logger.info(
        "pipeline stages at %s over %d devices", split_names, len(wrapped.devices)
    )
    return wrapped
