"""``accelerate-tpu config`` — questionnaire → yaml, plus programmatic config.

Reference analog: ``commands/config/`` (cluster.py questionnaire,
config_args.py dataclasses, default.py write_basic_config). The TPU build
asks only questions that exist on TPU (mesh axes, precision, hosts) and
keeps the same file contract: a yaml at
``~/.cache/accelerate_tpu/default_config.yaml`` that ``launch`` reads and
turns into ``ACCELERATE_*`` env vars.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from pathlib import Path

cache_dir = os.path.join(
    os.path.expanduser(os.environ.get("ACCELERATE_TPU_CACHE", "~/.cache/accelerate_tpu"))
)
default_yaml_config_file = os.path.join(cache_dir, "default_config.yaml")
default_json_config_file = os.path.join(cache_dir, "default_config.json")


def _yaml():
    try:
        import yaml

        return yaml
    except ImportError:  # pragma: no cover
        return None


@dataclass
class ClusterConfig:
    """The launch-relevant config (reference ``config_args.py:43-290``)."""

    compute_environment: str = "JAX_TPU"
    distributed_type: str = "TPU"  # NO | TPU | MULTI_HOST_TPU | CPU_MESH
    num_machines: int = 1
    machine_rank: int = 0
    coordinator_address: str | None = None  # host:port for jax.distributed
    mixed_precision: str = "bf16"
    gradient_accumulation_steps: int = 1
    # mesh axes (-1 = absorb remaining devices)
    mesh_dp: int = -1
    mesh_pp: int = 1
    mesh_fsdp: int = 1
    mesh_ep: int = 1
    mesh_cp: int = 1
    mesh_tp: int = 1
    use_fsdp: bool = False
    fsdp_config: dict = field(default_factory=dict)
    use_deepspeed: bool = False
    deepspeed_config: dict = field(default_factory=dict)
    context_parallel_mode: str | None = None  # ring | ulysses | allgather
    debug: bool = False
    num_cpu_devices: int = 0  # >0 → virtual CPU mesh (testing)
    max_restarts: int = 0  # launch fault tolerance: re-exec + auto-resume
    downcast_bf16: bool = False
    tpu_name: str | None = None
    tpu_zone: str | None = None
    main_training_function: str = "main"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}

    def save(self, path: str | None = None) -> str:
        path = path or default_yaml_config_file
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        yaml = _yaml()
        with open(path, "w") as f:
            if path.endswith(".json") or yaml is None:
                json.dump(self.to_dict(), f, indent=2)
            else:
                yaml.safe_dump(self.to_dict(), f)
        return path

    @classmethod
    def load(cls, path: str | None = None) -> "ClusterConfig":
        path = path or (
            default_yaml_config_file
            if os.path.exists(default_yaml_config_file)
            else default_json_config_file
        )
        with open(path) as f:
            if path.endswith(".json"):
                data = json.load(f)
            else:
                yaml = _yaml()
                data = yaml.safe_load(f) if yaml else json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in (data or {}).items() if k in known})

    def to_environment(self) -> dict[str, str]:
        """The env-var contract ``Accelerator``/``PartialState`` read."""
        env = {
            "ACCELERATE_MIXED_PRECISION": str(self.mixed_precision),
            "ACCELERATE_GRADIENT_ACCUMULATION_STEPS": str(self.gradient_accumulation_steps),
            "ACCELERATE_MESH_DP": str(self.mesh_dp),
            "ACCELERATE_MESH_PP": str(self.mesh_pp),
            "ACCELERATE_MESH_FSDP": str(self.mesh_fsdp),
            "ACCELERATE_MESH_EP": str(self.mesh_ep),
            "ACCELERATE_MESH_CP": str(self.mesh_cp),
            "ACCELERATE_MESH_TP": str(self.mesh_tp),
        }
        if self.use_fsdp:
            env["ACCELERATE_USE_FSDP"] = "true"
            for k, v in (self.fsdp_config or {}).items():
                env[f"FSDP_{k.upper()}"] = str(v)
        if self.use_deepspeed:
            env["ACCELERATE_USE_DEEPSPEED"] = "true"
            ds = self.deepspeed_config or {}
            if "zero_stage" in ds:
                env["ACCELERATE_DEEPSPEED_ZERO_STAGE"] = str(ds["zero_stage"])
            if ds.get("deepspeed_config_file"):
                env["ACCELERATE_DEEPSPEED_CONFIG_FILE"] = str(ds["deepspeed_config_file"])
        if self.context_parallel_mode:
            env["ACCELERATE_CP_MODE"] = self.context_parallel_mode
        if self.debug:
            env["ACCELERATE_DEBUG_MODE"] = "true"
        if self.num_machines > 1 and self.coordinator_address:
            env["ACCELERATE_COORDINATOR_ADDR"] = self.coordinator_address
            env["ACCELERATE_NUM_PROCESSES"] = str(self.num_machines)
            env["ACCELERATE_PROCESS_ID"] = str(self.machine_rank)
        if self.num_cpu_devices > 0:
            env["JAX_PLATFORMS"] = "cpu"
            flags = os.environ.get("XLA_FLAGS", "")
            flags = (
                flags + f" --xla_force_host_platform_device_count={self.num_cpu_devices}"
            ).strip()
            if "collective_call_terminate_timeout" not in flags:
                # few-core hosts time-slice device threads; the default 40s
                # collective rendezvous window would abort heavy programs.
                # (Guarded: a user-chosen value must not be clobbered —
                # XLA's flag parsing is last-wins.)
                flags += " --xla_cpu_collective_call_terminate_timeout_seconds=600"
            env["XLA_FLAGS"] = flags
            # a CPU-mesh child must not open a TPU-plugin session (single
            # physical chip ⇒ concurrent sessions deadlock); clearing the
            # pool var makes any site-level TPU registration a no-op
            env["PALLAS_AXON_POOL_IPS"] = ""
        return env


def _ask(prompt: str, default, cast=str):
    raw = input(f"{prompt} [{default}]: ").strip()
    if not raw:
        return default
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "y")
    return cast(raw)


def get_cluster_input() -> ClusterConfig:
    """Interactive questionnaire (reference ``cluster.py:54``), linearised —
    plain prompts instead of the cursor-menu UI, with the same
    sub-questionnaires (multi-host, FSDP, DeepSpeed-style sharding, context
    parallelism, TPU pod)."""
    cfg = ClusterConfig()
    env = _ask(
        "Compute environment? (jax_tpu / cpu_mesh for local testing)", "jax_tpu"
    )
    if env == "cpu_mesh":
        cfg.compute_environment = "CPU_MESH"
        cfg.distributed_type = "CPU_MESH"
        cfg.num_cpu_devices = _ask("How many virtual CPU devices?", 8, int)

    # -- multi-host sub-questionnaire (reference cluster.py:70-115) ---------
    cfg.num_machines = _ask("How many hosts (machines)?", 1, int)
    if cfg.num_machines > 1:
        cfg.distributed_type = "MULTI_HOST_TPU"
        cfg.machine_rank = _ask("Rank of this machine?", 0, int)
        cfg.coordinator_address = _ask("Coordinator address (host:port)?", "127.0.0.1:8476")
        if _ask("Is this a GCP TPU pod managed via gcloud?", False, bool):
            cfg.tpu_name = _ask("TPU name?", None)
            cfg.tpu_zone = _ask("TPU zone?", None)

    # -- sharding sub-questionnaire (reference FSDP/DeepSpeed menus) --------
    cfg.mesh_fsdp = _ask("FSDP (param-shard) mesh extent?", 1, int)
    cfg.use_fsdp = cfg.mesh_fsdp > 1
    if cfg.use_fsdp:
        cfg.fsdp_config = {
            "sharding_strategy": _ask(
                "FSDP sharding strategy? (FULL_SHARD/SHARD_GRAD_OP/NO_SHARD)", "FULL_SHARD"
            ),
            "min_num_params": _ask("Minimum parameter count to shard a tensor?", 0, int),
            "activation_checkpointing": _ask("Use activation checkpointing?", False, bool),
            # key name matches the env var the plugin reads (FSDP_OFFLOAD_PARAMS)
            "offload_params": _ask("Offload optimizer state to host memory?", False, bool),
        }
    elif _ask("Use a DeepSpeed-style ZeRO config instead?", False, bool):
        cfg.use_deepspeed = True
        ds_file = _ask("Path to a DeepSpeed JSON config (empty = questionnaire)?", "")
        if ds_file:
            cfg.deepspeed_config = {"deepspeed_config_file": ds_file}
        else:
            stage = _ask("ZeRO stage? (0/1/2/3)", 2, int)
            cfg.deepspeed_config = {"zero_stage": stage}
            if stage >= 2 and _ask("Offload optimizer state to host?", False, bool):
                cfg.deepspeed_config["offload_optimizer_device"] = "cpu"
            if stage == 3 and _ask("Offload parameters to host?", False, bool):
                cfg.deepspeed_config["offload_param_device"] = "cpu"
        if cfg.deepspeed_config.get("zero_stage", 0) >= 1:
            cfg.mesh_fsdp = _ask("ZeRO shard extent (mesh fsdp axis)?", 2, int)
            cfg.use_fsdp = cfg.mesh_fsdp > 1

    cfg.mesh_tp = _ask("Tensor-parallel mesh extent?", 1, int)
    cfg.mesh_cp = _ask("Context-parallel (sequence) mesh extent?", 1, int)
    cfg.mesh_ep = _ask("Expert-parallel mesh extent?", 1, int)
    cfg.mesh_pp = _ask("Pipeline-parallel (GPipe stage) mesh extent?", 1, int)
    if cfg.mesh_cp > 1:
        cfg.context_parallel_mode = _ask(
            "Context parallel mode? (ring/ulysses/allgather)", "ring"
        )

    cfg.mixed_precision = _ask("Mixed precision? (no/bf16/fp16/fp8)", "bf16")
    cfg.gradient_accumulation_steps = _ask("Gradient accumulation steps?", 1, int)
    cfg.debug = _ask("Check distributed operations for shape agreement (debug mode)?", False, bool)
    cfg.main_training_function = _ask(
        "Main training function (for notebook_launcher)?", "main"
    )
    return cfg


def write_basic_config(mixed_precision: str = "bf16", save_location: str | None = None):
    """Non-interactive default config (reference ``default.py:142``)."""
    cfg = ClusterConfig(mixed_precision=mixed_precision)
    return cfg.save(save_location)


def config_command(args):
    if getattr(args, "default", False):
        path = write_basic_config(mixed_precision=args.mixed_precision)
    else:
        cfg = get_cluster_input()
        path = cfg.save(args.config_file)
    print(f"configuration saved at {path}")
    return 0


def add_parser(subparsers):
    p = subparsers.add_parser("config", help="Create the launch configuration")
    p.add_argument("--config_file", default=None)
    p.add_argument("--default", action="store_true", help="write defaults, no questions")
    p.add_argument("--mixed_precision", default="bf16")
    p.set_defaults(func=config_command)
    return p
