"""``accelerate-tpu config`` — questionnaire → yaml, plus programmatic config.

Reference analog: ``commands/config/`` (cluster.py questionnaire,
config_args.py dataclasses, default.py write_basic_config). The TPU build
asks only questions that exist on TPU (mesh axes, precision, hosts) and
keeps the same file contract: a yaml at
``~/.cache/accelerate_tpu/default_config.yaml`` that ``launch`` reads and
turns into ``ACCELERATE_*`` env vars.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from pathlib import Path

cache_dir = os.path.join(
    os.path.expanduser(os.environ.get("ACCELERATE_TPU_CACHE", "~/.cache/accelerate_tpu"))
)
default_yaml_config_file = os.path.join(cache_dir, "default_config.yaml")
default_json_config_file = os.path.join(cache_dir, "default_config.json")


def _yaml():
    try:
        import yaml

        return yaml
    except ImportError:  # pragma: no cover
        return None


@dataclass
class ClusterConfig:
    """The launch-relevant config (reference ``config_args.py:43-290``)."""

    compute_environment: str = "JAX_TPU"
    distributed_type: str = "TPU"  # NO | TPU | MULTI_HOST_TPU | CPU_MESH
    num_machines: int = 1
    machine_rank: int = 0
    coordinator_address: str | None = None  # host:port for jax.distributed
    mixed_precision: str = "bf16"
    gradient_accumulation_steps: int = 1
    # mesh axes (-1 = absorb remaining devices)
    mesh_dp: int = -1
    mesh_fsdp: int = 1
    mesh_ep: int = 1
    mesh_cp: int = 1
    mesh_tp: int = 1
    use_fsdp: bool = False
    fsdp_config: dict = field(default_factory=dict)
    context_parallel_mode: str | None = None  # ring | ulysses | allgather
    debug: bool = False
    num_cpu_devices: int = 0  # >0 → virtual CPU mesh (testing)
    downcast_bf16: bool = False
    tpu_name: str | None = None
    tpu_zone: str | None = None
    main_training_function: str = "main"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}

    def save(self, path: str | None = None) -> str:
        path = path or default_yaml_config_file
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        yaml = _yaml()
        with open(path, "w") as f:
            if path.endswith(".json") or yaml is None:
                json.dump(self.to_dict(), f, indent=2)
            else:
                yaml.safe_dump(self.to_dict(), f)
        return path

    @classmethod
    def load(cls, path: str | None = None) -> "ClusterConfig":
        path = path or (
            default_yaml_config_file
            if os.path.exists(default_yaml_config_file)
            else default_json_config_file
        )
        with open(path) as f:
            if path.endswith(".json"):
                data = json.load(f)
            else:
                yaml = _yaml()
                data = yaml.safe_load(f) if yaml else json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in (data or {}).items() if k in known})

    def to_environment(self) -> dict[str, str]:
        """The env-var contract ``Accelerator``/``PartialState`` read."""
        env = {
            "ACCELERATE_MIXED_PRECISION": str(self.mixed_precision),
            "ACCELERATE_GRADIENT_ACCUMULATION_STEPS": str(self.gradient_accumulation_steps),
            "ACCELERATE_MESH_DP": str(self.mesh_dp),
            "ACCELERATE_MESH_FSDP": str(self.mesh_fsdp),
            "ACCELERATE_MESH_EP": str(self.mesh_ep),
            "ACCELERATE_MESH_CP": str(self.mesh_cp),
            "ACCELERATE_MESH_TP": str(self.mesh_tp),
        }
        if self.use_fsdp:
            env["ACCELERATE_USE_FSDP"] = "true"
            for k, v in (self.fsdp_config or {}).items():
                env[f"FSDP_{k.upper()}"] = str(v)
        if self.context_parallel_mode:
            env["ACCELERATE_CP_MODE"] = self.context_parallel_mode
        if self.debug:
            env["ACCELERATE_DEBUG_MODE"] = "true"
        if self.num_machines > 1 and self.coordinator_address:
            env["ACCELERATE_COORDINATOR_ADDR"] = self.coordinator_address
            env["ACCELERATE_NUM_PROCESSES"] = str(self.num_machines)
            env["ACCELERATE_PROCESS_ID"] = str(self.machine_rank)
        if self.num_cpu_devices > 0:
            env["JAX_PLATFORMS"] = "cpu"
            flags = os.environ.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={self.num_cpu_devices}"
            ).strip()
            # a CPU-mesh child must not open a TPU-plugin session (single
            # physical chip ⇒ concurrent sessions deadlock); clearing the
            # pool var makes any site-level TPU registration a no-op
            env["PALLAS_AXON_POOL_IPS"] = ""
        return env


def _ask(prompt: str, default, cast=str):
    raw = input(f"{prompt} [{default}]: ").strip()
    if not raw:
        return default
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "y")
    return cast(raw)


def get_cluster_input() -> ClusterConfig:
    """Interactive questionnaire (reference ``cluster.py:54``), linearised —
    plain prompts instead of the cursor-menu UI."""
    cfg = ClusterConfig()
    env = _ask(
        "Compute environment? (jax_tpu / cpu_mesh for local testing)", "jax_tpu"
    )
    if env == "cpu_mesh":
        cfg.compute_environment = "CPU_MESH"
        cfg.distributed_type = "CPU_MESH"
        cfg.num_cpu_devices = _ask("How many virtual CPU devices?", 8, int)
    cfg.num_machines = _ask("How many hosts (machines)?", 1, int)
    if cfg.num_machines > 1:
        cfg.distributed_type = "MULTI_HOST_TPU"
        cfg.machine_rank = _ask("Rank of this machine?", 0, int)
        cfg.coordinator_address = _ask("Coordinator address (host:port)?", "127.0.0.1:8476")
    cfg.mesh_fsdp = _ask("FSDP (param-shard) mesh extent?", 1, int)
    cfg.mesh_tp = _ask("Tensor-parallel mesh extent?", 1, int)
    cfg.mesh_cp = _ask("Context-parallel (sequence) mesh extent?", 1, int)
    cfg.mesh_ep = _ask("Expert-parallel mesh extent?", 1, int)
    if cfg.mesh_cp > 1:
        cfg.context_parallel_mode = _ask("Context parallel mode? (ring/ulysses)", "ring")
    cfg.use_fsdp = cfg.mesh_fsdp > 1
    cfg.mixed_precision = _ask("Mixed precision? (no/bf16/fp16)", "bf16")
    cfg.gradient_accumulation_steps = _ask("Gradient accumulation steps?", 1, int)
    cfg.debug = _ask("Check distributed operations for shape agreement (debug mode)?", False, bool)
    return cfg


def write_basic_config(mixed_precision: str = "bf16", save_location: str | None = None):
    """Non-interactive default config (reference ``default.py:142``)."""
    cfg = ClusterConfig(mixed_precision=mixed_precision)
    return cfg.save(save_location)


def config_command(args):
    if getattr(args, "default", False):
        path = write_basic_config(mixed_precision=args.mixed_precision)
    else:
        cfg = get_cluster_input()
        path = cfg.save(args.config_file)
    print(f"configuration saved at {path}")
    return 0


def add_parser(subparsers):
    p = subparsers.add_parser("config", help="Create the launch configuration")
    p.add_argument("--config_file", default=None)
    p.add_argument("--default", action="store_true", help="write defaults, no questions")
    p.add_argument("--mixed_precision", default="bf16")
    p.set_defaults(func=config_command)
    return p
