"""``accelerate-tpu launch`` — env construction + process spawning.

Reference analog: ``commands/launch.py`` (1178 LoC of torchrun/deepspeed/
xmp routing). The jax_tpu environment needs far less process machinery:

* **single host** — ONE process drives every local chip (JAX owns the
  device runtime), so launch = build env + ``Popen(script)``. No per-device
  fork like ``xmp.spawn``.
* **multi host** — the same command runs on every host with
  ``ACCELERATE_COORDINATOR_ADDR/NUM_PROCESSES/PROCESS_ID`` set;
  ``jax.distributed.initialize`` does the rendezvous (reference:
  MASTER_ADDR/RANK consumed by ``init_process_group``, ``state.py:214-249``).
* **cpu mesh** — ``--num_cpu_devices N`` forces an N-device virtual CPU
  platform: the "multi-node without a cluster" debug backend.
* **pod fanout** — ``--pod`` delegates to tpu.py's gcloud ssh fanout
  (reference ``tpu_pod_launcher``).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from .config import ClusterConfig, default_json_config_file, default_yaml_config_file


def launch_command_parser(subparsers=None):
    if subparsers is not None:
        p = subparsers.add_parser("launch", help="Launch a training script")
    else:
        p = argparse.ArgumentParser("accelerate-tpu launch")
    p.add_argument("--config_file", default=None)
    # hardware / env selection
    p.add_argument("--cpu", action="store_true", help="force CPU platform")
    p.add_argument(
        "--num_cpu_devices", type=int, default=0,
        help=">0: virtual CPU mesh with this many devices (debug/testing)",
    )
    # mesh
    p.add_argument("--mesh_dp", type=int, default=None)
    p.add_argument("--mesh_pp", type=int, default=None)
    p.add_argument("--mesh_fsdp", type=int, default=None)
    p.add_argument("--mesh_ep", type=int, default=None)
    p.add_argument("--mesh_cp", type=int, default=None)
    p.add_argument("--mesh_tp", type=int, default=None)
    p.add_argument("--use_fsdp", action="store_true", default=None)
    p.add_argument("--cp_mode", default=None, choices=("ring", "ulysses", "allgather"))
    # precision / accumulation
    p.add_argument("--mixed_precision", default=None, choices=("no", "bf16", "fp16"))
    p.add_argument("--gradient_accumulation_steps", type=int, default=None)
    # multi-host
    p.add_argument("--num_machines", type=int, default=None)
    p.add_argument("--machine_rank", type=int, default=None)
    p.add_argument("--coordinator_address", default=None, help="host:port of process 0")
    # pod fanout
    p.add_argument("--pod", action="store_true", help="fan out over TPU pod workers via gcloud ssh")
    p.add_argument("--tpu_name", default=None)
    p.add_argument("--tpu_zone", default=None)
    # fault tolerance (SURVEY §5: TPU-native analog of torchrun's elastic
    # agent, reference launchers.py:231-245 — re-exec on crash, resume from
    # the latest checkpoint via ACCELERATE_AUTO_RESUME)
    p.add_argument(
        "--max_restarts", type=int, default=None,
        help="re-exec the script up to N times on non-zero exit; restarted "
        "runs get ACCELERATE_AUTO_RESUME=true so an Accelerator with a "
        "project_dir reloads the latest checkpoint after prepare()",
    )
    p.add_argument(
        "--auto-resume", "--auto_resume", dest="auto_resume",
        action="store_true", default=None,
        help="set ACCELERATE_AUTO_RESUME=true from the FIRST run (not just "
        "restarts): the Accelerator resumes from the newest checkpoint "
        "whose manifest validates, skipping corrupt/partial ones — the "
        "resume half of the resilience subsystem's preemption flow",
    )
    # misc
    p.add_argument("--debug", action="store_true", default=None, help="collective shape verification")
    p.add_argument("-m", "--module", action="store_true", help="script is a python module")
    p.add_argument("training_script", help="script to launch")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    p.set_defaults(func=launch_command)
    return p


def _load_config(args) -> ClusterConfig:
    path = args.config_file
    if path is None:
        for candidate in (default_yaml_config_file, default_json_config_file):
            if os.path.exists(candidate):
                path = candidate
                break
    if path is None:
        return ClusterConfig()
    return ClusterConfig.load(path)


def _merge_args_into_config(args, cfg: ClusterConfig) -> ClusterConfig:
    """CLI flags override the config file (reference
    ``_validate_launch_command``, ``launch.py:966``)."""
    for cli, attr in [
        ("mesh_dp", "mesh_dp"), ("mesh_pp", "mesh_pp"), ("mesh_fsdp", "mesh_fsdp"), ("mesh_ep", "mesh_ep"),
        ("mesh_cp", "mesh_cp"), ("mesh_tp", "mesh_tp"),
        ("mixed_precision", "mixed_precision"),
        ("gradient_accumulation_steps", "gradient_accumulation_steps"),
        ("num_machines", "num_machines"), ("machine_rank", "machine_rank"),
        ("coordinator_address", "coordinator_address"),
        ("use_fsdp", "use_fsdp"), ("debug", "debug"),
        ("tpu_name", "tpu_name"), ("tpu_zone", "tpu_zone"),
        ("max_restarts", "max_restarts"),
    ]:
        v = getattr(args, cli, None)
        if v is not None:
            setattr(cfg, attr, v)
    if args.cp_mode is not None:
        cfg.context_parallel_mode = args.cp_mode
    if args.num_cpu_devices:
        cfg.num_cpu_devices = args.num_cpu_devices
        cfg.distributed_type = "CPU_MESH"
    if args.cpu and not cfg.num_cpu_devices:
        cfg.num_cpu_devices = 1
    return cfg


def prepare_environment(args, cfg: ClusterConfig) -> dict[str, str]:
    env = os.environ.copy()
    env.update(cfg.to_environment())
    # make the invoking project (and a source checkout of this package)
    # importable from the launched script regardless of its location
    extra = [os.getcwd(), os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))]
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    for p in extra:
        if p not in parts:
            parts.append(p)
    env["PYTHONPATH"] = os.pathsep.join(parts)
    return env


def simple_launcher(cmd: list[str], env: dict[str, str], max_restarts: int = 0) -> int:
    """Single-process spawn (reference ``simple_launcher`` ``launch.py:756``),
    with checkpoint-autoresume fault tolerance in place of torchrun's elastic
    agent: on a non-zero exit the script is re-exec'd up to ``max_restarts``
    times with ``ACCELERATE_AUTO_RESUME=true`` (+ a restart counter), which
    makes ``Accelerator.prepare`` reload the latest ``checkpoint_*`` under
    the project_dir — a crashed multi-day run resumes at its last save
    instead of dying (reference launchers.py:231-245; SURVEY §5)."""
    restarts = 0
    while True:
        proc = subprocess.Popen(cmd, env=env)
        proc.wait()
        rc = proc.returncode
        if rc == 0 or restarts >= max_restarts:
            return rc
        restarts += 1
        env = dict(env)
        env["ACCELERATE_AUTO_RESUME"] = "true"
        env["ACCELERATE_RESTART_COUNT"] = str(restarts)
        print(
            f"[accelerate-tpu launch] script exited with {rc}; "
            f"restart {restarts}/{max_restarts} (auto-resume from latest checkpoint)",
            file=sys.stderr,
            flush=True,
        )


def launch_command(args) -> int:
    cfg = _merge_args_into_config(args, _load_config(args))
    env = prepare_environment(args, cfg)
    if getattr(args, "auto_resume", None):
        env["ACCELERATE_AUTO_RESUME"] = "true"

    if args.pod:
        from .tpu import pod_fanout

        return pod_fanout(cfg, args.training_script, args.training_script_args, env)

    if args.module:
        cmd = [sys.executable, "-m", args.training_script, *args.training_script_args]
    else:
        cmd = [sys.executable, args.training_script, *args.training_script_args]
    rc = simple_launcher(cmd, env, max_restarts=getattr(cfg, "max_restarts", 0) or 0)
    if rc != 0:
        raise RuntimeError(
            f"launch failed (exit {rc}): {' '.join(cmd)}"
        )
    return rc


def add_parser(subparsers):
    return launch_command_parser(subparsers)


def main():  # standalone `accelerate-tpu-launch`
    parser = launch_command_parser()
    args = parser.parse_args()
    return launch_command(args)


if __name__ == "__main__":
    sys.exit(main())
