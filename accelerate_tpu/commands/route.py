"""``accelerate-tpu route`` — N engine replicas behind a health-checked
load balancer.

Spawns ``--replicas N`` serve processes (or ``--attach``\\ es to running
ones), waits for every ``/healthz`` to report ``ready``, then reads the
same JSONL request protocol as ``accelerate-tpu serve`` from stdin —
plus an optional ``"session_id"`` field for sticky placement — and writes
one JSON result line per request. Requests on a replica that dies
mid-stream are requeued to a surviving replica; the caller still gets
exactly one answer per request. ``--http PORT`` additionally mounts the
OpenAI-compatible door (``/v1/completions`` + ``/v1/chat/completions``,
:mod:`accelerate_tpu.serving.openai_api`) on the router itself, so an
unmodified OpenAI client drives the whole fleet.

SIGTERM drains: admission stops (late submissions are *answered* with an
error row, never dropped), in-flight requests finish, every spawned
replica is SIGTERM'd in turn (the serve front end's own drain path), and
the router exits 0. This is the resilience preemption contract
(``resilience/preemption.py``) applied to serving.
"""

from __future__ import annotations

import json
import os
import queue
import sys
import threading
import time


#: serve flags forwarded verbatim to every replica (the fleet must be
#: shape-identical for dispatch to treat replicas as interchangeable)
_ENGINE_FLAGS = (
    ("--preset", "preset"), ("--dtype", "dtype"), ("--num-slots", "num_slots"),
    ("--block-size", "block_size"), ("--max-seq-len", "max_seq_len"),
    ("--prefill-chunk", "prefill_chunk"), ("--decode-burst", "decode_burst"),
    ("--max-new-tokens", "max_new_tokens"), ("--eos-token-id", "eos_token_id"),
    ("--temperature", "temperature"), ("--seed", "seed"),
    ("--kv-dtype", "kv_dtype"), ("--chaos-spec", "chaos_spec"),
    ("--spec-k", "spec_k"), ("--draft", "draft"),
    ("--logprobs-topn", "logprobs_topn"),
)


def _serve_args(args) -> list[str]:
    tail: list[str] = []
    for flag, attr in _ENGINE_FLAGS:
        value = getattr(args, attr)
        if value is not None:
            tail += [flag, str(value)]
    if getattr(args, "mesh", False):
        tail.append("--mesh")
    if getattr(args, "sync_engine", False):
        tail.append("--sync-engine")
    return tail


def _route_http_server(router, port: int):
    """The router's OpenAI-compatible door: ``POST /v1/completions`` +
    ``/v1/chat/completions`` translated onto ``router.submit`` (so an
    unmodified OpenAI client speaks to the whole fleet), plus a
    ``GET /healthz`` fleet summary. Replicas answer whole completions, so
    SSE streams replay ``at_completion`` — same framing, one
    ``data: [DONE]``, exactly-once."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from ..serving.openai_api import OPENAI_PATHS, OpenAIFrontend

    frontend = OpenAIFrontend(
        lambda payload, cb: router.submit(payload, callback=cb),
        streaming="at_completion",
    )

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _send(self, code, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path.rstrip("/") == "/healthz":
                stats = router.stats()
                self._send(200, {
                    "state": "ready",
                    "replicas": stats.get("replicas"),
                    "queue_depth": stats.get("queue_depth"),
                })
            else:
                self._send(404, {"error": "unknown path"})

        def do_POST(self):
            path = self.path.rstrip("/")
            try:
                n = int(self.headers.get("Content-Length", 0) or 0)
            except ValueError:
                n = 0
            raw = self.rfile.read(n) if n else b""
            if path not in OPENAI_PATHS:
                self._send(404, {"error": {
                    "message": "unknown path", "type": "invalid_request_error",
                    "param": None, "code": None,
                }})
                return
            try:
                body = json.loads(raw)
            except json.JSONDecodeError as e:
                self._send(400, {"error": {
                    "message": f"bad JSON: {e}",
                    "type": "invalid_request_error",
                    "param": None, "code": None,
                }})
                return
            kind, *rest = frontend.handle(path, body)
            if kind == "sse":
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    for event in rest[0]:
                        data = event.encode()
                        self.wfile.write(
                            f"{len(data):X}\r\n".encode() + data + b"\r\n"
                        )
                        self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    self.close_connection = True
            else:
                self._send(rest[0], rest[1])

    class Server(ThreadingHTTPServer):
        request_queue_size = 128

    server = Server(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    print(
        f"route: OpenAI endpoint on http://127.0.0.1:{port}/v1 "
        "(POST /v1/completions, /v1/chat/completions)",
        file=sys.stderr,
    )
    return server


def route_command(args) -> int:
    from ..resilience.preemption import PreemptionHandler
    from ..serving.replica import ReplicaHandle, spawn_replica, wait_until_ready
    from ..serving.router import Router
    from ..serving.workload import (
        TraceSpecError,
        WorkloadRecorder,
        generate_schedule,
        parse_trace_spec,
        run_schedule,
        write_workload_manifest,
    )

    # seeded replayable workload: parsed before anything spawns — a
    # malformed spec is a bring-up refusal (exit 2), the --chaos-spec
    # contract, not a fleet brought up to replay nothing
    trace_spec = trace_schedule = None
    if args.trace:
        try:
            trace_spec = parse_trace_spec(args.trace)
            trace_schedule = generate_schedule(trace_spec)
        except TraceSpecError as e:
            print(json.dumps({"error": str(e)}))
            print(f"route: refusing to start: {e}", file=sys.stderr)
            return 2

    if args.logging_dir:
        os.makedirs(args.logging_dir, exist_ok=True)
        from ..diagnostics.tracing import Tracer, set_active_tracer

        # the router's half of every request flow (submit → dispatch →
        # finish) lands in <logging_dir>/traces/; each replica writes its
        # own half under replica_<i>/ — `trace merge` stitches them by
        # trace_id into one timeline, `trace tail` attributes the slowest
        set_active_tracer(Tracer(logging_dir=args.logging_dir, process_name="router"))

    def spawn_fn(replica_id: int):
        """One replica's spawn recipe — shared by bring-up and the
        supervisor's respawn/scale-up paths, so a respawned replica is
        byte-identical in configuration to the one it replaces."""
        serve_tail = _serve_args(args)
        env = None
        if args.logging_dir:
            # one telemetry trail per replica — two processes appending
            # the same telemetry.jsonl would interleave torn rows
            serve_tail += ["--logging-dir",
                           os.path.join(args.logging_dir, f"replica_{replica_id}")]
            # a replica-side LockWatch (ACCELERATE_SANITIZE=1) must dump its
            # RACE_REPORT where `monitor --once` globs — the fleet's logging
            # dir, not the replica process cwd (setdefault: an explicit
            # operator ACCELERATE_LOCKWATCH_DIR wins)
            env = dict(os.environ)
            env.setdefault("ACCELERATE_LOCKWATCH_DIR", args.logging_dir)
        return spawn_replica(replica_id, serve_tail, env=env, stderr=sys.stderr)

    replicas = []
    if args.attach:
        for i, url in enumerate(x for x in args.attach.split(",") if x):
            replicas.append(ReplicaHandle(i, url))
    else:
        try:
            for i in range(args.replicas):
                replicas.append(spawn_fn(i))
        except Exception:
            # a failed spawn mid-loop must not strand the earlier spawns:
            # kill + reap everything before the exception surfaces
            for r in replicas:
                r.kill()
            for r in replicas:
                r.wait(timeout=10.0)
            raise

    supervisor = None
    wants_supervision = (
        args.respawn
        or args.max_replicas is not None
        or args.min_replicas is not None
    )
    if wants_supervision:
        if args.attach:
            print(
                "route: --respawn/--min-replicas/--max-replicas need spawned "
                "replicas (they respawn via the serve spawn recipe) — "
                "ignoring for an --attach fleet", file=sys.stderr,
            )
        else:
            from ..serving.supervisor import ReplicaSupervisor, SupervisorConfig

            # SLO-driven scaling: when the fleet has a logging dir and any
            # ACCELERATE_SLO_* objective is armed, the supervisor's policy
            # reads the windowed verdict (throttled — evaluation is file
            # reads over the fleet's own trails)
            slo_fn = None
            if args.logging_dir:
                from ..metrics.slo import configured_objectives, evaluate_from_dir

                if configured_objectives():
                    slo_cache = {"ts": 0.0, "verdict": None}
                    slo_dir = args.logging_dir

                    def slo_fn():
                        now = time.monotonic()
                        if now - slo_cache["ts"] >= 2.0:
                            slo_cache["ts"] = now
                            slo_cache["verdict"] = evaluate_from_dir(slo_dir)
                        return slo_cache["verdict"]

                    print(
                        "route: SLO scaling policy armed "
                        f"({', '.join(configured_objectives())})",
                        file=sys.stderr,
                    )

            # explicit is-None tests: --min-replicas 0 (scale-to-zero floor)
            # must not be rewritten to --replicas
            min_replicas = (
                args.replicas if args.min_replicas is None else args.min_replicas
            )
            max_replicas = (
                args.replicas if args.max_replicas is None else args.max_replicas
            )
            supervisor = ReplicaSupervisor(
                spawn_fn,
                SupervisorConfig(
                    min_replicas=min_replicas,
                    max_replicas=max(max_replicas, min_replicas, 1),
                    respawn=bool(args.respawn),
                    ready_timeout=args.ready_timeout,
                    seed=args.seed,
                ),
                slo_fn=slo_fn,
            )
    print(
        f"route: waiting for {len(replicas)} replica(s) to report ready...",
        file=sys.stderr,
    )
    router = Router(
        replicas,
        logging_dir=args.logging_dir,
        health_interval=args.health_interval,
        request_timeout=args.request_timeout,
        supervisor=supervisor,
        max_queue_depth=args.max_queue_depth,
    )
    try:
        wait_until_ready(replicas, timeout=args.ready_timeout)
    except Exception as e:
        # no orphans on failed bring-up: close() kills AND reaps every
        # spawned replica (and stops the supervisor first, so a respawn
        # never races the teardown)
        print(f"route: bring-up failed: {e}", file=sys.stderr)
        router.close()
        return 1
    print(
        "route: fleet ready — "
        + "  ".join(f"replica {r.replica_id} @ {r.base_url} (pid {r.pid})"
                    for r in replicas),
        file=sys.stderr,
    )
    http_server = _route_http_server(router, args.http) if args.http else None

    # SIGTERM → drain (stop admission, answer in-flight, clean exit 0);
    # the handler only raises a flag — the loop below observes it between
    # submissions, exactly like the training loop observes it between steps
    handler = PreemptionHandler(handle_sigint=True)
    handler.install()

    out_lock = threading.Lock()

    def emit(result):
        with out_lock:
            print(json.dumps(result), flush=True)

    inbox: queue.Queue = queue.Queue()
    eof = threading.Event()

    # --trace-record: capture live arrivals into the replayable schedule
    # format (workload/recorded.jsonl) — replay later with
    # --trace replay:<path>
    recorder = None
    if getattr(args, "trace_record", False):
        if args.logging_dir:
            recorder = WorkloadRecorder(args.logging_dir)
            print(f"route: recording workload to {recorder.path}", file=sys.stderr)
        else:
            print(
                "route: --trace-record needs --logging-dir — ignoring",
                file=sys.stderr,
            )

    def read_stdin():
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                payload = json.loads(line)
            except json.JSONDecodeError as e:
                emit({"error": f"bad JSON: {e}"})
                continue
            if recorder is not None:
                recorder.observe(payload)
            inbox.put(payload)
        eof.set()

    if trace_schedule is not None:
        if args.logging_dir:
            write_workload_manifest(args.logging_dir, trace_spec, trace_schedule)
        print(
            f"route: replaying workload {trace_spec.as_text()} "
            f"({len(trace_schedule)} requests)", file=sys.stderr,
        )

        def feed_trace():
            run_schedule(
                trace_schedule,
                inbox.put,
                should_stop=lambda: handler.preemption_requested,
            )
            eof.set()

        threading.Thread(target=feed_trace, daemon=True).start()
    else:
        threading.Thread(target=read_stdin, daemon=True).start()

    drain_reason = "eof"
    try:
        while True:
            if handler.preemption_requested:
                drain_reason = handler.reason or "signal"
                # grace sweep: lines that were in the pipe before the signal
                # are in-flight work, not late arrivals — give the reader a
                # beat to surface them, then stop admission (anything later
                # still gets answered via submit()'s draining error row)
                grace_end = time.monotonic() + 1.0
                while time.monotonic() < grace_end:
                    try:
                        router.submit(inbox.get(timeout=0.1), callback=emit)
                    except queue.Empty:
                        continue
                router.stop_admission()
                while not inbox.empty():
                    router.submit(inbox.get_nowait(), callback=emit)
                break
            try:
                payload = inbox.get(timeout=0.1)
            except queue.Empty:
                if eof.is_set() and inbox.empty():
                    break
                continue
            router.submit(payload, callback=emit)
    finally:
        handler.uninstall()

    print(f"route: draining ({drain_reason})...", file=sys.stderr)
    clean = router.drain(timeout=args.drain_timeout)
    if http_server is not None:
        # after drain: in-flight OpenAI requests got their callbacks; a
        # late POST would have been answered with an admission-stopped row
        http_server.shutdown()
    # lines that arrived while drain() ran still get an answer (an
    # admission-stopped error row), never silence; a short quiet window
    # catches a producer mid-write before the process exits
    grace_end = time.monotonic() + 1.0
    while time.monotonic() < grace_end and not eof.is_set():
        try:
            router.submit(inbox.get(timeout=0.1), callback=emit)
        except queue.Empty:
            continue
    while not inbox.empty():
        router.submit(inbox.get_nowait(), callback=emit)
    if recorder is not None:
        recorder.close()
        print(
            f"route: recorded {recorder.recorded} request(s) to {recorder.path}",
            file=sys.stderr,
        )
    stats = router.stats()
    sup = stats.get("supervisor") or {}
    sup_text = (
        f", {sup['respawns']} respawn(s), {sup['scale_ups']} scale-up(s), "
        f"{sup['scale_downs']} scale-down(s)" if sup else ""
    )
    print(
        f"route: delivered {stats['delivered']} "
        f"({stats['tokens']} tokens, {stats['requeues']} requeues, "
        f"{stats['rejected']} rejected, {stats['shed']} shed, "
        f"{stats['deadline_expired']} deadline-expired, "
        f"{stats['dead']} dead replica(s){sup_text})",
        file=sys.stderr,
    )
    return 0 if clean else 1


def add_parser(subparsers):
    p = subparsers.add_parser(
        "route",
        help="Load-balance JSONL requests over N health-checked engine replicas",
    )
    p.add_argument("--replicas", type=int, default=2,
                   help="engine replica processes to spawn")
    p.add_argument("--attach", default=None, metavar="URL[,URL...]",
                   help="route to already-running serve endpoints instead of spawning")
    p.add_argument("--logging-dir", default=None,
                   help="fleet health JSONL (router/replicas.jsonl) + per-replica "
                   "telemetry land here; `accelerate-tpu monitor` shows the fleet")
    p.add_argument("--health-interval", type=float, default=0.5,
                   help="seconds between /healthz sweeps")
    p.add_argument("--ready-timeout", type=float, default=300.0,
                   help="seconds to wait for the fleet to report ready")
    p.add_argument("--drain-timeout", type=float, default=300.0,
                   help="seconds to wait for in-flight requests + replica exits")
    p.add_argument("--request-timeout", type=float, default=None,
                   help="per-dispatch HTTP timeout (default: wait forever); "
                   "expiry on a slow-but-alive replica requeues the request "
                   "without marking the replica dead")
    # self-healing fleet (serving/supervisor.py)
    p.add_argument("--respawn", action="store_true",
                   help="supervise the fleet: respawn dead replicas with "
                   "exponential crash-loop backoff, quarantine flapping ones "
                   "(half-open probation rejoin), and restore --min-replicas "
                   "(default off: a dead replica stays dead, the PR 7 "
                   "fixed-fleet behaviour)")
    p.add_argument("--min-replicas", type=int, default=None,
                   help="fleet floor the supervisor restores after deaths / "
                   "scale-down (default: --replicas; implies supervision — "
                   "pair with --respawn for death recovery)")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="autoscale ceiling: sustained router queue depth "
                   "spawns up to this many replicas; an idle fleet drains "
                   "back to --min-replicas (default: --replicas, i.e. no "
                   "scaling; implies supervision)")
    p.add_argument("--max-queue-depth", type=int, default=None,
                   help="bounded-queue admission: over this many queued "
                   "requests the router sheds batch-class before interactive "
                   "with explicit over-capacity error rows (default: "
                   "unbounded)")
    # engine shape passthrough (matches `serve`)
    p.add_argument("--preset", choices=("tiny", "flagship"), default="tiny")
    p.add_argument("--dtype", choices=("f32", "bf16"), default="f32")
    p.add_argument("--num-slots", type=int, default=8)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-seq-len", type=int, default=512)
    p.add_argument("--prefill-chunk", type=int, default=32)
    p.add_argument("--decode-burst", type=int, default=8)
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--eos-token-id", type=int, default=None)
    p.add_argument("--temperature", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--kv-dtype", choices=("auto", "bf16", "f32", "int8", "fp8"),
                   default=None,
                   help="forwarded to every replica's serve --kv-dtype "
                   "(replicas must store KV identically for dispatch to "
                   "treat them as interchangeable)")
    p.add_argument("--spec-k", type=int, default=None,
                   help="forwarded to every replica's serve --spec-k "
                   "(speculative decoding; the fleet must decode "
                   "identically for dispatch to treat replicas as "
                   "interchangeable)")
    p.add_argument("--draft", default=None,
                   help="forwarded to every replica's serve --draft "
                   "(e.g. early_exit:2)")
    p.add_argument("--logprobs-topn", type=int, default=None,
                   help="forwarded to every replica's serve --logprobs-topn "
                   "(the OpenAI 'logprobs' field needs it; the fleet must "
                   "harvest identically)")
    p.add_argument("--http", type=int, default=None, metavar="PORT",
                   help="mount the OpenAI-compatible endpoint "
                   "(/v1/completions, /v1/chat/completions; SSE + "
                   "non-streaming) on this port, translated onto the "
                   "routed fleet — an unmodified OpenAI client talks to "
                   "the whole fleet")
    p.add_argument("--mesh", action="store_true",
                   help="each replica shards its engine over the attached mesh "
                   "(forwards serve's --mesh; MeshPlugin reads ACCELERATE_MESH_*)")
    p.add_argument(
        "--sync-engine", action="store_true",
        default=os.environ.get("ACCELERATE_SYNC_ENGINE", "") not in ("", "0"),
        help="every replica runs the synchronous step loop (forwards "
        "serve's --sync-engine; env ACCELERATE_SYNC_ENGINE=1)")
    p.add_argument("--chaos-spec", default=None,
                   help="forwarded to every replica's serve --chaos-spec "
                   "(entries scoped rN: fire only on replica N) — the "
                   "fault-injection harness benchmarks/chaos_smoke.py drives")
    # replayable workload suite (serving/workload.py)
    p.add_argument("--trace", default=None, metavar="SPEC",
                   help="drive the fleet from a seeded replayable workload "
                   "instead of stdin: 'name:seed:duration:rps' with name in "
                   "bursty-diurnal|longctx-flood|agentic|overbudget-storm, "
                   "or 'replay:<path>' for a recorded schedule (same seed = "
                   "byte-identical schedule, manifest in WORKLOAD.json; "
                   "malformed spec = exit 2)")
    p.add_argument("--trace-record", action="store_true",
                   help="capture live stdin arrivals into the replayable "
                   "schedule format under <logging-dir>/workload/ — replay "
                   "with --trace replay:<path>")
    p.set_defaults(func=route_command)
    return p
