"""``accelerate-tpu monitor`` + ``accelerate-tpu trace`` — the operator
surface of the diagnostics subsystem.

* ``monitor <logging_dir>`` tails the telemetry JSONL and the per-host
  heartbeat files into a live terminal summary (step rate, MFU, per-host
  lag, recompiles, hang reports). Pure file reads — works on a wedged or
  dead run, and from any machine that can see the logging dir.
* ``trace merge <logging_dir>`` fuses ``traces/host_*.trace.json`` into
  one Perfetto/``chrome://tracing``-loadable timeline with host-clock-
  offset correction.

Neither command imports jax — they must run on a laptop against a synced
logging dir without a TPU (or any accelerator) in sight.
"""

from __future__ import annotations

import os
import sys
import time


def monitor_command(args) -> int:
    """Exit codes in ``--once`` mode (the scriptable health check):

    * ``0`` — healthy (or nothing to report yet)
    * ``1`` — usage error (``logging_dir`` is not a directory)
    * ``2`` — a host is wedged, a ``HANG_REPORT`` exists, a ``RACE_REPORT``
      exists (LockWatch witnessed a lock-order inversion — a deadlock
      waiting for the right interleaving), a serving-fleet replica is
      dead or its router rows went stale mid-run, or the per-host
      collective-sequence digests diverge (a pre-deadlock condition: the
      sanitizer writes one digest file per host, and disagreement means
      a cross-host collective will never match up). A supervised replica
      waiting out its respawn backoff still counts as dead — the
      condition clears itself once the respawned process writes a fresh
      ``ready`` row (newest row per replica wins)
    * ``3`` — an ``ACCELERATE_SLO_*`` alert rule is firing (``ALERTS.json``
      written next to the run's artifacts; wedged/hang wins when both hold)

    Precedence is fixed: ``1`` (usage) > ``2`` (wedged/dead/race/divergence)
    > ``3`` (SLO) > ``0`` — a wedged fleet must not be masked by a mere SLO
    breach, and scripts can rely on the ordering.
    """
    from ..diagnostics.monitor import collect_status, render_status
    from ..metrics.alerts import EXIT_SLO_VIOLATION
    from ..metrics.slo import evaluate_from_dir, write_slo_alerts

    logging_dir = args.logging_dir
    if not os.path.isdir(logging_dir):
        print(f"monitor: {logging_dir} is not a directory", file=sys.stderr)
        return 1
    try:
        while True:
            status = collect_status(logging_dir)
            text = render_status(status)
            if args.once:
                # windowed burn-rate evaluation (metrics/slo.py) over the
                # run's own trails — the verdict lands in ALERTS.json
                # (schema 2) exactly as the exporter would write it
                verdict = evaluate_from_dir(logging_dir)
                firing = verdict["firing"]
                write_slo_alerts(
                    logging_dir, firing, verdict["objectives"],
                    snapshot=verdict["snapshot"],
                )
                for alert in firing:
                    observed = alert.get("observed")
                    extra = f", burn {alert['burn_rate']:.2f}x"
                    if alert.get("dominant_phase"):
                        extra += f", phase {alert['dominant_phase']}"
                    text += (
                        f"\n  !! SLO {alert['rule']}: observed "
                        f"{observed if observed is None else format(observed, '.4g')}"
                        f" vs threshold "
                        f"{alert['threshold']:.4g} ({alert['env']}{extra})"
                    )
                print(text)
                if (
                    status["wedged"]
                    or status["hang_reports"]
                    or status.get("race_reports")
                    or status.get("collective_divergence")
                    or status.get("fleet_dead")
                ):
                    return 2
                return EXIT_SLO_VIOLATION if firing else 0
            # repaint in place: clear screen + home, like `watch`
            sys.stdout.write("\x1b[2J\x1b[H" + text + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def trace_merge_command(args) -> int:
    from ..diagnostics.tracing import (
        discover_profile_artifacts,
        discover_trace_files,
        merge_traces,
        validate_chrome_trace,
    )

    trace_dir = args.logging_dir
    # accept the logging dir, its traces/ subdir, or a whole routed-fleet
    # dir (router traces/ + every replica_*/traces/) — discovery finds all
    # per-process files so one merge shows a request hopping processes
    paths = discover_trace_files(trace_dir)
    if not paths:
        print(f"trace merge: no host_*.trace.json under {trace_dir}", file=sys.stderr)
        return 1
    subdir = os.path.join(trace_dir, "traces")
    out_dir = subdir if os.path.isdir(subdir) else trace_dir
    output = args.output or os.path.join(out_dir, "merged.trace.json")
    trace = merge_traces(paths=paths, output_path=output)
    validate_chrome_trace(trace)
    hosts = trace["metadata"]["merged_hosts"]
    flows = trace["metadata"].get("request_flows") or {}
    flow_text = ""
    if flows.get("trace_ids"):
        flow_text = (
            f"\nstitched {flows['trace_ids']} request flow(s) by trace_id "
            f"({flows['cross_process']} cross-process, "
            f"{flows['orphan_flows']} orphan flow event(s))"
        )
    profile_text = ""
    profiles = discover_profile_artifacts(trace_dir)
    if profiles:
        profile_text = (
            f"\n{len(profiles)} on-demand profiler capture(s) "
            "(jax-profiler artifacts + flight windows):\n"
            + "\n".join(f"  {p}" for p in profiles)
        )
    print(
        f"merged {len(trace['traceEvents'])} events from "
        f"{len(hosts) or '?'} process(es) -> {output}{flow_text}\n"
        f"open in https://ui.perfetto.dev or chrome://tracing" + profile_text
    )
    return 0


def trace_tail_command(args) -> int:
    """Tail-latency attribution over the slowest K requests (or, with
    ``--iterations``, the slowest K engine iterations by wall time with
    host-vs-device phase attribution) — exit 1 when the directory holds
    no matching trace events at all (tracing was off, or the run predates
    this instrumentation)."""
    import json as _json

    from ..diagnostics.reqtrace import (
        iteration_report,
        render_iteration_report,
        render_tail_report,
        tail_report,
    )

    if not os.path.isdir(args.logging_dir):
        print(f"trace tail: {args.logging_dir} is not a directory", file=sys.stderr)
        return 1
    if getattr(args, "iterations", False):
        try:
            report = iteration_report(args.logging_dir, k=args.k)
        except (FileNotFoundError, ValueError) as e:
            print(f"trace tail: {e}", file=sys.stderr)
            return 1
        if args.json:
            print(_json.dumps(report, indent=2))
        else:
            print(render_iteration_report(report))
        return 0 if report["iterations"] else 1
    try:
        report = tail_report(args.logging_dir, k=args.k, metric=args.metric)
    except (FileNotFoundError, ValueError) as e:
        print(f"trace tail: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(_json.dumps(report, indent=2))
    else:
        print(render_tail_report(report))
    return 0 if report["total_requests"] else 1


def add_parser(subparsers):
    monitor = subparsers.add_parser(
        "monitor", help="Live terminal status of a training run's logging dir"
    )
    monitor.add_argument("logging_dir", help="the run's logging/project dir")
    monitor.add_argument(
        "--interval", type=float, default=2.0, help="refresh period in seconds"
    )
    monitor.add_argument(
        "--once",
        action="store_true",
        help="print one snapshot and exit (scriptable health check: exit 2 "
        "when a host is wedged or a hang report exists, exit 3 when an "
        "ACCELERATE_SLO_* alert rule fires — ALERTS.json is written — else 0)",
    )
    monitor.set_defaults(func=monitor_command)

    trace = subparsers.add_parser(
        "trace", help="Operate on diagnostics trace files"
    )
    trace_sub = trace.add_subparsers(dest="trace_command")
    merge = trace_sub.add_parser(
        "merge",
        help="fuse per-host trace files into one Perfetto-loadable timeline",
    )
    merge.add_argument("logging_dir", help="the run's logging dir (or its traces/ subdir)")
    merge.add_argument("-o", "--output", default=None, help="merged output path")
    merge.set_defaults(func=trace_merge_command)

    tail = trace_sub.add_parser(
        "tail",
        help="slowest-K requests by TTFT/TPOT with per-phase tail attribution "
        "(queued / prefill / swap_in / preempted) from the request-scoped "
        "trace events",
    )
    tail.add_argument("logging_dir", help="the serve/route logging dir")
    tail.add_argument("-k", type=int, default=10, help="tail size (default 10)")
    tail.add_argument("--metric", choices=("ttft", "tpot"), default="ttft",
                      help="latency metric ranking the tail (default ttft)")
    tail.add_argument("--iterations", action="store_true",
                      help="rank engine iterations instead of requests: "
                      "slowest-K by wall time with per-phase host-vs-device "
                      "attribution from the flight recorder's serve/flight "
                      "events")
    tail.add_argument("--json", action="store_true",
                      help="machine-readable report instead of the table")
    tail.set_defaults(func=trace_tail_command)
    return monitor
