"""``accelerate-tpu lint`` — the TPU-correctness static-analysis pass.

Lints training scripts for the anti-patterns that silently destroy the
"~5 lines and your loop runs fast on TPU" contract: implicit host syncs
inside step functions, retrace hazards, wall-clock/RNG baked into traces,
unfenced timing, collectives under data-dependent control flow. Rule
catalogue: ``accelerate_tpu/analysis/rules.py`` (docs:
``usage_guides/linting.md``).

Exit codes (consistent with ``monitor --once``):

* ``0`` — clean, or warnings only
* ``1`` — usage error (no such path, unknown rule id)
* ``2`` — at least one **error**-severity finding

The runtime half of the pass — recompile naming, donation report,
collective-digest files, NaN/inf loss probe — is the sanitizer:
``ACCELERATE_SANITIZE=1`` or ``Accelerator(sanitize=True)``.
"""

from __future__ import annotations

import json
import os
import sys


def lint_command(args) -> int:
    from ..analysis.engine import lint_paths, normalize_rule_ids
    from ..analysis.rules import RULES

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id}  [{rule.severity:7s}] {rule.summary}")
        return 0

    for path in args.paths:
        if not os.path.exists(path):
            print(f"lint: no such path: {path}", file=sys.stderr)
            return 1
    if not args.paths:
        print("lint: no paths given (try `accelerate-tpu lint .`)", file=sys.stderr)
        return 1

    try:
        select = normalize_rule_ids(args.select)
        ignore = normalize_rule_ids(args.ignore)
    except ValueError as e:
        print(f"lint: {e}", file=sys.stderr)
        return 1

    findings, files_scanned = lint_paths(args.paths, select=select, ignore=ignore)
    errors = [f for f in findings if f.severity == "error"]
    warnings = [f for f in findings if f.severity != "error"]

    if args.json:
        print(
            json.dumps(
                {
                    "files_scanned": files_scanned,
                    "errors": len(errors),
                    "warnings": len(warnings),
                    "findings": [f.to_dict() for f in findings],
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        print(
            f"lint: {files_scanned} file(s) scanned — "
            f"{len(errors)} error(s), {len(warnings)} warning(s)"
        )
    return 2 if errors else 0


def add_parser(subparsers):
    p = subparsers.add_parser(
        "lint",
        help="Static-analysis pass for TPU anti-patterns (host syncs, "
        "retrace hazards, collective-order bugs)",
    )
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--select",
        default=None,
        help="comma-separated rule IDs to run exclusively (e.g. TPU001,TPU004)",
    )
    p.add_argument(
        "--ignore",
        default=None,
        help="comma-separated rule IDs to skip",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalogue and exit",
    )
    p.set_defaults(func=lint_command)
    return p
