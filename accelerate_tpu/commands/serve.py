"""``accelerate-tpu serve`` — drive the continuous-batching engine from
JSONL on stdin or a local HTTP endpoint.

Request protocol (one JSON object per line / per POST body):
``{"id": <any>, "prompt": [token ids], "max_new_tokens": <int?>}``;
each completion is written back as
``{"id", "tokens", "ttft_s", "tpot_s", "finish_reason"}``.
Prompts are raw token ids — tokenization is deliberately out of scope (the
engine is model-zoo-generic and this box ships no tokenizer assets).

The engine loop owns the main thread; stdin/HTTP submissions land in a
thread-safe inbox the loop drains between iterations, so network/pipe
latency never stalls decode. ``--logging-dir`` turns on telemetry so
``accelerate-tpu monitor <dir>`` shows live serving health (tokens/s,
queue depth, slot occupancy, TTFT).
"""

from __future__ import annotations

import json
import queue
import sys
import threading
import time


def _build_model(args):
    import jax.numpy as jnp

    from ..models import LlamaConfig, LlamaForCausalLM

    presets = {
        "tiny": lambda: LlamaConfig.tiny(
            vocab_size=256, hidden_size=64, layers=2, heads=4, seq=max(args.max_seq_len, 128)
        ),
        # the bench flagship slice (~700M): the largest single-chip shape
        "flagship": lambda: LlamaConfig.flagship_700m(
            max_position_embeddings=max(args.max_seq_len, 1024)
        ),
    }
    config = presets[args.preset]()
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    return LlamaForCausalLM.from_config(config, seed=args.seed, dtype=dtype)


def _make_engine(args):
    from ..serving import EngineConfig, InferenceEngine

    model = _build_model(args)
    return InferenceEngine(
        model,
        EngineConfig(
            num_slots=args.num_slots,
            block_size=args.block_size,
            max_seq_len=args.max_seq_len,
            prefill_chunk=args.prefill_chunk,
            decode_burst=args.decode_burst,
            eos_token_id=args.eos_token_id,
            do_sample=args.temperature is not None,
            temperature=args.temperature if args.temperature is not None else 1.0,
            seed=args.seed,
            max_new_tokens=args.max_new_tokens,
        ),
    )


def _result_dict(req, req_id) -> dict:
    return {
        "id": req_id,
        "tokens": req.output_tokens,
        "ttft_s": req.ttft_s,
        "tpot_s": req.tpot_s,
        "finish_reason": req.finish_reason,
    }


def _engine_loop(engine, inbox, emit, stop):
    """Drain inbox → step → deliver completion dicts; idle-sleep when empty
    so a quiet server doesn't spin a core. A malformed or over-budget
    request is answered with an ``{"error": ...}`` result — it must never
    kill the loop out from under the other in-flight requests."""
    pending = {}  # engine request_id -> (user id, per-request callback)

    def deliver(result, cb):
        emit(result)
        if cb is not None:
            cb(result)

    while not stop.is_set() or engine.scheduler.has_work() or not inbox.empty():
        try:
            while True:
                payload, cb = inbox.get_nowait()
                try:
                    req = engine.add_request(
                        payload["prompt"], payload.get("max_new_tokens")
                    )
                except Exception as e:  # noqa: BLE001 — reported, not fatal
                    req_id = payload.get("id") if isinstance(payload, dict) else None
                    deliver({"id": req_id, "error": str(e)}, cb)
                    continue
                pending[req.request_id] = (payload.get("id"), cb)
        except queue.Empty:
            pass
        if engine.scheduler.has_work():
            for req in engine.step():
                req_id, cb = pending.pop(req.request_id, (None, None))
                deliver(_result_dict(req, req_id), cb)
        else:
            time.sleep(0.005)


def serve_command(args) -> int:
    # live metrics registry: the telemetry hook (when --logging-dir is set)
    # and the /metrics scrape both publish through it — the vLLM-style
    # in-process exposition, vs the sidecar for embedded-serverless training
    from ..metrics.registry import MetricsRegistry, set_active_registry

    set_active_registry(MetricsRegistry())
    if args.logging_dir:
        from ..telemetry import TelemetryRecorder, set_active_recorder

        set_active_recorder(TelemetryRecorder(logging_dir=args.logging_dir))

    engine = _make_engine(args)
    inbox: queue.Queue = queue.Queue()
    stop = threading.Event()
    out_lock = threading.Lock()

    def emit(result):
        with out_lock:
            print(json.dumps(result), flush=True)

    if args.http:
        return _serve_http(engine, inbox, stop, args.http)

    # stdin/JSONL mode: a reader thread feeds the inbox; EOF arms stop and
    # the loop drains what's in flight before exiting
    def read_stdin():
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                payload = json.loads(line)
            except json.JSONDecodeError as e:
                with out_lock:
                    print(json.dumps({"error": f"bad JSON: {e}"}), flush=True)
                continue
            inbox.put((payload, None))
        stop.set()

    threading.Thread(target=read_stdin, daemon=True).start()
    try:
        _engine_loop(engine, inbox, emit, stop)
    except KeyboardInterrupt:
        pass
    stats = engine.stats()
    print(
        f"served {stats['completed']} requests, "
        f"{stats['tokens_emitted']} tokens "
        f"({stats.get('tokens_per_sec', 0.0):.1f} tok/s), "
        f"decode compiles {stats['decode_compiles']}",
        file=sys.stderr,
    )
    return 0


def _serve_http(engine, inbox, stop, port) -> int:
    """Minimal local HTTP front end: POST /generate blocks until the
    request completes (400 on a rejected one); GET /stats returns engine
    health JSON; GET /metrics answers OpenMetrics text from the active
    registry (refreshed from ``engine.stats()`` on each scrape)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from ..metrics.ingest import observe_engine_stats
    from ..metrics.openmetrics import CONTENT_TYPE, render_openmetrics
    from ..metrics.registry import get_active_registry

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_metrics(self):
            registry = get_active_registry()
            if registry:
                try:
                    observe_engine_stats(registry, engine.stats())
                except Exception:
                    pass
            body = render_openmetrics(registry).encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            # drop any query string (Prometheus scrape params, proxies)
            path = self.path.split("?")[0].rstrip("/")
            if path == "/metrics":
                self._send_metrics()
            elif path in ("", "/stats", "/health"):
                self._send(200, engine.stats())
            else:
                self._send(404, {"error": "unknown path"})

        def do_POST(self):
            if self.path.rstrip("/") != "/generate":
                self._send(404, {"error": "unknown path"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n))
                if not isinstance(payload, dict):
                    raise ValueError("body must be a JSON object")
                if not payload.get("prompt"):
                    raise ValueError("missing prompt")
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": str(e)})
                return
            done = threading.Event()
            box: dict = {}

            def cb(result):
                box["result"] = result
                done.set()

            inbox.put((payload, cb))
            done.wait()
            result = box["result"]
            self._send(400 if "error" in result else 200, result)

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    print(f"serving on http://127.0.0.1:{port} "
          f"(POST /generate, GET /stats, GET /metrics)",
          file=sys.stderr)
    try:
        _engine_loop(engine, inbox, lambda *a: None, stop)
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
    return 0


def add_parser(subparsers):
    p = subparsers.add_parser(
        "serve",
        help="Continuous-batching inference engine over stdin/JSONL or local HTTP",
    )
    p.add_argument("--preset", choices=("tiny", "flagship"), default="tiny",
                   help="model shape (random weights; prompts are token ids)")
    p.add_argument("--dtype", choices=("f32", "bf16"), default="f32")
    p.add_argument("--num-slots", type=int, default=8,
                   help="decode batch slots (the compiled step's static dim)")
    p.add_argument("--block-size", type=int, default=16, help="KV block tokens")
    p.add_argument("--max-seq-len", type=int, default=512,
                   help="per-request prompt+output cap")
    p.add_argument("--prefill-chunk", type=int, default=32,
                   help="prompt tokens prefilled per engine iteration")
    p.add_argument("--decode-burst", type=int, default=8,
                   help="decode steps per dispatch (scheduling granularity)")
    p.add_argument("--max-new-tokens", type=int, default=64,
                   help="default output budget when a request omits it")
    p.add_argument("--eos-token-id", type=int, default=None)
    p.add_argument("--temperature", type=float, default=None,
                   help="enable sampling at this temperature (default: greedy)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--http", type=int, default=None, metavar="PORT",
                   help="serve a local HTTP endpoint instead of stdin JSONL")
    p.add_argument("--logging-dir", default=None,
                   help="enable telemetry here (accelerate-tpu monitor shows "
                   "serving health)")
    p.set_defaults(func=serve_command)
    return p
