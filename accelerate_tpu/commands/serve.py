"""``accelerate-tpu serve`` — drive the continuous-batching engine from
JSONL on stdin or a local HTTP endpoint.

Request protocol (one JSON object per line / per POST body):
``{"id": <any>, "prompt": [token ids], "max_new_tokens": <int?>,
"priority": "interactive"|"batch"?, "deadline_ms": <number?>,
"tenant": <str?>, "sampling": {...}?, "grammar": {...}?}``;
each completion is written back as
``{"id", "tenant", "tokens", "ttft_s", "tpot_s", "finish_reason"}`` plus
the usage ledger's measured costs (``device_time_s`` /
``kv_block_seconds`` / ``swap_bytes``) when accounting is on. ``priority``
defaults to ``interactive``; under pool pressure the scheduler swaps
``batch`` victims to host DRAM before ever touching interactive ones.
``deadline_ms`` is a relative budget: once it elapses the scheduler
finishes the request with ``finish_reason="deadline_exceeded"`` (partial
tokens kept, KV blocks freed the same iteration); a malformed value is
answered with an error row, like an unknown ``priority``. ``sampling``
carries per-request :class:`~accelerate_tpu.serving.SamplingParams`
fields (temperature/top_k/top_p/seed/stop/...); ``grammar`` a
constrained-decoding spec (:mod:`accelerate_tpu.serving.grammar`) —
both ride the ONE compiled decode executable as lane inputs.
Prompts are raw token ids — tokenization is deliberately out of scope (the
engine is model-zoo-generic and this box ships no tokenizer assets).
``--http`` additionally mounts the OpenAI-compatible door
(``POST /v1/completions`` + ``/v1/chat/completions``, SSE streaming and
non-streaming — :mod:`accelerate_tpu.serving.openai_api`), where string
prompts byte-tokenize and ``response_format={"type": "json_schema"}``
maps onto ``grammar``.

The engine loop owns the main thread; stdin/HTTP submissions land in a
thread-safe inbox the loop drains between iterations, so network/pipe
latency never stalls decode. ``--logging-dir`` turns on telemetry so
``accelerate-tpu monitor <dir>`` shows live serving health (tokens/s,
queue depth, slot occupancy, TTFT).

Lifecycle (the router's dispatch + drain signals):

* ``GET /healthz`` reports a real state machine — ``starting`` (engine
  building/compiling), ``ready`` (loop serving), ``draining`` (SIGTERM
  observed: admission stopped, in-flight finishing) — plus live
  ``queue_depth``/``active_slots`` gauges;
* SIGTERM reuses the resilience :class:`PreemptionHandler` flag: the loop
  observes it between iterations, stops admission (late requests get an
  error *answer*, never silence), drains everything already admitted, and
  exits 0. kill-proven in ``tests/test_router.py``.
"""

from __future__ import annotations

import json
import os
import queue
import sys
import threading
import time

#: seconds the drain loop waits for pipe-buffered stragglers after going
#: idle — lines written before the signal but not yet through the reader
#: thread still deserve answers
_DRAIN_IDLE_GRACE_S = 0.75


class ServeHealth:
    """The front end's lifecycle state machine: ``starting`` → ``ready`` →
    ``draining``. Transitions are one-way; readers (the /healthz handler,
    the stdin reader, the engine loop) only ever look at ``state``."""

    def __init__(self, replica_id: int | None = None):
        from ..analysis.lockwatch import maybe_watch

        self.replica_id = replica_id
        self._state = "starting"
        self._lock = maybe_watch(threading.Lock(), "ServeHealth._lock")

    @property
    def state(self) -> str:
        with self._lock:  # every reader goes through here (race-check RC001)
            return self._state

    @property
    def ready(self) -> bool:
        return self.state == "ready"

    @property
    def draining(self) -> bool:
        return self.state == "draining"

    def mark_ready(self) -> None:
        with self._lock:
            if self._state == "starting":
                self._state = "ready"

    def mark_draining(self) -> None:
        with self._lock:
            self._state = "draining"

    def payload(self, engine=None) -> dict:
        """The /healthz answer: state + the router's dispatch gauges."""
        out = {
            "state": self.state,
            "pid": os.getpid(),
            "replica_id": self.replica_id,
            "queue_depth": None,
            "active_slots": None,
            "num_slots": None,
        }
        if engine is not None:
            try:
                out["queue_depth"] = int(engine.scheduler.queue_depth)
                out["active_slots"] = len(engine.scheduler.active())
                out["num_slots"] = int(engine.config.num_slots)
                # cumulative engine-side deadline evictions: expiries happen
                # in the replica (the slot is evicted, the partial answer
                # still delivered), so without this the fleet totals — and
                # the windowed error-rate objective reading them — would
                # only ever see *router-queue* expiries
                out["deadline_expired"] = int(
                    getattr(engine, "_deadline_expired", 0)
                )
            except Exception:
                pass
        return out


def _build_model(args):
    import jax.numpy as jnp

    from ..models import LlamaConfig, LlamaForCausalLM

    presets = {
        "tiny": lambda: LlamaConfig.tiny(
            vocab_size=256, hidden_size=64, layers=2, heads=4, seq=max(args.max_seq_len, 128)
        ),
        # the bench flagship slice (~700M): the largest single-chip shape
        "flagship": lambda: LlamaConfig.flagship_700m(
            max_position_embeddings=max(args.max_seq_len, 1024)
        ),
    }
    config = presets[args.preset]()
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    return LlamaForCausalLM.from_config(config, seed=args.seed, dtype=dtype)


def _plan_kv_dtype(args) -> str:
    """The storage dtype string the shard-check HBM model prices blocks
    with — quantized policies price payload + scale arrays, so
    ``--auto-blocks`` sizes the pool from the bytes the engine will
    actually allocate (capacity ~doubles at int8/fp8)."""
    from ..analysis.shardplan import kv_storage_name

    return kv_storage_name(
        args.kv_dtype, "bfloat16" if args.dtype == "bf16" else "float32"
    )


class _PreflightRefusal(Exception):
    """Engine construction refused to start (the SP004 pre-flight, or
    invalid geometry) — distinct from a ValueError escaping the live
    serving loop, which must not be mislabeled as a startup refusal."""


def _auto_num_blocks(args, model, mesh) -> int:
    """``--auto-blocks``: size ``num_blocks`` from the shard-check HBM
    model instead of a hand-picked constant. Budget = ``--hbm-gb`` or the
    attached device's reported HBM; raises ValueError (the SP004 refusal)
    when neither is known or even one request's blocks don't fit."""
    from ..analysis.shardplan import auto_num_blocks, mesh_sizes_of, plan_kv_pool, plan_params
    from ..mesh import device_hbm_bytes
    from ..serving.blocks import blocks_needed

    budget_bytes = (
        int(args.hbm_gb * (1 << 30)) if args.hbm_gb is not None else device_hbm_bytes()
    )
    if budget_bytes is None:
        raise ValueError(
            "SP004: --auto-blocks needs an HBM budget, and this backend "
            "reports no device memory limit — pass --hbm-gb"
        )
    inner = getattr(model, "_model", None) or model
    cfg = inner.config
    sizes = (
        mesh_sizes_of(mesh) if mesh is not None
        else {ax: 1 for ax in ("dp", "pp", "fsdp", "ep", "cp", "tp")}
    )
    rules = getattr(inner, "partition_rules", None)
    params_bytes = sum(
        p.bytes_per_device for p in plan_params(model.params, sizes, rules=rules)
    )
    n_kv = getattr(cfg, "num_key_value_heads", None) or cfg.num_attention_heads
    per_block = sum(
        p.bytes_per_device
        for p in plan_kv_pool(
            num_layers=cfg.num_hidden_layers,
            num_kv_heads=n_kv,
            head_dim=cfg.head_dim,
            num_slots=1,
            block_size=args.block_size,
            max_seq_len=args.max_seq_len,
            num_blocks=1,
            mesh_sizes=sizes,
            dtype=_plan_kv_dtype(args),
        )
    )
    blocks_per_slot = blocks_needed(args.max_seq_len, args.block_size)
    full_residency = args.num_slots * blocks_per_slot + 1
    num_blocks, headroom = auto_num_blocks(
        budget_bytes,
        params_bytes,
        per_block,
        full_residency_blocks=full_residency,
        min_blocks=blocks_per_slot + 1,  # one full request + the null block
    )
    gib = 1 << 30
    print(
        f"auto-blocks: {num_blocks} blocks "
        f"({per_block / 1e6:.2f} MB/block/device; full residency "
        f"{full_residency}) — params {params_bytes / gib:.3f} GiB/device, "
        f"predicted headroom {headroom / gib:.3f} GiB under the "
        f"{budget_bytes / gib:.3f} GiB budget",
        file=sys.stderr,
    )
    return num_blocks


def _make_engine(args):
    from ..serving import EngineConfig, InferenceEngine

    mesh = None
    if getattr(args, "mesh", False):
        from ..mesh import build_mesh

        mesh = build_mesh()  # MeshPlugin reads ACCELERATE_MESH_* env vars
    model = _build_model(args)
    num_blocks = args.num_blocks
    if args.auto_blocks:
        num_blocks = _auto_num_blocks(args, model, mesh)
    return InferenceEngine(
        model,
        EngineConfig(
            num_slots=args.num_slots,
            block_size=args.block_size,
            max_seq_len=args.max_seq_len,
            num_blocks=num_blocks,
            prefill_chunk=args.prefill_chunk,
            decode_burst=args.decode_burst,
            eos_token_id=args.eos_token_id,
            do_sample=args.temperature is not None,
            temperature=args.temperature if args.temperature is not None else 1.0,
            seed=args.seed,
            max_new_tokens=args.max_new_tokens,
            hbm_budget_gb=args.hbm_gb,
            prefix_cache=args.prefix_cache,
            swap_gb=args.swap_gb,
            kv_dtype=args.kv_dtype,
            spec_k=args.spec_k,
            draft=args.draft,
            flight_history=args.flight_history,
            stats_interval=getattr(args, "stats_interval", 32),
            logprobs_topn=args.logprobs_topn,
            async_dispatch=not getattr(args, "sync_engine", False),
            usage_accounting=getattr(args, "usage_accounting", True),
        ),
        mesh=mesh,
    )


def _write_flight_drain(logging_dir, engine, k: int = 32) -> None:
    """On a SIGTERM drain, persist the flight recorder's last-``k``
    iterations beside the run's other artifacts — the post-mortem twin of
    the watchdog's HANG_REPORT ``flight_tail``, for engines that exited
    cleanly but slowly."""
    if not logging_dir or engine is None:
        return
    fl = getattr(engine, "_flight", None)
    if fl is None:
        return
    path = os.path.join(logging_dir, f"FLIGHT_DRAIN_{os.getpid()}.json")
    try:
        with open(path, "w") as f:
            json.dump(
                {
                    "type": "flight_drain",
                    "pid": os.getpid(),
                    "ts": time.time(),
                    "current_phase": fl.current_phase,
                    "iterations": fl.iterations,
                    "host_fraction": fl.host_fraction(),
                    "entries": fl.tail(k),
                },
                f, indent=2,
            )
    except OSError:
        pass


def _result_dict(req, req_id) -> dict:
    out = {
        "id": req_id,
        "trace_id": req.trace_id,
        "tenant": req.tenant,
        "tokens": req.output_tokens,
        "prompt_tokens": req.prompt_len,
        "ttft_s": req.ttft_s,
        "tpot_s": req.tpot_s,
        "finish_reason": req.finish_reason,
    }
    if req.logprobs is not None:
        out["logprobs"] = req.logprobs
    if req.usage is not None:
        # the usage ledger's answer-row costs: what THIS request spent
        # (device_time_s / kv_block_seconds / swap_bytes, measured)
        out.update(req.usage)
    return out


def _engine_loop(engine, inbox, emit, stop, health=None, handler=None,
                 max_queue=None):
    """Drain inbox → step → deliver completion dicts; idle-sleep when empty
    so a quiet server doesn't spin a core. A malformed or over-budget
    request is answered with an ``{"error": ...}`` result — it must never
    kill the loop out from under the other in-flight requests.

    Exit conditions: ``stop`` (stdin EOF / server teardown) with nothing
    left in flight, or a drain (SIGTERM → ``health.draining``) once the
    engine has been idle for a short grace window — stragglers already in
    the pipe still get answered.

    A payload may carry a ``_stream`` callable (the OpenAI SSE path):
    it is called with each NEW token chunk as decode emits them. When the
    request has stop sequences, streaming lags by ``max(len(stop)) - 1``
    tokens so a delta can never over-send tokens a matched stop sequence
    later truncates — the final result row is always authoritative and
    exactly completes what was streamed."""
    pending = {}  # engine request_id -> (user id, per-request callback)
    streams = {}  # engine request_id -> [stream_cb, req, served, holdback]

    def deliver(result, cb):
        emit(result)
        if cb is not None:
            cb(result)

    idle_since = None
    while True:
        if (
            handler is not None
            and handler.preemption_requested
            and health is not None
            and not health.draining
        ):
            health.mark_draining()
        try:
            while True:
                payload, cb = inbox.get_nowait()
                req_id = payload.get("id") if isinstance(payload, dict) else None
                if (
                    max_queue is not None
                    and engine.scheduler.queue_depth >= max_queue
                ):
                    # bounded admission: an explicit over-capacity answer
                    # beats letting the waiting queue grow without limit
                    # (the router's shed path does class-aware shedding;
                    # the single-engine bound is a hard backstop)
                    deliver({
                        "id": req_id,
                        "error": f"over capacity: engine queue depth "
                        f"{engine.scheduler.queue_depth} at --max-queue "
                        f"{max_queue} — request shed",
                    }, cb)
                    continue
                try:
                    req = engine.add_request(
                        payload["prompt"], payload.get("max_new_tokens"),
                        priority=payload.get("priority", "interactive"),
                        deadline_ms=payload.get("deadline_ms"),
                        trace_id=payload.get("trace_id"),
                        # only a routed replica closes the router's flow
                        # arrow — a standalone serve emitting flow heads
                        # would count every request as an orphaned flow
                        upstream_hop=(
                            health is not None
                            and health.replica_id is not None
                            and payload.get("trace_id") is not None
                        ),
                        sampling=payload.get("sampling"),
                        grammar=payload.get("grammar"),
                        tenant=payload.get("tenant"),
                    )
                except Exception as e:  # noqa: BLE001 — reported, not fatal
                    deliver({"id": req_id, "error": str(e)}, cb)
                    continue
                pending[req.request_id] = (payload.get("id"), cb)
                stream_cb = payload.get("_stream")
                if stream_cb is not None:
                    hold = 0
                    if req.sampling is not None and req.sampling.stop:
                        hold = max(len(s) for s in req.sampling.stop) - 1
                    streams[req.request_id] = [stream_cb, req, 0, hold]
        except queue.Empty:
            pass
        if engine.scheduler.has_work():
            idle_since = None
            for req in engine.step():
                req_id, cb = pending.pop(req.request_id, (None, None))
                streams.pop(req.request_id, None)
                deliver(_result_dict(req, req_id), cb)
            for entry in streams.values():
                stream_cb, req, served, hold = entry
                avail = len(req.output_tokens) - hold
                if avail > served:
                    stream_cb(req.output_tokens[served:avail])
                    entry[2] = avail
            continue
        if stop.is_set() and inbox.empty():
            return  # EOF/teardown: the pipe is closed, nothing more can arrive
        if health is not None and health.draining:
            if idle_since is None:
                idle_since = time.monotonic()
            elif time.monotonic() - idle_since > _DRAIN_IDLE_GRACE_S:
                return
            time.sleep(0.01)
        else:
            time.sleep(0.005)


def serve_command(args) -> int:
    # live metrics registry: the telemetry hook (when --logging-dir is set)
    # and the /metrics scrape both publish through it — the vLLM-style
    # in-process exposition, vs the sidecar for embedded-serverless training
    from ..metrics.registry import MetricsRegistry, set_active_registry
    from ..resilience.preemption import PreemptionHandler

    set_active_registry(MetricsRegistry())
    if args.logging_dir:
        from ..diagnostics.tracing import Tracer, set_active_tracer
        from ..telemetry import TelemetryRecorder, set_active_recorder

        set_active_recorder(TelemetryRecorder(logging_dir=args.logging_dir))
        # request-scoped tracing rides the same switch as telemetry: every
        # request's lifecycle (arrive → admit → prefill → first token →
        # finish) lands in this process's trace file, stitched fleet-wide
        # by `accelerate-tpu trace merge`/`trace tail` via the trace_id
        set_active_tracer(Tracer(
            logging_dir=args.logging_dir,
            process_name=(
                f"replica_{args.replica_id}" if args.replica_id is not None
                else "serve"
            ),
        ))

    health = ServeHealth(replica_id=args.replica_id)
    # SIGTERM = drain request (the preemption contract): flag only; the
    # engine loop observes it between iterations. Ctrl-C keeps its
    # KeyboardInterrupt fast path below.
    handler = PreemptionHandler(handle_sigint=False)
    handler.install()

    inbox: queue.Queue = queue.Queue()
    stop = threading.Event()
    out_lock = threading.Lock()

    def emit(result):
        with out_lock:
            print(json.dumps(result), flush=True)

    # fault injection (serving/chaos.py): a parse error is a bring-up
    # refusal — a typo'd spec silently running a clean "chaos" test would
    # certify nothing
    from ..serving.chaos import ChaosInjector, ChaosSpecError

    try:
        chaos = ChaosInjector.from_spec(args.chaos_spec, replica_id=args.replica_id)
    except ChaosSpecError as e:
        emit({"error": str(e)})
        print(f"serve: refusing to start: {e}", file=sys.stderr)
        handler.uninstall()
        return 2
    if chaos is not None:
        print(
            f"serve: chaos injection armed (replica {args.replica_id})",
            file=sys.stderr,
        )
        if not args.http:
            print(
                "serve: chaos faults fire at the HTTP replica boundary — "
                "stdin mode ignores the spec", file=sys.stderr,
            )

    # seeded replayable workload (serving/workload.py): same contract as
    # --chaos-spec — a malformed spec is a bring-up refusal (exit 2), not
    # a silent empty run
    from ..serving.workload import (
        TraceSpecError,
        generate_schedule,
        parse_trace_spec,
        run_schedule,
        write_workload_manifest,
    )

    trace_spec = trace_schedule = None
    if args.trace:
        try:
            trace_spec = parse_trace_spec(args.trace)
            trace_schedule = generate_schedule(trace_spec)
        except TraceSpecError as e:
            emit({"error": str(e)})
            print(f"serve: refusing to start: {e}", file=sys.stderr)
            handler.uninstall()
            return 2
        if args.http:
            # the HTTP door has external clients driving it; a workload
            # generator feeding the same inbox would interleave with them
            print(
                "serve: --trace drives the stdin/JSONL loop — HTTP mode "
                "ignores the spec (route --trace drives a fleet)",
                file=sys.stderr,
            )
            trace_spec = trace_schedule = None

    try:
        if args.http:
            # factory form: the server binds FIRST (so /healthz answers
            # `starting` while the engine builds/compiles), then the engine
            # comes up and the state flips to `ready`. Only a ValueError
            # raised while BUILDING the engine is a refusal — one escaping
            # the live serving loop later must keep its traceback.
            def build_engine():
                try:
                    return _make_engine(args)
                except ValueError as e:
                    raise _PreflightRefusal(str(e)) from e

            try:
                return _serve_http(build_engine, inbox, stop,
                                   args.http, health=health, handler=handler,
                                   chaos=chaos, max_queue=args.max_queue,
                                   logging_dir=args.logging_dir)
            except _PreflightRefusal as e:
                # SP004 pre-flight refusal (or invalid geometry): an error
                # row + exit 2, the same contract as shard-check
                emit({"error": str(e)})
                print(f"serve: refusing to start: {e}", file=sys.stderr)
                return 2

        try:
            engine = _make_engine(args)
        except ValueError as e:
            emit({"error": str(e)})
            print(f"serve: refusing to start: {e}", file=sys.stderr)
            return 2
        # stdin/JSONL mode: a reader thread feeds the inbox; EOF arms stop
        # and the loop drains what's in flight before exiting. Once
        # draining, admission stops — late lines are answered, not queued.
        health.mark_ready()

        def read_stdin():
            for line in sys.stdin:
                line = line.strip()
                if not line:
                    continue
                try:
                    payload = json.loads(line)
                except json.JSONDecodeError as e:
                    with out_lock:
                        print(json.dumps({"error": f"bad JSON: {e}"}), flush=True)
                    continue
                if health.draining:
                    req_id = payload.get("id") if isinstance(payload, dict) else None
                    emit({"id": req_id, "error": "draining: admission stopped"})
                    continue
                inbox.put((payload, None))
            stop.set()

        if trace_schedule is not None:
            if args.logging_dir:
                write_workload_manifest(args.logging_dir, trace_spec, trace_schedule)
            print(
                f"serve: replaying workload {trace_spec.as_text()} "
                f"({len(trace_schedule)} requests)", file=sys.stderr,
            )

            def feed_trace():
                run_schedule(
                    trace_schedule,
                    lambda payload: inbox.put((payload, None)),
                    should_stop=lambda: health.draining or stop.is_set(),
                )
                stop.set()

            threading.Thread(target=feed_trace, daemon=True).start()
        else:
            threading.Thread(target=read_stdin, daemon=True).start()
        try:
            _engine_loop(engine, inbox, emit, stop, health=health,
                         handler=handler, max_queue=args.max_queue)
        except KeyboardInterrupt:
            pass
        stats = engine.stats()
        drained = " (drained on SIGTERM)" if health.draining else ""
        if health.draining:
            _write_flight_drain(args.logging_dir, engine)
        print(
            f"served {stats['completed']} requests, "
            f"{stats['tokens_emitted']} tokens "
            f"({stats.get('tokens_per_sec', 0.0):.1f} tok/s), "
            f"decode compiles {stats['decode_compiles']}{drained}",
            file=sys.stderr,
        )
        return 0
    finally:
        handler.uninstall()


def _serve_http(engine, inbox, stop, port, health=None, handler=None,
                chaos=None, max_queue=None, logging_dir=None) -> int:
    """Minimal local HTTP front end: POST /generate blocks until the
    request completes (400 on a rejected one, 503 while starting or
    draining); GET /healthz answers the lifecycle state machine +
    queue/slot gauges; GET /stats returns engine health JSON; GET /metrics
    answers OpenMetrics text from the active registry (refreshed from
    ``engine.stats()`` on each scrape); GET /profile?seconds=N captures an
    on-demand jax-profiler window + flight-recorder dump into
    ``logging_dir/profiles/`` while the engine keeps serving (409 when a
    capture is already running, 400 without a logging dir).

    ``chaos`` (a :class:`~accelerate_tpu.serving.chaos.ChaosInjector`)
    injects scheduled faults at this boundary: ``kill``/``stop``/``delay``
    and 503 bursts fire per received ``/generate`` request, health-check
    blackouts tear ``/healthz`` connections. Disabled = one falsy check
    per request, like the telemetry null object.

    ``engine`` may be a ready instance or a zero-arg factory — with a
    factory the server binds and answers ``/healthz`` as ``starting``
    *while* the engine builds, which is what the router's bring-up
    health-checks observe."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from ..metrics.ingest import observe_engine_stats
    from ..metrics.openmetrics import CONTENT_TYPE, render_openmetrics
    from ..metrics.registry import get_active_registry
    from ..serving.openai_api import OPENAI_PATHS, OpenAIFrontend

    health = health or ServeHealth()
    box = {"engine": None if callable(engine) else engine}
    frontend = OpenAIFrontend(
        lambda payload, cb: inbox.put((payload, cb)), streaming="delta"
    )

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 so SSE streams ride chunked transfer encoding (every
        # non-stream answer already sends Content-Length)
        protocol_version = "HTTP/1.1"
        #: one capture at a time — jax.profiler has a single global trace
        #: session; a concurrent request gets an explicit 409, not a crash
        profile_lock = threading.Lock()

        def log_message(self, *a):  # quiet
            pass

        def _send(self, code, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_metrics(self):
            registry = get_active_registry()
            if registry and box["engine"] is not None:
                try:
                    observe_engine_stats(registry, box["engine"].stats())
                except Exception:
                    pass
            body = render_openmetrics(registry).encode()
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            # split off the query string (Prometheus scrape params,
            # /profile?seconds=N) instead of dropping it
            path, _, query = self.path.partition("?")
            path = path.rstrip("/")
            if path == "/metrics":
                self._send_metrics()
            elif path == "/profile":
                self._handle_profile(query)
            elif path == "/healthz":
                if chaos is not None and chaos.healthz_blackout():
                    # injected health blackout: tear the connection — the
                    # prober sees exactly what a starved /healthz looks like
                    self.close_connection = True
                    return
                self._send(200, health.payload(box["engine"]))
            elif path in ("", "/stats", "/health"):
                eng = box["engine"]
                self._send(200, eng.stats() if eng is not None
                           else {"state": health.state})
            else:
                self._send(404, {"error": "unknown path"})

        def _handle_profile(self, query: str):
            """On-demand windowed capture: jax.profiler trace + the flight
            iterations that land inside the window. Runs in this handler
            thread — the engine loop keeps stepping underneath, which is
            the point (profile the engine *while it serves*)."""
            eng = box["engine"]
            if eng is None or not health.ready:
                self._send(503, {"error": f"engine not ready: {health.state}"})
                return
            if not logging_dir:
                self._send(400, {"error": "profiling needs --logging-dir"})
                return
            from urllib.parse import parse_qs

            try:
                seconds = float((parse_qs(query).get("seconds") or ["2.0"])[0])
            except (TypeError, ValueError):
                self._send(400, {"error": "seconds must be a number"})
                return
            seconds = min(max(seconds, 0.05), 120.0)
            if not Handler.profile_lock.acquire(blocking=False):
                self._send(409, {"error": "a profile capture is already running"})
                return
            try:
                from ..serving.flight import capture_profile_window

                manifest = capture_profile_window(logging_dir, seconds, engine=eng)
            except Exception as e:  # noqa: BLE001 — reported, never fatal
                self._send(500, {"error": f"profile capture failed: {e}"})
                return
            finally:
                Handler.profile_lock.release()
            self._send(200, manifest)

        def _send_sse(self, events):
            """Stream SSE events as HTTP/1.1 chunked transfer frames; a
            client hangup mid-stream is normal teardown, not an error."""
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                for event in events:
                    data = event.encode()
                    self.wfile.write(
                        f"{len(data):X}\r\n".encode() + data + b"\r\n"
                    )
                    self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                self.close_connection = True

        def _handle_openai(self, path: str, raw: bytes):
            """The OpenAI-compatible door: same chaos/lifecycle gates as
            /generate, OpenAI-shaped error objects on every refusal."""
            def err(status, message, type_="invalid_request_error"):
                self._send(status, {"error": {
                    "message": message, "type": type_,
                    "param": None, "code": None,
                }})

            if chaos is not None and chaos.on_generate() == "err503":
                err(503, "chaos: injected 503 burst", "server_error")
                return
            if not health.ready:
                err(503, f"not accepting requests: {health.state}",
                    "server_error")
                return
            try:
                body = json.loads(raw)
            except json.JSONDecodeError as e:
                err(400, f"bad JSON: {e}")
                return
            kind, *rest = frontend.handle(path, body)
            if kind == "sse":
                self._send_sse(rest[0])
            else:
                self._send(rest[0], rest[1])

        def do_POST(self):
            path = self.path.rstrip("/")
            # read the body up front: on a keep-alive connection an early
            # refusal that skips the body would desync the next request
            try:
                n = int(self.headers.get("Content-Length", 0) or 0)
            except ValueError:
                n = 0
            raw = self.rfile.read(n) if n else b""
            if path in OPENAI_PATHS:
                self._handle_openai(path, raw)
                return
            if path != "/generate":
                self._send(404, {"error": "unknown path"})
                return
            if chaos is not None:
                # kill/stop never return; delay sleeps in this handler
                # thread (the request is mid-flight, exactly like a slow
                # engine); a 503 burst answers before admission
                if chaos.on_generate() == "err503":
                    self._send(503, {"error": "chaos: injected 503 burst"})
                    return
            if not health.ready:
                # starting or draining: an explicit answer, so the router
                # (or any client) fails fast instead of queueing into a
                # front end that will never serve it
                self._send(503, {"error": f"not accepting requests: {health.state}"})
                return
            try:
                payload = json.loads(raw)
                if not isinstance(payload, dict):
                    raise ValueError("body must be a JSON object")
                if not payload.get("prompt"):
                    raise ValueError("missing prompt")
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": str(e)})
                return
            done = threading.Event()
            answer: dict = {}  # NOT `box` — that closure holds the engine

            def cb(result):
                answer["result"] = result
                done.set()

            inbox.put((payload, cb))
            done.wait()
            result = answer["result"]
            self._send(400 if "error" in result else 200, result)

    class Server(ThreadingHTTPServer):
        # default request_queue_size=5: under router redispatch churn a LIVE
        # replica would refuse connections, which reads as a transport death
        request_queue_size = 128

    server = Server(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    print(f"serving on http://127.0.0.1:{port} "
          f"(POST /generate + /v1/completions + /v1/chat/completions, "
          f"GET /healthz, GET /stats, GET /metrics)",
          file=sys.stderr)
    try:
        if box["engine"] is None:
            box["engine"] = engine()  # /healthz says `starting` during this build
        health.mark_ready()
        _engine_loop(box["engine"], inbox, lambda *a: None, stop,
                     health=health, handler=handler, max_queue=max_queue)
    except KeyboardInterrupt:
        pass
    finally:
        # build failures (the pre-flight refusal) must also unbind the
        # port — a leaked server thread answers /healthz `starting` forever
        server.shutdown()
        if health.draining:
            _write_flight_drain(logging_dir, box["engine"])
    return 0


def add_parser(subparsers):
    p = subparsers.add_parser(
        "serve",
        help="Continuous-batching inference engine over stdin/JSONL or local HTTP",
    )
    p.add_argument("--preset", choices=("tiny", "flagship"), default="tiny",
                   help="model shape (random weights; prompts are token ids)")
    p.add_argument("--dtype", choices=("f32", "bf16"), default="f32")
    p.add_argument("--num-slots", type=int, default=8,
                   help="decode batch slots (the compiled step's static dim)")
    p.add_argument("--block-size", type=int, default=16, help="KV block tokens")
    p.add_argument("--max-seq-len", type=int, default=512,
                   help="per-request prompt+output cap")
    p.add_argument("--prefill-chunk", type=int, default=32,
                   help="prompt tokens prefilled per engine iteration")
    p.add_argument("--decode-burst", type=int, default=8,
                   help="decode steps per dispatch (scheduling granularity)")
    p.add_argument("--num-blocks", type=int, default=None,
                   help="paged KV pool blocks (default: full residency — "
                   "num_slots x blocks-per-slot + 1)")
    p.add_argument("--hbm-gb", type=float, default=None,
                   help="per-device HBM budget: the engine runs the "
                   "shard-check pre-flight and refuses to start (error row, "
                   "exit 2) if params + pools exceed it")
    p.add_argument("--auto-blocks", action="store_true",
                   help="size num_blocks from the shard-check HBM model "
                   "(budget: --hbm-gb, or the device's reported HBM) and log "
                   "the chosen count + predicted headroom")
    p.add_argument("--max-new-tokens", type=int, default=64,
                   help="default output budget when a request omits it")
    p.add_argument("--max-queue", type=int, default=None,
                   help="bounded admission: shed (error row) any request "
                   "arriving while this many are already waiting for a slot "
                   "(default: unbounded, the pre-robustness behaviour)")
    p.add_argument("--trace", default=None, metavar="SPEC",
                   help="drive the engine from a seeded replayable workload "
                   "instead of stdin: 'name:seed:duration:rps' with name in "
                   "bursty-diurnal|longctx-flood|agentic|overbudget-storm, "
                   "or 'replay:<path>' for a recorded schedule (same seed = "
                   "byte-identical schedule; malformed spec = exit 2)")
    p.add_argument("--chaos-spec", default=None,
                   help="fault-injection schedule for chaos testing (env "
                   "ACCELERATE_CHAOS_SPEC; seed via ACCELERATE_CHAOS_SEED): "
                   "e.g. 'r0:kill@5;r1:delay@3:0.2;err503@2:3;blackout@6:1.5' "
                   "— kill -9 / SIGSTOP / delay / 503 burst / healthz "
                   "blackout keyed on the replica's /generate request "
                   "ordinal; deterministic per (spec, seed). HTTP mode only")
    # prefix sharing + swap preemption knobs (env defaults let a fleet
    # flip them without touching every replica's command line). Parsed
    # defensively: add_parser runs while building EVERY subcommand's
    # parser, so a malformed env value must warn, not kill `monitor`.
    prefix_env = os.environ.get("ACCELERATE_SERVE_PREFIX_CACHE", "1")
    p.add_argument("--prefix-cache", dest="prefix_cache", action="store_true",
                   default=prefix_env.strip().lower()
                   not in ("0", "false", "no", "off", ""),
                   help="radix prefix sharing over the block pool (default "
                   "on; env ACCELERATE_SERVE_PREFIX_CACHE=0 disables)")
    p.add_argument("--no-prefix-cache", dest="prefix_cache", action="store_false",
                   help="disable prefix sharing (every prompt prefills cold)")
    usage_env = os.environ.get("ACCELERATE_SERVE_USAGE", "1")
    p.add_argument("--usage-accounting", dest="usage_accounting",
                   action="store_true",
                   default=usage_env.strip().lower()
                   not in ("0", "false", "no", "off", ""),
                   help="conservation-checked per-request usage ledger: "
                   "device-seconds, KV block-seconds, swap bytes by tenant/"
                   "class (default on; env ACCELERATE_SERVE_USAGE=0 disables)")
    p.add_argument("--no-usage-accounting", dest="usage_accounting",
                   action="store_false",
                   help="disable the usage ledger (answer rows carry no "
                   "cost fields; stats()/telemetry carry no usage snapshot)")
    try:
        swap_default = float(os.environ.get("ACCELERATE_SERVE_SWAP_GB", "0") or 0)
    except ValueError:
        print(
            "accelerate-tpu: ignoring malformed ACCELERATE_SERVE_SWAP_GB="
            f"{os.environ['ACCELERATE_SERVE_SWAP_GB']!r} (want GiB as a float)",
            file=sys.stderr,
        )
        swap_default = 0.0
    p.add_argument("--swap-gb", type=float, default=swap_default,
                   help="host-DRAM KV swap tier in GiB (default 0 = off; env "
                   "ACCELERATE_SERVE_SWAP_GB): under pool exhaustion the "
                   "lowest-priority request is swapped out instead of being "
                   "truncated with finish_reason=out_of_blocks")
    kv_env = os.environ.get("ACCELERATE_SERVE_KV_DTYPE", "auto").strip().lower()
    if kv_env not in ("auto", "bf16", "f32", "int8", "fp8"):
        print(
            "accelerate-tpu: ignoring malformed ACCELERATE_SERVE_KV_DTYPE="
            f"{kv_env!r} (want auto|bf16|f32|int8|fp8)",
            file=sys.stderr,
        )
        kv_env = "auto"
    p.add_argument("--kv-dtype", choices=("auto", "bf16", "f32", "int8", "fp8"),
                   default=kv_env,
                   help="KV pool storage policy (default auto = the params' "
                   "compute dtype; env ACCELERATE_SERVE_KV_DTYPE): int8/fp8 "
                   "quantize on scatter with per-row amax scales — half the "
                   "decode bytes, ~2x the slot capacity at equal --hbm-gb")
    try:
        spec_k_default = int(os.environ.get("ACCELERATE_SERVE_SPEC_K", "0") or 0)
    except ValueError:
        print(
            "accelerate-tpu: ignoring malformed ACCELERATE_SERVE_SPEC_K="
            f"{os.environ['ACCELERATE_SERVE_SPEC_K']!r} (want an integer)",
            file=sys.stderr,
        )
        spec_k_default = 0
    p.add_argument("--spec-k", type=int, default=spec_k_default,
                   help="speculative decoding: draft this many tokens per "
                   "slot per round and verify them in ONE [num_slots, k+1] "
                   "compiled forward (default 0 = off; env "
                   "ACCELERATE_SERVE_SPEC_K). Greedy requests stay "
                   "token-identical to the non-speculative engine; sampled "
                   "requests verify by rejection sampling. A bad spec/draft "
                   "combination is a startup refusal (error row, exit 2)")
    p.add_argument("--draft", default=os.environ.get(
                       "ACCELERATE_SERVE_DRAFT", "early_exit:2"),
                   help="draft policy when --spec-k > 0 (env "
                   "ACCELERATE_SERVE_DRAFT): 'early_exit:N' runs the "
                   "target's own first N layers as the draft, sharing the "
                   "target's paged pool — no second cache, no extra "
                   "weights resident")
    try:
        flight_default = int(
            os.environ.get("ACCELERATE_SERVE_FLIGHT_HISTORY", "256") or 256
        )
    except ValueError:
        print(
            "accelerate-tpu: ignoring malformed ACCELERATE_SERVE_FLIGHT_HISTORY="
            f"{os.environ['ACCELERATE_SERVE_FLIGHT_HISTORY']!r} (want an integer)",
            file=sys.stderr,
        )
        flight_default = 256
    p.add_argument("--flight-history", type=int, default=flight_default,
                   help="per-iteration flight recorder ring size (default "
                   "256; 0 disables; env ACCELERATE_SERVE_FLIGHT_HISTORY): "
                   "host-vs-device phase attribution behind "
                   "stats()['host_fraction'], `trace tail --iterations`, "
                   "GET /profile, and HANG_REPORT flight tails")
    try:
        stats_default = int(
            os.environ.get("ACCELERATE_SERVE_STATS_INTERVAL", "32") or 32
        )
    except ValueError:
        print(
            "accelerate-tpu: ignoring malformed ACCELERATE_SERVE_STATS_INTERVAL="
            f"{os.environ['ACCELERATE_SERVE_STATS_INTERVAL']!r} (want an integer)",
            file=sys.stderr,
        )
        stats_default = 32
    p.add_argument("--stats-interval", type=int, default=stats_default,
                   help="emit a telemetry kind=\"step\" row (windowed "
                   "throughput, cumulative counters, the usage-ledger "
                   "snapshot) every N engine iterations (default 32; 0 "
                   "disables; env ACCELERATE_SERVE_STATS_INTERVAL)")
    try:
        logprobs_default = int(
            os.environ.get("ACCELERATE_SERVE_LOGPROBS_TOPN", "0") or 0
        )
    except ValueError:
        print(
            "accelerate-tpu: ignoring malformed ACCELERATE_SERVE_LOGPROBS_TOPN="
            f"{os.environ['ACCELERATE_SERVE_LOGPROBS_TOPN']!r} (want an integer)",
            file=sys.stderr,
        )
        logprobs_default = 0
    p.add_argument("--logprobs-topn", type=int, default=logprobs_default,
                   help="top-N per-step logprobs harvest ceiling (default 0 "
                   "= disabled; env ACCELERATE_SERVE_LOGPROBS_TOPN): the "
                   "harvest shape is static engine geometry, so requests opt "
                   "in UP TO this cap via the OpenAI 'logprobs' field; "
                   "unsupported with --spec-k > 0")
    p.add_argument(
        "--sync-engine", action="store_true",
        default=os.environ.get("ACCELERATE_SYNC_ENGINE", "") not in ("", "0"),
        help="disable double-buffered dispatch and run the synchronous "
        "step loop (schedule, dispatch, blocking harvest every "
        "iteration; env ACCELERATE_SYNC_ENGINE=1): escape hatch for "
        "A/B timing and for triaging suspected overlap bugs — tokens "
        "are identical either way, only the host-hiding differs")
    p.add_argument("--eos-token-id", type=int, default=None)
    p.add_argument("--temperature", type=float, default=None,
                   help="default sampling temperature when a request sends no "
                   "per-request params (default: greedy; per-request "
                   "temperature always wins)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--mesh", action="store_true",
                   help="shard the engine over the attached mesh "
                   "(ACCELERATE_MESH_* env vars declare the shape)")
    p.add_argument("--replica-id", type=int, default=None,
                   help="identity stamped on /healthz when running behind "
                   "`accelerate-tpu route`")
    p.add_argument("--http", type=int, default=None, metavar="PORT",
                   help="serve a local HTTP endpoint instead of stdin JSONL")
    p.add_argument("--logging-dir", default=None,
                   help="enable telemetry here (accelerate-tpu monitor shows "
                   "serving health)")
    p.set_defaults(func=serve_command)
    return p
