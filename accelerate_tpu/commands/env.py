"""``accelerate-tpu env`` — platform report for bug reports (reference
``commands/env.py:47``)."""

from __future__ import annotations

import os
import platform

from .config import ClusterConfig, default_json_config_file, default_yaml_config_file


def env_command(args) -> int:
    import jax

    import accelerate_tpu

    from accelerate_tpu.utils.environment import parse_flag_from_env

    info = {
        "`accelerate_tpu` version": accelerate_tpu.__version__,
        "Platform": platform.platform(),
        "Python version": platform.python_version(),
        "jax version": jax.__version__,
        "Backend": jax.default_backend(),
        "Device count": jax.device_count(),
        "Device kind": jax.devices()[0].device_kind if jax.devices() else "none",
        "Process count": jax.process_count(),
        "Telemetry": (
            "active (ACCELERATE_TELEMETRY=1)"
            if parse_flag_from_env("ACCELERATE_TELEMETRY")
            else "inactive (set ACCELERATE_TELEMETRY=1 or Accelerator(telemetry=True))"
        ),
        "Fault tolerance": (
            "active (ACCELERATE_FAULT_TOLERANCE=1)"
            if parse_flag_from_env("ACCELERATE_FAULT_TOLERANCE")
            else "inactive (set ACCELERATE_FAULT_TOLERANCE=1 or "
            "Accelerator(fault_tolerance=FaultTolerancePlugin(...)))"
        ),
        "Auto-resume": (
            "active (ACCELERATE_AUTO_RESUME)"
            if parse_flag_from_env("ACCELERATE_AUTO_RESUME")
            else "inactive (set ACCELERATE_AUTO_RESUME=1 or launch --auto-resume)"
        ),
        "Diagnostics": (
            "active (ACCELERATE_DIAGNOSTICS=1)"
            if parse_flag_from_env("ACCELERATE_DIAGNOSTICS")
            else "inactive (set ACCELERATE_DIAGNOSTICS=1 or "
            "Accelerator(diagnostics=True) for tracing + hang watchdog)"
        ),
        "Sanitizer": (
            "active (ACCELERATE_SANITIZE=1)"
            if parse_flag_from_env("ACCELERATE_SANITIZE")
            else "inactive (set ACCELERATE_SANITIZE=1 or "
            "Accelerator(sanitize=True) for recompile naming, donation "
            "report, collective digests, NaN loss probe; static pass: "
            "`accelerate-tpu lint <paths>`)"
        ),
        "LockWatch": (
            "active (ACCELERATE_SANITIZE=1): serving locks are wrapped, "
            "lock-order inversions dump RACE_REPORT_<host>.json"
            if parse_flag_from_env("ACCELERATE_SANITIZE")
            else "inactive (set ACCELERATE_SANITIZE=1 for the runtime "
            "lock-order sanitizer; static pass: `accelerate-tpu "
            "race-check <paths>`)"
        ),
        "Metrics": (
            "active (ACCELERATE_METRICS=1)"
            if parse_flag_from_env("ACCELERATE_METRICS")
            else "inactive (set ACCELERATE_METRICS=1 for an in-process "
            "OpenMetrics registry, or run `accelerate-tpu metrics export "
            "<logging_dir>` as a sidecar)"
        ),
    }
    try:
        import flax

        info["flax version"] = flax.__version__
    except ImportError:
        pass
    try:
        import optax

        info["optax version"] = optax.__version__
    except ImportError:
        pass

    config_path = None
    for candidate in (default_yaml_config_file, default_json_config_file):
        if os.path.exists(candidate):
            config_path = candidate
            break
    if config_path:
        info["Default config"] = ClusterConfig.load(config_path).to_dict()
    else:
        info["Default config"] = "not found"
    accelerate_env = {k: v for k, v in os.environ.items() if k.startswith("ACCELERATE_")}
    if accelerate_env:
        info["ACCELERATE_* env"] = accelerate_env

    print("\nCopy-and-paste the text below in your GitHub issue\n")
    for k, v in info.items():
        print(f"- {k}: {v}")
    return 0


def add_parser(subparsers):
    p = subparsers.add_parser("env", help="Print environment information")
    p.set_defaults(func=env_command)
    return p
