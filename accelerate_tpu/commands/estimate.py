"""``accelerate-tpu estimate-memory`` — dtype-wise model memory report
(reference ``commands/estimate.py``: meta-load from Hub → size table).

Zero-egress TPU build: models come from (a) the built-in zoo by name
(``llama2-7b`` …), (b) a local HF-style config.json, or (c) a local
checkpoint (``*.safetensors`` / sharded index) whose tensor shapes are read
from headers without loading data — the ``init_empty_weights`` analog.
"""

from __future__ import annotations

import json
import os

from ..utils.other import convert_bytes

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "int8": 1, "int4": 0.5}


def _sizes_from_zoo(name: str):
    from ..models import MODEL_ZOO

    entry = MODEL_ZOO.get(name.lower())
    if entry is None:
        return None
    config, factory = entry
    return factory_shapes(factory, config)


def factory_shapes(factory, config):
    """eval_shape the param tree — zero memory, any size."""
    import jax

    from ..big_modeling import init_empty_weights

    with init_empty_weights():
        model = factory(config)
    flat = jax.tree_util.tree_flatten_with_path(model.params)[0]
    out = {}
    for path, leaf in flat:
        key = ".".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        out[key] = tuple(leaf.shape)
    return out


def _sizes_from_safetensors(path: str) -> dict[str, tuple]:
    """Read tensor shapes from safetensors header(s) without loading data."""
    import struct

    def header(fp):
        with open(fp, "rb") as f:
            n = struct.unpack("<Q", f.read(8))[0]
            meta = json.loads(f.read(n))
        meta.pop("__metadata__", None)
        return {k: tuple(v["shape"]) for k, v in meta.items()}

    if os.path.isdir(path):
        index = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index):
            with open(index) as f:
                files = sorted(set(json.load(f)["weight_map"].values()))
            out = {}
            for fn in files:
                out.update(header(os.path.join(path, fn)))
            return out
        out = {}
        for fn in sorted(os.listdir(path)):
            if fn.endswith(".safetensors"):
                out.update(header(os.path.join(path, fn)))
        if out:
            return out
        raise FileNotFoundError(f"no safetensors found under {path}")
    return header(path)


def _param_count(shapes: dict[str, tuple]) -> tuple[int, int]:
    import numpy as np

    total = 0
    largest = 0
    for shape in shapes.values():
        n = int(np.prod(shape)) if shape else 1
        total += n
        largest = max(largest, n)
    return total, largest


_human = convert_bytes


def estimate_command(args) -> int:
    name = args.model_name
    shapes = None
    if os.path.exists(name):
        if name.endswith(".json"):
            from ..models import config_from_hf_json, model_factory_for_config

            config = config_from_hf_json(name)
            shapes = factory_shapes(model_factory_for_config(config), config)
        else:
            shapes = _sizes_from_safetensors(name)
    else:
        shapes = _sizes_from_zoo(name)
    if shapes is None:
        raise ValueError(
            f"unknown model {name!r}: pass a zoo name, a config.json, or a "
            "safetensors checkpoint path"
        )

    total, largest = _param_count(shapes)
    dtypes = args.dtypes or ["float32", "bfloat16", "int8", "int4"]
    rows = []
    for dt in dtypes:
        b = _DTYPE_BYTES[dt]
        # training: params + grads + adam m/v in fp32 (the TPU recipe:
        # bf16 compute, fp32 master+moments)
        train = total * (b + b + 8)
        rows.append((dt, _human(largest * b), _human(total * b), _human(train)))

    width = max(len(r[2]) for r in rows) + 2
    print(f"Model: {name}  —  {total/1e9:.2f}B params, {len(shapes)} tensors")
    print(f"{'dtype':>10} | {'largest layer':>14} | {'inference':>{width}} | {'training (adam)':>16}")
    print("-" * (50 + width))
    for dt, lg, inf, train in rows:
        print(f"{dt:>10} | {lg:>14} | {inf:>{width}} | {train:>16}")
    return 0


def add_parser(subparsers):
    p = subparsers.add_parser(
        "estimate-memory", help="Estimate model memory per dtype"
    )
    p.add_argument("model_name", help="zoo name / config.json / checkpoint path")
    p.add_argument("--dtypes", nargs="+", default=None, choices=list(_DTYPE_BYTES))
    p.set_defaults(func=estimate_command)
    return p
