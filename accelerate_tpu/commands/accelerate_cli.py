"""CLI root: subcommand registry (reference ``commands/accelerate_cli.py``)."""

from __future__ import annotations

import argparse
import sys

from . import (
    config, env, estimate, launch, lint, merge, metrics, monitor, profile,
    racecheck, route, serve, shardcheck, slo, test, tpu, usage,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        "accelerate-tpu",
        usage="accelerate-tpu <command> [<args>]",
        allow_abbrev=False,
    )
    subparsers = parser.add_subparsers(dest="command")
    for module in (config, env, launch, test, estimate, lint, merge, metrics, monitor, profile, racecheck, route, serve, shardcheck, slo, tpu, usage):
        module.add_parser(subparsers)

    args = parser.parse_args(argv)
    if not hasattr(args, "func"):
        parser.print_help()
        return 1
    return args.func(args) or 0


if __name__ == "__main__":
    sys.exit(main())
