"""``accelerate-tpu metrics`` — the scrape surface over a run's logging dir.

``metrics export <logging_dir>`` runs the sidecar exporter: it tails the
telemetry JSONL segments and trace trails the training (or serving) job
writes, aggregates them into OpenMetrics, and answers ``GET /metrics`` on
a local port — a Prometheus scrape target for a job that embeds no HTTP
server. Like ``monitor``, it never talks to the job: pure file reads, so
it runs next to the job, on a login host over a shared filesystem, or
post-mortem. ``--once`` prints one exposition to stdout instead (pipe it,
diff it, or use the exit code: 3 when an ``ACCELERATE_SLO_*`` rule fires,
0 otherwise — the same contract as ``monitor --once``).

No jax import anywhere on this path — the sidecar must run on a CPU-only
probe box.
"""

from __future__ import annotations

import os
import sys


def metrics_export_command(args) -> int:
    from ..metrics.alerts import EXIT_SLO_VIOLATION
    from ..metrics.exporter import LoggingDirExporter, serve_exporter

    logging_dir = args.logging_dir
    if not os.path.isdir(logging_dir):
        print(f"metrics export: {logging_dir} is not a directory", file=sys.stderr)
        return 1
    exporter = LoggingDirExporter(logging_dir)
    if args.once:
        firing = exporter.refresh()
        sys.stdout.write(exporter.render())
        for alert in firing:
            print(
                f"SLO {alert['rule']}: observed {alert['observed']:.4g} vs "
                f"threshold {alert['threshold']:.4g} ({alert['env']})",
                file=sys.stderr,
            )
        return EXIT_SLO_VIOLATION if firing else 0

    server = serve_exporter(
        exporter, args.port, host=args.host, min_refresh_seconds=args.min_refresh
    )
    bound_port = server.server_address[1]
    print(
        f"exporting {logging_dir} on http://{args.host}:{bound_port}/metrics "
        f"(scrape-triggered refresh, min {args.min_refresh:g}s; /healthz for "
        f"liveness)",
        file=sys.stderr,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
    return 0


def add_parser(subparsers):
    metrics = subparsers.add_parser(
        "metrics", help="OpenMetrics export of a run's logging dir"
    )
    metrics_sub = metrics.add_subparsers(dest="metrics_command")
    export = metrics_sub.add_parser(
        "export",
        help="sidecar exporter: tail telemetry/trace files, serve GET /metrics",
    )
    export.add_argument("logging_dir", help="the run's logging/project dir")
    export.add_argument(
        "--port", type=int, default=9464,
        help="HTTP port (0 picks a free one; default mirrors the OTel "
        "Prometheus-exporter convention)",
    )
    export.add_argument("--host", default="127.0.0.1", help="bind address")
    export.add_argument(
        "--min-refresh", type=float, default=1.0,
        help="minimum seconds between file re-scans (scrapes inside the "
        "window serve the cached registry)",
    )
    export.add_argument(
        "--once", action="store_true",
        help="print one exposition to stdout and exit (exit 3 when an "
        "ACCELERATE_SLO_* rule fires, else 0)",
    )
    export.set_defaults(func=metrics_export_command)
    return metrics
