"""``accelerate-tpu slo`` — the scenario × objective scorecard.

``slo report <logging_dir>`` renders one run's (or a whole suite's) SLO
verdict from its trails alone: per-objective burn rates and pass/fail
from the windowed engine (:mod:`accelerate_tpu.metrics.slo`), the tail's
phase attribution with exemplar trace_ids (so a failing row links
straight into ``trace tail``/``trace merge``), and the supervisor's
``scale_decision`` rows — what the closed loop actually *did* about it.

Given a dir that is itself a traced run (it has a ``WORKLOAD.json``
manifest, or any trails at all) the scorecard has one scenario row; given
a suite dir whose immediate children are traced runs (``bench.py fleet``
lays scenarios out this way), one row per child. ``--json`` emits the
same scorecard machine-readably — the smoke pins that the two agree.

Pure file reads, no jax — like ``monitor``, it runs anywhere the logging
dir is visible.
"""

from __future__ import annotations

import json
import os
import sys

#: scorecard schema stamp on the --json output
REPORT_SCHEMA = 1


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            data = json.load(f)
        return data if isinstance(data, dict) else None
    except (OSError, json.JSONDecodeError):
        return None


def _scale_decisions(logging_dir: str, max_rows: int = 50) -> list[dict]:
    from ..diagnostics.monitor import _tail_jsonl

    path = os.path.join(logging_dir, "router", "replicas.jsonl")
    return [
        row
        for row in _tail_jsonl(path, max_records=2000)
        if row.get("kind") == "scale_decision"
    ][-max_rows:]


def scorecard_for_run(logging_dir: str) -> dict:
    """One scenario row: workload identity + windowed objective verdicts +
    tail attribution + exemplars + scaling decisions."""
    from ..diagnostics.reqtrace import tail_from_dir_throttled
    from ..metrics.slo import evaluate_from_dir
    from ..serving.workload import WORKLOAD_FILENAME

    manifest = _read_json(os.path.join(logging_dir, WORKLOAD_FILENAME)) or {}
    verdict = evaluate_from_dir(logging_dir)
    tail = tail_from_dir_throttled(logging_dir) or {}
    objectives = verdict["objectives"]
    firing = verdict["firing"]
    if not objectives:
        outcome = "unarmed"
    elif firing:
        outcome = "fail"
    elif all(o.get("burn_rate") is None for o in objectives.values()):
        outcome = "no-data"
    else:
        outcome = "pass"
    return {
        "dir": logging_dir,
        "scenario": manifest.get("scenario") or "(untraced)",
        "spec": manifest.get("spec"),
        "seed": manifest.get("seed"),
        "requests": manifest.get("requests"),
        "schedule_sha256": manifest.get("schedule_sha256"),
        "objectives": objectives,
        "firing": firing,
        "verdict": outcome,
        "attribution": tail.get("attribution") or {},
        "exemplar_trace_ids": [
            t["trace_id"] for t in (tail.get("tail") or [])[:3] if t.get("trace_id")
        ],
        "scale_decisions": _scale_decisions(logging_dir)[-10:],
    }


def build_report(logging_dir: str) -> dict:
    """The full scorecard: the dir itself when it is a traced run, else
    every immediate child that is one (a ``bench.py fleet`` suite dir)."""
    from ..serving.workload import WORKLOAD_FILENAME

    def is_run(d: str) -> bool:
        return (
            os.path.exists(os.path.join(d, WORKLOAD_FILENAME))
            or os.path.isdir(os.path.join(d, "router"))
            or os.path.isdir(os.path.join(d, "traces"))
            or os.path.isdir(os.path.join(d, "telemetry"))
        )

    runs = []
    if is_run(logging_dir):
        runs.append(logging_dir)
    else:
        for name in sorted(os.listdir(logging_dir)):
            child = os.path.join(logging_dir, name)
            if os.path.isdir(child) and is_run(child):
                runs.append(child)
    scenarios = [scorecard_for_run(d) for d in runs]
    return {
        "schema": REPORT_SCHEMA,
        "logging_dir": logging_dir,
        "scenarios": scenarios,
        "pass": bool(scenarios)
        and all(s["verdict"] in ("pass", "unarmed", "no-data") for s in scenarios),
    }


def render_report(report: dict) -> str:
    lines = [f"accelerate-tpu slo report — {report['logging_dir']}"]
    if not report["scenarios"]:
        lines.append("  no traced runs found (nothing with trails or WORKLOAD.json)")
        return "\n".join(lines)
    for s in report["scenarios"]:
        spec = f" [{s['spec']}]" if s.get("spec") else ""
        head = f"  scenario {s['scenario']}{spec}: {s['verdict'].upper()}"
        if s.get("requests") is not None:
            head += f"  ({s['requests']} scheduled requests)"
        if s.get("schedule_sha256"):
            head += f"  schedule {s['schedule_sha256'][:12]}"
        lines.append(head)
        firing_names = {f["rule"] for f in s["firing"]}
        for name, o in s["objectives"].items():
            def fmt(v, p="{:.2f}"):
                return "-" if v is None else p.format(v)

            mark = "FAIL" if name in firing_names else (
                "pass" if o.get("burn_rate") is not None else "no-data"
            )
            lines.append(
                f"    {name:<24} {mark:<8} "
                f"burn {fmt(o.get('burn_rate'))}x "
                f"(long {fmt(o.get('burn_rate_long'))}x)  "
                f"budget {fmt(o.get('budget_remaining'))}  "
                f"observed {fmt(o.get('observed'), '{:.4g}')}  "
                f"window {o.get('window_s'):.0f}s"
            )
        if not s["objectives"]:
            lines.append(
                "    no objectives armed (set ACCELERATE_SLO_* to arm)"
            )
        if s["attribution"]:
            attribution = "   ".join(
                f"{phase} {pct:.0f}%"
                for phase, pct in sorted(
                    s["attribution"].items(), key=lambda kv: -kv[1]
                )
                if pct >= 0.5
            )
            lines.append(f"    tail attribution: {attribution}")
        if s["exemplar_trace_ids"]:
            lines.append(
                "    exemplar trace_ids: " + ", ".join(s["exemplar_trace_ids"])
            )
        for d in s["scale_decisions"][-3:]:
            evidence = ""
            if d.get("objective"):
                burn = d.get("burn_rate")
                evidence = (
                    f"  [{d['objective']} burn "
                    f"{'-' if burn is None else format(burn, '.2f')}x, "
                    f"phase {d.get('dominant_phase') or '?'}]"
                )
            lines.append(
                f"    decision: {d.get('action')} ({d.get('reason')})"
                f"  queue {d.get('queue_depth')}"
                f"  ready {d.get('ready_replicas')}" + evidence
            )
    lines.append(f"  overall: {'PASS' if report['pass'] else 'FAIL'}")
    return "\n".join(lines)


def slo_report_command(args) -> int:
    if not os.path.isdir(args.logging_dir):
        print(f"slo report: {args.logging_dir} is not a directory", file=sys.stderr)
        return 1
    report = build_report(args.logging_dir)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render_report(report))
    return 0


def add_parser(subparsers):
    p = subparsers.add_parser(
        "slo", help="Windowed SLO scorecards over a run's logging dir"
    )
    sub = p.add_subparsers(dest="slo_command")
    report = sub.add_parser(
        "report",
        help="scenario × objective scorecard: burn rates, pass/fail, tail "
        "attribution, exemplar trace_ids, and the supervisor's scale "
        "decisions — from the trails alone",
    )
    report.add_argument(
        "logging_dir",
        help="a traced run's logging dir, or a suite dir whose children are "
        "traced runs (bench.py fleet layout)",
    )
    report.add_argument("--json", action="store_true",
                        help="machine-readable scorecard instead of the table")
    report.set_defaults(func=slo_report_command)
    return p
