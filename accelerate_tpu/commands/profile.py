"""``accelerate-tpu profile`` — trigger an on-demand profiler window on a
live serving engine (or a whole routed fleet) from the outside.

The serve front end exposes ``GET /profile?seconds=N``: the replica runs a
``jax.profiler`` capture for N seconds *while it keeps serving* and dumps
the flight-recorder iterations that landed inside the window, both under
its ``logging_dir/profiles/``. This command is the client side:

* ``accelerate-tpu profile http://127.0.0.1:8400 --seconds 2`` hits one
  replica directly;
* ``accelerate-tpu profile <logging_dir> --seconds 2`` reads the router's
  fleet trail (``router/replicas.jsonl``) and fans the trigger out to
  EVERY live replica concurrently — the captures share one wall-clock
  window, so the per-replica timelines line up when compared.

Artifacts are discovered afterwards by ``accelerate-tpu trace merge``
(which lists ``profiles/profile_*`` directories beside the merged
timeline). This module never imports jax — it runs from any host that can
reach the replicas' HTTP ports.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import urllib.request

#: replica states worth profiling — a `dead`/`terminated` row's base_url
#: points at nothing, and `draining` replicas are on their way out
_LIVE_STATES = frozenset(("ready", "starting", "draining"))


def discover_replica_urls(logging_dir: str) -> list[str]:
    """Live replicas' base URLs from the router's fleet trail — newest row
    per replica identity wins (a respawned replica's fresh ``ready`` row
    supersedes its predecessor's ``dead`` one)."""
    trail = os.path.join(logging_dir, "router", "replicas.jsonl")
    if not os.path.exists(trail):
        return []
    latest: dict[int, dict] = {}
    try:
        with open(trail) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                rid = row.get("replica_id")
                if rid is None:  # aggregate kind="router" totals row
                    continue
                latest[rid] = row
    except OSError:
        return []
    return [
        str(row["base_url"]).rstrip("/")
        for _rid, row in sorted(latest.items())
        if row.get("base_url") and row.get("state") in _LIVE_STATES
    ]


def _profile_one(url: str, seconds: float, timeout: float) -> dict:
    """One replica's ``GET /profile`` round trip; error dicts, never
    raises (a fleet fan-out must report per-replica outcomes)."""
    target = f"{url}/profile?seconds={seconds:g}"
    try:
        with urllib.request.urlopen(target, timeout=timeout) as resp:
            return {"url": url, "ok": True, **json.loads(resp.read())}
    except Exception as e:  # noqa: BLE001 — per-replica outcome, not fatal
        return {"url": url, "ok": False, "error": str(e)}


def profile_fleet(urls: list[str], seconds: float) -> list[dict]:
    """Fan the capture out to every URL concurrently so all replicas
    profile the SAME wall-clock window (sequential triggers would capture
    disjoint slices of fleet time)."""
    timeout = seconds + 30.0
    results: list[dict | None] = [None] * len(urls)

    def run(i: int, url: str):
        results[i] = _profile_one(url, seconds, timeout)

    threads = [
        threading.Thread(target=run, args=(i, url), daemon=True)
        for i, url in enumerate(urls)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout + 30.0)
    return [
        r if r is not None else {"url": urls[i], "ok": False, "error": "timed out"}
        for i, r in enumerate(results)
    ]


def profile_command(args) -> int:
    target = args.target
    if target.startswith(("http://", "https://")):
        urls = [target.rstrip("/")]
    else:
        if not os.path.isdir(target):
            print(f"profile: {target} is not a directory or URL", file=sys.stderr)
            return 1
        urls = discover_replica_urls(target)
        if not urls:
            print(
                f"profile: no live replicas in {target}/router/replicas.jsonl "
                "— is `accelerate-tpu route --logging-dir` running? (or pass "
                "a replica URL directly)",
                file=sys.stderr,
            )
            return 1
    results = profile_fleet(urls, args.seconds)
    if args.json:
        print(json.dumps(results, indent=2))
    else:
        for r in results:
            if r["ok"]:
                print(
                    f"{r['url']}: {r.get('flight_iterations', 0)} iteration(s) "
                    f"in {r.get('seconds', 0.0):.2f}s window"
                    + (
                        f", host fraction {r['host_fraction']:.1%}"
                        if r.get("host_fraction") is not None
                        else ""
                    )
                    + f" -> {r.get('profile_dir')}"
                )
            else:
                print(f"{r['url']}: FAILED — {r.get('error')}")
    failed = sum(1 for r in results if not r["ok"])
    if failed:
        print(
            f"profile: {failed}/{len(results)} replica(s) failed",
            file=sys.stderr,
        )
    return 1 if failed == len(results) else 0


def add_parser(subparsers):
    p = subparsers.add_parser(
        "profile",
        help="Trigger an on-demand jax-profiler + flight-recorder window on "
        "a live serving engine (URL) or every replica of a routed fleet "
        "(logging dir)",
    )
    p.add_argument(
        "target",
        help="a replica base URL (http://host:port) or a routed fleet's "
        "logging dir (replicas discovered from router/replicas.jsonl)",
    )
    p.add_argument(
        "--seconds", type=float, default=2.0,
        help="capture window length (default 2.0; server clamps to "
        "0.05-120)",
    )
    p.add_argument("--json", action="store_true",
                   help="machine-readable per-replica manifests")
    p.set_defaults(func=profile_command)
    return p
