"""``accelerate-tpu merge-weights`` — merge a sharded checkpoint into one
consolidated safetensors file (reference ``commands/merge.py`` +
``utils/fsdp_utils.py:247-329``).

In the TPU build there are no per-rank FSDP shard files — checkpoints are
already name→array shards split only by size (``model.safetensors`` +
optional numbered shards + index). Merging = read every shard, write one
file (or one consolidated set under ``--max_shard_size``).
"""

from __future__ import annotations

import json
import os


def merge_command(args) -> int:
    from ..checkpointing import load_array_dict, save_array_dict

    src = args.checkpoint_dir
    flat = {}
    if os.path.isdir(src):
        index = os.path.join(src, "model.safetensors.index.json")
        if os.path.exists(index):
            with open(index) as f:
                files = sorted(set(json.load(f)["weight_map"].values()))
        else:
            files = sorted(
                fn for fn in os.listdir(src)
                if fn.endswith((".safetensors", ".npz")) and fn.startswith("model")
            )
        if not files:
            raise FileNotFoundError(f"no model shards found in {src}")
        for fn in files:
            flat.update(load_array_dict(os.path.join(src, fn)))
    else:
        flat.update(load_array_dict(src))

    out_dir = args.output_path
    os.makedirs(out_dir, exist_ok=True)
    out_file = os.path.join(out_dir, "model.safetensors")
    written = save_array_dict(flat, out_file, safe_serialization=not args.unsafe_serialization)
    print(f"merged {len(flat)} tensors -> {written}")
    return 0


def add_parser(subparsers):
    p = subparsers.add_parser(
        "merge-weights", help="Merge sharded checkpoint into one file"
    )
    p.add_argument("checkpoint_dir", help="directory (or file) holding the shards")
    p.add_argument("output_path", help="directory to write the merged model into")
    p.add_argument("--unsafe_serialization", action="store_true", help="write .npz instead")
    p.set_defaults(func=merge_command)
    return p
