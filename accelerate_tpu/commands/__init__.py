"""The ``accelerate-tpu`` CLI (reference: ``src/accelerate/commands/``).

Subcommands: config, env, launch, test, estimate-memory, merge-weights,
tpu-config — same verbs as the reference CLI, with a ``jax_tpu`` compute
environment instead of torchrun/xmp process spawning (one process drives all
local chips; multi-host = same command per host + coordinator env vars).
"""
