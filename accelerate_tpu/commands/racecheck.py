"""``accelerate-tpu race-check`` — the static concurrency-analysis pass.

Checks threaded code (the serving fleet, the metrics exporter, the
diagnostics watchdog — anything holding locks) for the defect classes
that reviewer vigilance keeps missing: lock-guarded attributes touched
without the lock, lock-order inversions, blocking calls under a lock,
Condition misuse, half-built objects visible to early-started threads,
and callbacks invoked with a lock held. Rule catalogue RC001…RC006:
``accelerate_tpu/analysis/concurrency.py`` (docs:
``usage_guides/linting.md``, "Concurrency rules").

Exit codes (consistent with ``lint`` and ``monitor --once``):

* ``0`` — clean, or warnings only
* ``1`` — usage error (no such path, unknown rule id)
* ``2`` — at least one **error**-severity finding

The runtime half of the pass is **LockWatch**
(``accelerate_tpu/analysis/lockwatch.py``): armed via
``ACCELERATE_SANITIZE=1``, it wraps the serving fleet's locks, keeps the
real acquisition-order graph per thread, and dumps
``RACE_REPORT_<host>.json`` (both stacks named) the moment an
order-inverting acquisition happens — including through the bare
``.acquire()`` paths the static pass cannot see.
"""

from __future__ import annotations

import json
import os
import sys


def race_check_command(args) -> int:
    from ..analysis.concurrency import RC_RULES, race_check_paths
    from ..analysis.engine import normalize_rule_ids

    if args.list_rules:
        for rule in RC_RULES.values():
            print(f"{rule.id}  [{rule.severity:7s}] {rule.summary}")
        return 0

    for path in args.paths:
        if not os.path.exists(path):
            print(f"race-check: no such path: {path}", file=sys.stderr)
            return 1
    if not args.paths:
        print(
            "race-check: no paths given (try `accelerate-tpu race-check "
            "accelerate_tpu/serving`)",
            file=sys.stderr,
        )
        return 1

    try:
        select = normalize_rule_ids(args.select, catalogue=RC_RULES, prefix="RC")
        ignore = normalize_rule_ids(args.ignore, catalogue=RC_RULES, prefix="RC")
    except ValueError as e:
        print(f"race-check: {e}", file=sys.stderr)
        return 1

    findings, files_scanned = race_check_paths(args.paths, select=select, ignore=ignore)
    errors = [f for f in findings if f.severity == "error"]
    warnings = [f for f in findings if f.severity != "error"]

    if args.json:
        print(
            json.dumps(
                {
                    "files_scanned": files_scanned,
                    "errors": len(errors),
                    "warnings": len(warnings),
                    "findings": [f.to_dict() for f in findings],
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        print(
            f"race-check: {files_scanned} file(s) scanned — "
            f"{len(errors)} error(s), {len(warnings)} warning(s)"
        )
    return 2 if errors else 0


def add_parser(subparsers):
    p = subparsers.add_parser(
        "race-check",
        help="Static concurrency analysis (guarded-by violations, lock-order "
        "inversions, blocking calls under locks)",
    )
    p.add_argument("paths", nargs="*", help="files or directories to check")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--select",
        default=None,
        help="comma-separated rule IDs to run exclusively (e.g. RC001,RC002)",
    )
    p.add_argument(
        "--ignore",
        default=None,
        help="comma-separated rule IDs to skip",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalogue and exit",
    )
    p.set_defaults(func=race_check_command)
    return p
