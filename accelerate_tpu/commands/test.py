"""``accelerate-tpu test`` — run the bundled assertion script through the
launcher as a smoke test (reference ``commands/test.py:22-57``)."""

from __future__ import annotations

import os


#: the bundled assertion-script suite (reference ships test_script plus
#: test_ops/test_sync/test_distributed_data_loop/test_merge_weights under
#: the same dir)
ALL_SCRIPTS = (
    "test_script.py", "test_ops.py", "test_sync.py", "test_data_loop.py",
    "test_merge_weights.py", "test_notebook.py", "test_performance.py",
)


def test_command(args) -> int:
    from ..test_utils import scripts

    from .launch import launch_command, launch_command_parser

    names = ALL_SCRIPTS if getattr(args, "all", False) else ("test_script.py",)
    parser = launch_command_parser()
    forwarded = ["--num_cpu_devices", str(args.num_cpu_devices)] if args.num_cpu_devices else []
    for name in names:
        script = os.path.join(os.path.dirname(scripts.__file__), name)
        largs = parser.parse_args([*forwarded, script])
        try:
            launch_command(largs)  # raises on a nonzero child exit
        except RuntimeError as e:
            print(f"FAILED: {name}: {e}")
            return 1
    print("Test is a success! You are ready for your distributed training!")
    return 0


def add_parser(subparsers):
    p = subparsers.add_parser("test", help="Run the bundled distributed smoke test")
    p.add_argument(
        "--num_cpu_devices", type=int, default=0,
        help="run on a virtual CPU mesh of this many devices",
    )
    p.add_argument(
        "--all", action="store_true",
        help="run the full assertion-script suite (ops/sync/data-loop too)",
    )
    p.set_defaults(func=test_command)
    return p
