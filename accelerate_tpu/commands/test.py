"""``accelerate-tpu test`` — run the bundled assertion script through the
launcher as a smoke test (reference ``commands/test.py:22-57``)."""

from __future__ import annotations

import os


def test_command(args) -> int:
    from ..test_utils import scripts

    script = os.path.join(os.path.dirname(scripts.__file__), "test_script.py")

    from .launch import launch_command, launch_command_parser

    parser = launch_command_parser()
    forwarded = ["--num_cpu_devices", str(args.num_cpu_devices)] if args.num_cpu_devices else []
    largs = parser.parse_args([*forwarded, script])
    rc = launch_command(largs)
    if rc == 0:
        print("Test is a success! You are ready for your distributed training!")
    return rc


def add_parser(subparsers):
    p = subparsers.add_parser("test", help="Run the bundled distributed smoke test")
    p.add_argument(
        "--num_cpu_devices", type=int, default=0,
        help="run on a virtual CPU mesh of this many devices",
    )
    p.set_defaults(func=test_command)
    return p
