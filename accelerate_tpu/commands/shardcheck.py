"""``accelerate-tpu shard-check`` — static sharding-plan pre-flight.

Given a model shape, a mesh (real devices via ``--mesh``, or a virtual
axis declaration via ``--virtual dp,fsdp,tp`` — no devices touched), and
the partition rules from ``parallel/sharding.py``, statically compute the
per-device HBM footprint (params, optimizer state, paged KV block pool,
optional gradient/activation estimate) and emit SP001-SP006 findings —
the planning questions you otherwise answer by OOMing on the TPU.

Exit codes mirror ``lint``:

* ``0`` — clean, or warnings only
* ``1`` — usage error (bad mesh spec, unknown finding id, missing file)
* ``2`` — at least one **error**-severity finding (dead rule, forced
  replication, non-divisible axis, over-budget HBM)

The runtime twins: ``serve --hbm-gb`` arms the engine's refuse-to-start
pre-flight, ``serve --auto-blocks`` sizes the block pool from the same
model, and the sanitizer stamps predicted-vs-actual arg bytes onto
compile facts.
"""

from __future__ import annotations

import json
import os
import sys


def _parse_extra_rule(raw: str):
    """``"regex=axis,axis"`` → ``(regex, PartitionSpec(...))``. Axis
    entries: a mesh axis name, ``None`` (keep dim unsharded), or
    ``a+b`` for a multi-axis entry. ``"regex="`` forces replication."""
    from jax.sharding import PartitionSpec as P

    pattern, sep, spec_str = raw.partition("=")
    if not sep:
        raise ValueError(
            f"--extra-rule needs regex=spec (e.g. 'embed_tokens=tp,fsdp'), got {raw!r}"
        )
    entries = []
    for part in spec_str.split(","):
        part = part.strip()
        if not part or part.lower() == "none":
            entries.append(None)
        elif "+" in part:
            entries.append(tuple(p.strip() for p in part.split("+")))
        else:
            entries.append(part)
    if entries == [None]:
        entries = []
    return pattern, P(*entries)


def _build_abstract(args):
    """(abstract params, model config, partition rules) for the preset —
    ``jax.eval_shape`` only: no weights materialize, no device is used."""
    import jax
    import jax.numpy as jnp

    from ..models.llama import (
        LLAMA_PARTITION_RULES,
        LlamaConfig,
        init_llama_params,
    )

    presets = {
        "tiny": lambda: LlamaConfig.tiny(),
        "flagship": lambda: LlamaConfig.flagship_700m(),
        "llama2-7b": lambda: LlamaConfig.llama2_7b(),
    }
    config = presets[args.preset]()
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    params = jax.eval_shape(
        lambda key: init_llama_params(key, config, dtype=dtype),
        jax.random.PRNGKey(0),
    )
    return params, config, list(LLAMA_PARTITION_RULES)


def shard_check_command(args) -> int:
    from ..analysis.shardplan import (
        SP_RULES,
        analyze_plan,
        manifest_findings,
        mesh_sizes_of,
        normalize_sp_ids,
        parse_mesh_spec,
        resharding_findings,
    )

    if args.list_rules:
        for rule in SP_RULES.values():
            print(f"{rule.id}  [{rule.severity:7s}] {rule.summary}")
        return 0

    try:
        select = normalize_sp_ids(args.select)
        ignore = normalize_sp_ids(args.ignore)
    except ValueError as e:
        print(f"shard-check: {e}", file=sys.stderr)
        return 1

    if args.mesh:
        from ..mesh import build_mesh

        mesh_sizes = mesh_sizes_of(build_mesh())
    else:
        try:
            mesh_sizes = parse_mesh_spec(args.virtual)
        except ValueError as e:
            print(f"shard-check: {e}", file=sys.stderr)
            return 1

    params, config, rules = _build_abstract(args)
    if args.extra_rule:
        try:
            extra = [_parse_extra_rule(raw) for raw in args.extra_rule]
        except ValueError as e:
            print(f"shard-check: {e}", file=sys.stderr)
            return 1
        rules = extra + rules  # prepended: extra rules take priority

    kv_pool = None
    if args.no_serve_pool and args.swap_gb:
        # the host tier's geometry comes from the serve pool's; pricing it
        # without that tier would be a guess — say so instead of silently
        # dropping an explicitly requested number from the pre-flight
        print(
            "shard-check: --swap-gb needs the serve-pool tier for its block "
            "geometry; ignored with --no-serve-pool",
            file=sys.stderr,
        )
    if not args.no_serve_pool:
        # kv_dtype policy: "auto" stores the pool in the params' compute
        # dtype; int8/fp8 price the 1-byte payload PLUS the f32 scale
        # arrays, matching the engine's live footprint byte-exactly
        # (kv_storage_name: the one mapping shared with serve --auto-blocks)
        from ..analysis.shardplan import kv_storage_name

        kv_dtype = kv_storage_name(
            args.kv_dtype, "float32" if args.dtype == "f32" else "bfloat16"
        )
        kv_pool = dict(
            num_layers=config.num_hidden_layers,
            num_kv_heads=config.num_key_value_heads,
            head_dim=config.head_dim,
            num_slots=args.num_slots,
            block_size=args.block_size,
            max_seq_len=min(args.max_seq_len, config.max_position_embeddings),
            num_blocks=args.num_blocks,
            dtype=kv_dtype,
        )
    draft_layers = None
    if args.spec_k:
        # the serving engine's speculative-decoding draft tier: parse with
        # the SAME parser EngineConfig uses, so shard-check refuses exactly
        # the drafts the engine would refuse at bring-up
        from ..serving.spec import parse_draft_spec

        try:
            draft_layers = parse_draft_spec(
                args.draft, config.num_hidden_layers
            ).layers
        except ValueError as e:
            print(f"shard-check: {e}", file=sys.stderr)
            return 1
    activations = None
    include_grads = False
    if args.batch:
        include_grads = True
        activations = dict(
            apply_fn=lambda p, **kw: _abstract_apply(config, p, **kw),
            params=params,
            batch=args.batch,
            seq=args.seq or config.max_position_embeddings,
            hidden=config.hidden_size,
            num_layers=config.num_hidden_layers,
            remat=bool(config.remat),
            dtype="float32" if args.dtype == "f32" else "bfloat16",
        )

    try:
        report = analyze_plan(
            params,
            mesh_sizes,
            rules=rules,
            optimizer=args.optimizer,
            kv_pool=kv_pool,
            activations=activations,
            include_grads=include_grads,
            hbm_gb=args.hbm_gb,
            swap_gb=args.swap_gb,
            replicated_threshold_bytes=int(args.replicated_threshold_mb * (1 << 20)),
            draft_layers=draft_layers,
        )
    except ValueError as e:
        print(f"shard-check: {e}", file=sys.stderr)
        return 1

    if args.hlo:
        if not os.path.exists(args.hlo):
            print(f"shard-check: no such HLO file: {args.hlo}", file=sys.stderr)
            return 1
        with open(args.hlo, encoding="utf-8", errors="replace") as f:
            report.findings.extend(
                resharding_findings(f.read(), label=os.path.basename(args.hlo))
            )
    if args.manifest:
        from ..resilience.manifest import read_manifest

        manifest = read_manifest(args.manifest)
        if manifest is None:
            print(
                f"shard-check: no readable manifest.json under {args.manifest}",
                file=sys.stderr,
            )
            return 1
        report.findings.extend(
            manifest_findings(manifest, [l for l in report.leaves if l.tier == "params"])
        )

    findings = [
        f
        for f in report.findings
        if (not select or f.rule in select) and (not ignore or f.rule not in ignore)
    ]
    report.findings = findings
    errors = [f for f in findings if f.severity == "error"]

    if args.json:
        payload = report.to_dict()
        if not args.leaves:
            payload.pop("leaves")
        print(json.dumps(payload, indent=2))
    else:
        gib = 1 << 30
        mesh_str = ", ".join(f"{ax}={n}" for ax, n in report.mesh.items() if n > 1) or "1 device"
        print(f"shard-check: {args.preset} over mesh ({mesh_str})")
        for tier, t in sorted(report.tiers.items(), key=lambda kv: -kv[1]["bytes_per_device"]):
            print(
                f"  {tier:12s} {t['bytes_per_device'] / gib:8.3f} GiB/device "
                f"(global {t['bytes_global'] / gib:.3f} GiB)"
            )
        total = report.bytes_per_device / gib
        budget = f" / budget {args.hbm_gb:.3f} GiB" if args.hbm_gb is not None else ""
        print(f"  {'TOTAL':12s} {total:8.3f} GiB/device{budget}")
        if report.host:
            print(
                f"  {'kv_swap':12s} {report.host['swap_pool_host_bytes'] / gib:8.3f} GiB "
                f"host DRAM ({report.host['swap_blocks']} blocks — excluded "
                "from the HBM budget)"
            )
        for f in findings:
            print(f.render())
        print(
            f"shard-check: {len(errors)} error(s), "
            f"{len(findings) - len(errors)} warning(s)"
        )
    return 2 if errors else 0


def _abstract_apply(config, params, **kw):
    from ..models.llama import llama_apply

    return llama_apply(config, params, **kw)


def add_parser(subparsers):
    p = subparsers.add_parser(
        "shard-check",
        help="Static sharding-plan pre-flight: per-device HBM tiers + "
        "SP001-SP006 findings before the job runs",
    )
    p.add_argument("--preset", choices=("tiny", "flagship", "llama2-7b"),
                   default="flagship", help="model shape to plan")
    p.add_argument("--dtype", choices=("f32", "bf16"), default="f32")
    p.add_argument("--virtual", default="1,1,1", metavar="DP,FSDP,TP",
                   help="virtual mesh axis sizes — positional dp,fsdp,tp or "
                   "named dp=1,fsdp=2,tp=2,cp=1; no devices needed")
    p.add_argument("--mesh", action="store_true",
                   help="plan over the attached mesh (ACCELERATE_MESH_* env "
                   "vars) instead of --virtual")
    p.add_argument("--optimizer", choices=("adam", "adamw", "sgd", "none"),
                   default="adam", help="optimizer whose state the plan prices")
    p.add_argument("--hbm-gb", type=float, default=None,
                   help="per-device HBM budget; exceeding it is an "
                   "error-severity SP004 finding (exit 2)")
    p.add_argument("--extra-rule", action="append", default=[],
                   metavar="REGEX=SPEC",
                   help="prepend a partition rule (takes priority), e.g. "
                   "'embed_tokens=tp,fsdp' or 'lm_head=' (force replicated); "
                   "repeatable")
    p.add_argument("--replicated-threshold-mb", type=float, default=16.0,
                   help="SP002 fires for replicated params at or above this size")
    # serving-pool tier (priced by default: the capacity question ROADMAP
    # item 3 asks is params + pool)
    p.add_argument("--num-slots", type=int, default=8)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-seq-len", type=int, default=512)
    p.add_argument("--num-blocks", type=int, default=None,
                   help="paged pool blocks (default: full residency)")
    p.add_argument("--no-serve-pool", action="store_true",
                   help="drop the paged KV pool tier (training-only plan)")
    p.add_argument("--kv-dtype", choices=("auto", "bf16", "f32", "int8", "fp8"),
                   default="auto",
                   help="KV pool storage policy (EngineConfig(kv_dtype=...)): "
                   "int8/fp8 price the quantized payload + f32 amax scale "
                   "arrays; auto follows --dtype")
    p.add_argument("--spec-k", type=int, default=0,
                   help="speculative decoding armed (EngineConfig(spec_k=...)): "
                   "adds the draft_params tier — the early-exit draft's "
                   "sliced layer stack — to the plan and the SP004 budget "
                   "breakdown, matching the engine's --hbm-gb pre-flight")
    p.add_argument("--draft", default="early_exit:2",
                   help="draft policy priced when --spec-k > 0 "
                   "(EngineConfig(draft=...), e.g. 'early_exit:2')")
    p.add_argument("--swap-gb", type=float, default=None,
                   help="serving KV swap tier (EngineConfig(swap_gb=...)): "
                   "report its host-DRAM footprint alongside the HBM tiers "
                   "(never counted against --hbm-gb — swapped blocks live "
                   "on the host)")
    # training estimate tier
    p.add_argument("--batch", type=int, default=None,
                   help="global batch size: adds gradient + activation-"
                   "estimate tiers")
    p.add_argument("--seq", type=int, default=None,
                   help="sequence length for the activation estimate")
    # extra analyses
    p.add_argument("--hlo", default=None, metavar="FILE",
                   help="compiled-HLO text dump: SP005 reshard/wire-bytes "
                   "ranking")
    p.add_argument("--manifest", default=None, metavar="CHECKPOINT_DIR",
                   help="checkpoint dir: SP006 manifest-vs-plan sharding diff")
    # output
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument("--leaves", action="store_true",
                   help="include the per-leaf plan in --json output")
    p.add_argument("--select", default=None,
                   help="comma-separated finding IDs to report exclusively")
    p.add_argument("--ignore", default=None,
                   help="comma-separated finding IDs to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the finding catalogue and exit")
    p.set_defaults(func=shard_check_command)
    return p
