"""``accelerate-tpu tpu-config`` + pod fanout — run a command on every
worker of a TPU pod over gcloud ssh (reference ``commands/tpu.py:29-152``
and ``tpu_pod_launcher`` ``launch.py:887``).

One process per *host*: the fanout injects ``ACCELERATE_PROCESS_ID`` per
worker and the coordinator address of worker 0; JAX's distributed runtime
does the rest. ``--dry_run`` prints the gcloud invocation (the testable
path; real ssh needs pod credentials).
"""

from __future__ import annotations

import subprocess


def _gcloud_cmd(tpu_name: str, zone: str, worker: str, command: str) -> list[str]:
    return [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
        f"--zone={zone}", f"--worker={worker}", "--command", command,
    ]


def resolve_coordinator(cfg) -> str | None:
    """Worker 0's address, resolved *on the launcher*.

    Explicit config wins; otherwise ask gcloud for worker 0's internal IP.
    Returns None when neither works — the workers then fall back to JAX's
    TPU-pod auto-detection (``jax.distributed.initialize()`` with no
    coordinator reads the TPU metadata server), which is always correct on
    a real pod. Never emit an unexpanded ``$(hostname -i)``: quoted it is a
    literal, and unquoted it would resolve to each worker's *own* IP.
    """
    if cfg.coordinator_address:
        return cfg.coordinator_address
    try:
        out = subprocess.run(
            [
                "gcloud", "compute", "tpus", "tpu-vm", "describe",
                cfg.tpu_name or "tpu", f"--zone={cfg.tpu_zone or 'zone'}",
                "--format=value(networkEndpoints[0].ipAddress)",
            ],
            capture_output=True, text=True, timeout=60,
        )
        ip = out.stdout.strip().splitlines()[0] if out.returncode == 0 and out.stdout.strip() else ""
        return f"{ip}:8476" if ip else None
    except (OSError, subprocess.TimeoutExpired):
        return None


def build_pod_commands(cfg, script: str, script_args: list[str], env: dict) -> list[list[str]]:
    """One gcloud ssh command per pod worker, each exporting the multi-host
    rendezvous env (coordinator = worker 0 port 8476 by convention)."""
    n = max(cfg.num_machines, 1)
    coordinator = resolve_coordinator(cfg)
    cmds = []
    accelerate_env = {k: v for k, v in env.items() if k.startswith(("ACCELERATE_", "JAX_", "XLA_"))}
    for worker in range(n):
        worker_env = {
            **accelerate_env,
            "ACCELERATE_NUM_PROCESSES": str(n),
            "ACCELERATE_PROCESS_ID": str(worker),
        }
        if coordinator is not None:
            worker_env["ACCELERATE_COORDINATOR_ADDR"] = coordinator
        exports = " ".join(f"{k}={v!r}" for k, v in worker_env.items())
        inner = f"export {exports}; python3 {script} {' '.join(script_args)}"
        cmds.append(_gcloud_cmd(cfg.tpu_name or "tpu", cfg.tpu_zone or "zone", str(worker), inner))
    return cmds


def pod_fanout(cfg, script: str, script_args: list[str], env: dict, dry_run: bool = False) -> int:
    cmds = build_pod_commands(cfg, script, script_args, env)
    if dry_run:
        for c in cmds:
            print(" ".join(c))
        return 0
    procs = [subprocess.Popen(c) for c in cmds]
    rc = 0
    for p in procs:
        rc = rc or p.wait()
    return rc


def tpu_command(args) -> int:
    from .config import ClusterConfig
    from .launch import _load_config

    cfg = _load_config(args)
    if args.tpu_name:
        cfg.tpu_name = args.tpu_name
    if args.tpu_zone:
        cfg.tpu_zone = args.tpu_zone
    command = args.command or ""
    if args.install_accelerate:
        command = "pip install accelerate-tpu; " + command
    cmds = [
        _gcloud_cmd(cfg.tpu_name or "tpu", cfg.tpu_zone or "zone", "all", command)
    ]
    if args.debug:
        for c in cmds:
            print(" ".join(c))
        return 0
    rc = 0
    for c in cmds:
        rc = rc or subprocess.call(c)
    return rc


def add_parser(subparsers):
    p = subparsers.add_parser("tpu-config", help="Run commands on all TPU pod workers")
    p.add_argument("--config_file", default=None)
    p.add_argument("--tpu_name", default=None)
    p.add_argument("--tpu_zone", default=None)
    p.add_argument("--command", default=None)
    p.add_argument("--install_accelerate", action="store_true")
    p.add_argument("--debug", action="store_true", help="print, don't run")
    p.set_defaults(func=tpu_command)
    return p
