"""``accelerate-tpu usage`` — the per-request resource-attribution report.

``usage report <logging_dir>`` renders one run's (or a whole suite's)
usage-ledger rollup from its trails alone: who spent the device
(device-seconds split decode/prefill), who occupied the KV cache
(block-seconds), who churned the swap tier (bytes in+out), by tenant or
by priority class, plus the heaviest individual requests with their
``trace_id`` exemplars (so an expensive row links straight into ``trace
tail``). The report re-checks the ledger's **conservation invariant**
from the snapshot's partner totals — Σ per-request decode shares vs the
engine's cumulative ``device_wait``, and Σ per-request block-second
integrals vs the pool-occupancy integral — and the scorecard fails if
either pair disagrees beyond float tolerance.

Data comes from the newest telemetry step row carrying a ``usage``
snapshot (the ledger's cumulative state), plus the router fleet trail's
``by_tenant`` delivery outcomes when the run was routed. Pure file
reads, no jax — like ``monitor`` and ``slo``, it runs anywhere the
logging dir is visible.
"""

from __future__ import annotations

import json
import os
import sys

#: report schema stamp on the --json output
REPORT_SCHEMA = 1

#: conservation re-check tolerance: the partner totals are accrued from
#: the same floats at the same edges, so only accumulation-order rounding
#: separates them
_REL_TOL = 1e-6
_ABS_TOL = 1e-9


def _conserved(total, partner) -> dict:
    ok = None
    if isinstance(total, (int, float)) and isinstance(partner, (int, float)):
        ok = abs(total - partner) <= _ABS_TOL + _REL_TOL * max(
            abs(total), abs(partner)
        )
    return {"sum": total, "partner": partner, "ok": ok}


def _newest_usage(logging_dir: str) -> dict | None:
    """The newest serving step row's ``usage`` snapshot — the ledger's
    cumulative state as of the run's last telemetry flush."""
    from ..diagnostics.monitor import _tail_trail

    jsonl = os.path.join(logging_dir, "telemetry", "telemetry.jsonl")
    records, _ = _tail_trail(jsonl, max_records=2000)
    for row in reversed(records):
        if (
            row.get("type") == "serving"
            and row.get("kind") == "step"
            and isinstance(row.get("usage"), dict)
        ):
            return row["usage"]
    return None


def _router_tenants(logging_dir: str) -> dict | None:
    """The newest aggregate router row's ``by_tenant`` delivery outcomes
    (delivered/shed/requeued/deadline_expired), when the run was routed."""
    from ..diagnostics.monitor import _tail_jsonl

    path = os.path.join(logging_dir, "router", "replicas.jsonl")
    tenants = None
    for row in _tail_jsonl(path, max_records=2000):
        if row.get("kind") == "router" and isinstance(row.get("by_tenant"), dict):
            tenants = row["by_tenant"]  # append-ordered: newest wins
    return tenants


def report_for_run(logging_dir: str) -> dict:
    usage = _newest_usage(logging_dir)
    row = {
        "dir": logging_dir,
        "usage": usage,
        "router_by_tenant": _router_tenants(logging_dir),
        "conservation": None,
    }
    if usage is not None:
        row["conservation"] = {
            "device": _conserved(
                usage.get("decode_device_seconds"),
                usage.get("device_wait_seconds"),
            ),
            "blocks": _conserved(
                usage.get("block_seconds"), usage.get("pool_block_seconds")
            ),
        }
    return row


def build_report(logging_dir: str, by: str = "tenant") -> dict:
    """The full report: the dir itself when it is a traced run, plus every
    immediate child that is one — covering a plain ``serve`` run, a
    ``bench.py fleet`` suite dir, and a routed fleet's layout (router
    trail at the root, one telemetry trail per ``replica_<i>/`` child).
    ``pass`` requires every run with a ledger snapshot to conserve both
    resources."""

    def is_run(d: str) -> bool:
        return (
            os.path.isdir(os.path.join(d, "telemetry"))
            or os.path.isdir(os.path.join(d, "router"))
        )

    runs = []
    if is_run(logging_dir):
        runs.append(logging_dir)
    for name in sorted(os.listdir(logging_dir)):
        child = os.path.join(logging_dir, name)
        if os.path.isdir(child) and is_run(child):
            runs.append(child)
    rows = [report_for_run(d) for d in runs]
    checked = [
        check["ok"]
        for r in rows
        if r["conservation"]
        for check in r["conservation"].values()
        if check["ok"] is not None
    ]
    conserved = all(checked) if checked else None
    return {
        "schema": REPORT_SCHEMA,
        "logging_dir": logging_dir,
        "by": by,
        "runs": rows,
        "conserved": conserved,
        "pass": bool(rows) and conserved is not False,
    }


def _fmt(value, pattern="{:.4g}", none="-") -> str:
    return none if value is None else pattern.format(value)


def render_report(report: dict) -> str:
    by = report["by"]
    lines = [f"accelerate-tpu usage report — {report['logging_dir']} (by {by})"]
    if not report["runs"]:
        lines.append("  no runs found (nothing with telemetry or router trails)")
        return "\n".join(lines)
    for r in report["runs"]:
        usage = r.get("usage")
        if usage is None:
            lines.append(
                f"  {r['dir']}: no usage snapshot in the telemetry trail "
                f"(usage_accounting off, or no step rows yet)"
            )
            continue
        lines.append(
            f"  {r['dir']}: {usage.get('requests_finished')} closed / "
            f"{usage.get('requests_live')} live — "
            f"device {_fmt(usage.get('device_seconds'))}s "
            f"(decode {_fmt(usage.get('decode_device_seconds'))} + "
            f"prefill {_fmt(usage.get('prefill_device_seconds'))})   "
            f"kv {_fmt(usage.get('block_seconds'))} blk·s   "
            f"swap {_fmt(usage.get('swap_bytes'), '{}')} B"
        )
        cons = r.get("conservation") or {}
        for label, key, unit in (
            ("decode device-time", "device", "s"),
            ("block-seconds", "blocks", "blk·s"),
        ):
            c = cons.get(key)
            if not c:
                continue
            mark = {True: "CONSERVED", False: "VIOLATED", None: "no-data"}[c["ok"]]
            lines.append(
                f"    conservation {label:<18} {mark:<10} "
                f"Σ shares {_fmt(c['sum'], '{:.6g}')}{unit} vs "
                f"partner {_fmt(c['partner'], '{:.6g}')}{unit}"
            )
        table = usage.get("by_tenant" if by == "tenant" else "by_class") or {}
        for key, row in sorted(
            table.items(),
            key=lambda kv: -(kv[1].get("device_seconds") or 0.0)
            if isinstance(kv[1], dict)
            else 0.0,
        ):
            if not isinstance(row, dict):
                continue
            lines.append(
                f"    {by} {str(key):<16} "
                f"req {_fmt(row.get('requests'), '{}'):<5} "
                f"tok {_fmt(row.get('tokens'), '{}'):<7} "
                f"device {_fmt(row.get('device_seconds'))}s  "
                f"kv {_fmt(row.get('block_seconds'))} blk·s  "
                f"swap {_fmt(row.get('swap_bytes'), '{}')} B  "
                f"spec {_fmt(row.get('spec_accepted_tokens'), '{}')}"
                f"/{_fmt(row.get('spec_drafted_tokens'), '{}')}  "
                f"grammar {_fmt(row.get('grammar_masked_steps'), '{}')}"
            )
        for h in (usage.get("heavy_hitters") or [])[:5]:
            lines.append(
                f"    heavy: {str(h.get('trace_id') or h.get('request_id'))[:16]:<16} "
                f"tenant {h.get('tenant')}  class {h.get('class')}  "
                f"device {_fmt(h.get('device_seconds'))}s  "
                f"kv {_fmt(h.get('block_seconds'))} blk·s  "
                f"tokens {_fmt(h.get('new_tokens'), '{}')}  "
                f"finish {h.get('finish_reason') or '?'}"
            )
        router = r.get("router_by_tenant")
        if router:
            parts = [
                f"{t} {_fmt(row.get('delivered'), '{}')}d"
                f"/{_fmt(row.get('shed'), '{}')}s"
                f"/{_fmt(row.get('requeued'), '{}')}r"
                f"/{_fmt(row.get('deadline_expired'), '{}')}x"
                for t, row in sorted(router.items())
                if isinstance(row, dict)
            ]
            lines.append(
                "    router (delivered/shed/requeued/expired): "
                + "  ".join(parts)
            )
    verdict = report.get("conserved")
    lines.append(
        "  overall: "
        + {True: "CONSERVED", False: "VIOLATED", None: "no ledger data"}[verdict]
    )
    return "\n".join(lines)


def usage_report_command(args) -> int:
    if not os.path.isdir(args.logging_dir):
        print(f"usage report: {args.logging_dir} is not a directory", file=sys.stderr)
        return 1
    report = build_report(args.logging_dir, by=args.by)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render_report(report))
    return 0 if report["pass"] else 1


def add_parser(subparsers):
    p = subparsers.add_parser(
        "usage", help="Per-request resource attribution from a run's trails"
    )
    sub = p.add_subparsers(dest="usage_command")
    report = sub.add_parser(
        "report",
        help="who spent the device / held the KV cache / churned swap, by "
        "tenant or class, with heavy-hitter exemplars and the ledger's "
        "conservation re-check — from the trails alone",
    )
    report.add_argument(
        "logging_dir",
        help="a run's logging dir, or a suite dir whose children are runs",
    )
    report.add_argument(
        "--by", choices=("tenant", "class"), default="tenant",
        help="rollup dimension for the rendered table (default: tenant)",
    )
    report.add_argument("--json", action="store_true",
                        help="machine-readable report instead of the table")
    report.set_defaults(func=usage_report_command)
    return p
