"""Checkpoint/resume.

Reference: ``/root/reference/src/accelerate/checkpointing.py`` (306 LoC) +
``Accelerator.save_state/load_state`` (``accelerator.py:2966,3132``).
Directory contract preserved (``checkpoint_<i>/`` rotation under
``project_dir/checkpoints`` with ``total_limit``; model/optimizer/scheduler/
sampler/RNG files per component) so reference users find the same layout.

TPU-native storage: parameters and optimizer state are saved as flat
``name → array`` dicts in **safetensors** when available (numpy fallback:
``.npz``), fetched from device with their shardings dropped — reload
re-places them onto the live arrays' shardings, so a checkpoint written on
one mesh restores onto any other (the GSPMD analog of the reference's
FSDP ``SHARDED_STATE_DICT``/rank-0 consolidation split).
"""

from __future__ import annotations

import json
import os
import pickle
import random
import shutil
from pathlib import Path
from typing import Any

import jax
import numpy as np

from .logging import get_logger
from .utils.imports import is_safetensors_available

logger = get_logger(__name__)

MODEL_NAME = "model"
OPTIMIZER_NAME = "optimizer"
SCHEDULER_NAME = "scheduler"
SAMPLER_NAME = "sampler"
RNG_STATE_NAME = "random_states"
CUSTOM_STATES_NAME = "custom_checkpoint"
SCALER_NAME = "scaler"  # reference saves GradScaler state as scaler.pt


# ---------------------------------------------------------------------------
# flat-dict array IO
# ---------------------------------------------------------------------------


def _fetch_leaf(leaf) -> np.ndarray:
    """Bring one (possibly multi-host-sharded) array to host. For
    non-fully-addressable arrays this is a COLLECTIVE — every process must
    call it, which is why flattening happens outside any main-process guard."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten_tree(tree) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = ".".join(_path_part(p) for p in path)
        flat[key] = _fetch_leaf(leaf)
    return flat


def _path_part(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


def save_array_dict(flat: dict[str, np.ndarray], path: str, safe_serialization: bool = True):
    if safe_serialization and is_safetensors_available():
        # safetensors.NUMPY, deliberately: the flax backend round-trips
        # every array through jnp.asarray — i.e. through the attached
        # accelerator, a gratuitous device hop. The numpy backend stays
        # host-only and handles ml_dtypes bfloat16 natively.
        # ascontiguousarray is LOAD-BEARING: some TPU backends hand back
        # host arrays with device-chosen (non-C) strides, and safetensors
        # serialises the raw buffer without honouring them — silently
        # interleaving the tensor on disk.
        from safetensors.numpy import save_file

        def _c_order(v):
            v = np.asarray(v)
            # ascontiguousarray would promote 0-d scalars to shape (1,)
            if v.ndim == 0 or v.flags["C_CONTIGUOUS"]:
                return v
            return np.ascontiguousarray(v)

        out = {k: _c_order(v) for k, v in flat.items()}
        save_file(out, path if path.endswith(".safetensors") else path + ".safetensors")
        return path + ("" if path.endswith(".safetensors") else ".safetensors")
    np.savez(path + ".npz", **flat)
    return path + ".npz"


def load_array_dict(path: str) -> dict[str, np.ndarray]:
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return {k: np.asarray(v) for k, v in load_file(path).items()}
    if path.endswith(".npz"):
        data = np.load(path)
        return {k: data[k] for k in data.files}
    for suffix in (".safetensors", ".npz"):
        if os.path.exists(path + suffix):
            return load_array_dict(path + suffix)
    raise FileNotFoundError(path)


def _restore_tree_like(live_tree, flat: dict[str, np.ndarray]):
    """Rebuild a pytree with the structure+shardings of ``live_tree`` from a
    flat dict (cross-mesh restore: values are re-placed per the live
    arrays' shardings)."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(live_tree)
    leaves = []
    for path, leaf in paths:
        key = ".".join(_path_part(p) for p in path)
        if key not in flat:
            raise KeyError(f"checkpoint is missing tensor {key!r}")
        value = np.asarray(flat[key])
        if hasattr(leaf, "shape") and tuple(value.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"shape mismatch for {key}: checkpoint {value.shape} vs live {np.shape(leaf)}"
            )
        if isinstance(leaf, jax.Array):
            value = jax.device_put(value.astype(leaf.dtype), leaf.sharding)
        leaves.append(value)
    return jax.tree.unflatten(jax.tree.structure(live_tree), leaves)


# ---------------------------------------------------------------------------
# RNG bundles (reference ``checkpointing.py:144-161`` per-rank pickles)
# ---------------------------------------------------------------------------


def _collect_rng_state() -> dict[str, Any]:
    from .utils.random import jax_rng_state

    states = {"random_state": random.getstate(), "numpy_random_seed": np.random.get_state()}
    jax_key = jax_rng_state()
    if jax_key is not None:
        # the framework jax key — the xm-seed analog in the reference's
        # per-rank bundle (``checkpointing.py:144-161``)
        states["jax_key"] = jax_key
    try:
        import torch

        states["torch_manual_seed"] = torch.get_rng_state()
    except Exception:
        pass
    return states


def _restore_rng_state(states: dict[str, Any]):
    from .utils.random import set_jax_rng_state

    random.setstate(states["random_state"])
    np.random.set_state(states["numpy_random_seed"])
    if "jax_key" in states:
        set_jax_rng_state(states["jax_key"])
    if "torch_manual_seed" in states:
        try:
            import torch

            torch.set_rng_state(states["torch_manual_seed"])
        except Exception:
            pass


# ---------------------------------------------------------------------------
# accelerator-level save/load
# ---------------------------------------------------------------------------


#: in-flight async checkpoint write (single-worker: saves are ordered)
_ASYNC_SAVE: dict[str, Any] = {"executor": None, "future": None}


def wait_for_checkpoint():
    """Block until a pending ``async_save`` finished writing (orbax-style
    contract: training continues while files land; the next save/load —
    or an explicit call — joins the writer). Multi-process note: this
    joins the LOCAL writer; ``load_accelerator_state`` additionally
    barriers so no process reads files another process is still writing."""
    future = _ASYNC_SAVE["future"]
    if future is not None:
        try:
            future.result()
        finally:
            # a failed write must not poison every later save/load — the
            # exception surfaces once, then the slot clears
            _ASYNC_SAVE["future"] = None


def _join_writer_then_barrier(accelerator):
    """Join the local async writer, ALWAYS reach the cross-process barrier,
    then surface any local write failure — raising before the barrier would
    leave the other processes hanging in it forever."""
    error = None
    try:
        wait_for_checkpoint()
    except Exception as e:  # noqa: BLE001 — surfaced after the barrier
        error = e
    accelerator.wait_for_everyone()
    if error is not None:
        raise error


def save_accelerator_state(
    accelerator,
    output_dir: str | None = None,
    safe_serialization: bool = True,
    async_save: bool = False,
):
    """(Reference ``save_accelerator_state`` ``checkpointing.py:53`` +
    rotation ``accelerator.py:3004-3028``.)

    ``async_save=True`` → the device→host gather (a collective, main-thread
    only) runs now, the file writes land on a background worker, and the
    call returns immediately; see :func:`wait_for_checkpoint`.
    """
    # join the previous writer, then barrier — saves are ordered, and the
    # barrier bounds cross-process skew to ONE in-flight checkpoint (the
    # rotation below deletes directories other processes may otherwise
    # still be writing into). A local write failure must surface AFTER the
    # barrier, or the other processes hang in it while this one raises.
    _join_writer_then_barrier(accelerator)
    if output_dir is None:
        if accelerator.project_dir is None:
            raise ValueError("pass output_dir or set project_dir on the Accelerator")
        checkpoints_dir = os.path.join(accelerator.project_dir, "checkpoints")
        config = accelerator.project_configuration
        if config.automatic_checkpoint_naming:
            output_dir = os.path.join(checkpoints_dir, f"checkpoint_{config.iteration}")
            if accelerator.is_main_process and config.total_limit is not None:
                existing = _sorted_checkpoints(checkpoints_dir)
                while len(existing) + 1 > config.total_limit:
                    shutil.rmtree(existing.pop(0), ignore_errors=True)
        else:
            output_dir = checkpoints_dir
    os.makedirs(output_dir, exist_ok=True)

    # Flatten/gather on ALL processes (collective for multi-host shards)…
    model_flats = [_flatten_tree(m.params) for m in accelerator._models]
    opt_flats = [_flatten_tree(o.opt_state) for o in accelerator._optimizers]

    # Snapshot every host-side state NOW (the background writer must see
    # this step's values, not whatever the training loop mutates next)…
    sched_states = [s.state_dict() for s in accelerator._schedulers]
    # deep sampler/loader state: epoch + mid-epoch position, so load_state
    # resumes without a manual skip_first_batches (reference saves
    # sampler/dataloader state_dicts, ``checkpointing.py:116-143``)
    dl_states = [dl.state_dict() for dl in accelerator._dataloaders]
    custom_states = [obj.state_dict() for obj in accelerator._custom_objects]
    scaler_state = (
        accelerator._loss_scale.state_dict()
        if getattr(accelerator, "_loss_scale", None) is not None
        else None
    )
    meta = {"step": accelerator.step, "iteration": accelerator.save_iteration}
    rng_state = _collect_rng_state()
    is_main = accelerator.is_main_process
    process_index = accelerator.process_index
    if not is_main:  # only the main process touches the array files
        model_flats, opt_flats = [], []

    def _write_files():
        if is_main:
            for i, flat in enumerate(model_flats):
                suffix = "" if i == 0 else f"_{i}"
                save_array_dict(flat, os.path.join(output_dir, f"{MODEL_NAME}{suffix}"), safe_serialization)
            for i, flat in enumerate(opt_flats):
                suffix = "" if i == 0 else f"_{i}"
                save_array_dict(flat, os.path.join(output_dir, f"{OPTIMIZER_NAME}{suffix}"), safe_serialization)
            for i, state in enumerate(sched_states):
                with open(os.path.join(output_dir, f"{SCHEDULER_NAME}{'' if i == 0 else f'_{i}'}.bin"), "wb") as f:
                    pickle.dump(state, f)
            for i, state in enumerate(dl_states):
                with open(os.path.join(output_dir, f"{SAMPLER_NAME}{'' if i == 0 else f'_{i}'}.bin"), "wb") as f:
                    pickle.dump(state, f)
            for i, state in enumerate(custom_states):
                with open(os.path.join(output_dir, f"{CUSTOM_STATES_NAME}_{i}.pkl"), "wb") as f:
                    pickle.dump(state, f)
            if scaler_state is not None:
                with open(os.path.join(output_dir, f"{SCALER_NAME}.bin"), "wb") as f:
                    pickle.dump(scaler_state, f)
            with open(os.path.join(output_dir, "accelerator_state.json"), "w") as f:
                json.dump(meta, f)
        # per-process RNG bundle (every process writes its own, like the
        # reference's random_states_{i}.pkl)
        with open(os.path.join(output_dir, f"{RNG_STATE_NAME}_{process_index}.pkl"), "wb") as f:
            pickle.dump(rng_state, f)
        logger.info(f"Saved state to {output_dir}")

    accelerator.project_configuration.iteration += 1
    if async_save:
        from concurrent.futures import ThreadPoolExecutor

        if _ASYNC_SAVE["executor"] is None:
            _ASYNC_SAVE["executor"] = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="checkpoint-writer"
            )
        _ASYNC_SAVE["future"] = _ASYNC_SAVE["executor"].submit(_write_files)
        return output_dir

    _write_files()
    accelerator.wait_for_everyone()
    return output_dir


def _sorted_checkpoints(checkpoints_dir: str) -> list[str]:
    if not os.path.isdir(checkpoints_dir):
        return []
    entries = [
        os.path.join(checkpoints_dir, d)
        for d in os.listdir(checkpoints_dir)
        if d.startswith("checkpoint_")
    ]
    return sorted(entries, key=lambda p: int(p.rsplit("_", 1)[-1]))


def load_accelerator_state(accelerator, input_dir: str | None = None, **kwargs):
    """(Reference ``load_accelerator_state`` ``checkpointing.py:165``.)"""
    # an in-flight async save must land on EVERY process before ANY
    # process reads (each joins its own writer, then all meet)
    _join_writer_then_barrier(accelerator)
    if input_dir is None:
        if accelerator.project_dir is None:
            raise ValueError("pass input_dir or set project_dir on the Accelerator")
        checkpoints_dir = os.path.join(accelerator.project_dir, "checkpoints")
        existing = _sorted_checkpoints(checkpoints_dir)
        if not existing:
            raise FileNotFoundError(f"no checkpoints under {checkpoints_dir}")
        input_dir = existing[-1]

    for i, model in enumerate(accelerator._models):
        suffix = "" if i == 0 else f"_{i}"
        flat = load_array_dict(os.path.join(input_dir, f"{MODEL_NAME}{suffix}"))
        model.params = _restore_tree_like(model.params, flat)
    for i, opt in enumerate(accelerator._optimizers):
        suffix = "" if i == 0 else f"_{i}"
        flat = load_array_dict(os.path.join(input_dir, f"{OPTIMIZER_NAME}{suffix}"))
        opt.opt_state = _restore_tree_like(opt.opt_state, flat)
    for i, sched in enumerate(accelerator._schedulers):
        path = os.path.join(input_dir, f"{SCHEDULER_NAME}{'' if i == 0 else f'_{i}'}.bin")
        with open(path, "rb") as f:
            sched.load_state_dict(pickle.load(f))
    for i, dl in enumerate(accelerator._dataloaders):
        path = os.path.join(input_dir, f"{SAMPLER_NAME}{'' if i == 0 else f'_{i}'}.bin")
        if os.path.exists(path):
            with open(path, "rb") as f:
                state = pickle.load(f)
            dl.load_state_dict(state)
    for i, obj in enumerate(accelerator._custom_objects):
        with open(os.path.join(input_dir, f"{CUSTOM_STATES_NAME}_{i}.pkl"), "rb") as f:
            obj.load_state_dict(pickle.load(f))
    scaler_file = os.path.join(input_dir, f"{SCALER_NAME}.bin")
    if getattr(accelerator, "_loss_scale", None) is not None and os.path.exists(scaler_file):
        with open(scaler_file, "rb") as f:
            accelerator._loss_scale.load_state_dict(pickle.load(f))
    state_file = os.path.join(input_dir, "accelerator_state.json")
    if os.path.exists(state_file):
        with open(state_file) as f:
            meta = json.load(f)
        accelerator.step = meta.get("step", 0)
        if "iteration" in meta:
            # resume the rotation counter past the loaded checkpoint so the
            # next save doesn't clobber history (reference ``load_state``
            # sets iteration = loaded + 1, ``accelerator.py:3227``)
            accelerator.project_configuration.iteration = meta["iteration"] + 1
    base = os.path.basename(os.path.normpath(input_dir))
    if base.startswith("checkpoint_"):
        accelerator.project_configuration.iteration = int(base.rsplit("_", 1)[-1]) + 1

    rng_file = os.path.join(input_dir, f"{RNG_STATE_NAME}_{accelerator.process_index}.pkl")
    if not os.path.exists(rng_file):
        rng_file = os.path.join(input_dir, f"{RNG_STATE_NAME}_0.pkl")
    if os.path.exists(rng_file):
        with open(rng_file, "rb") as f:
            _restore_rng_state(pickle.load(f))
    logger.info(f"Loaded state from {input_dir}")
    return input_dir


# ---------------------------------------------------------------------------
# standalone model save (reference ``save_model`` ``accelerator.py:2823``)
# ---------------------------------------------------------------------------


def save_model_weights(accelerator, model, save_directory: str, max_shard_size="10GB", safe_serialization=True):
    os.makedirs(save_directory, exist_ok=True)
    from .modules import Model, PreparedModel

    if isinstance(model, (PreparedModel, Model)):
        flat = _flatten_tree(model.params)  # collective on all processes
    else:
        raise TypeError(f"cannot save {type(model)}")
    if not accelerator.is_main_process:
        accelerator.wait_for_everyone()
        return
    max_bytes = _parse_size(max_shard_size)
    shards = _shard_flat_dict(flat, max_bytes)
    if len(shards) == 1:
        save_array_dict(shards[0], os.path.join(save_directory, "model"), safe_serialization)
    else:
        index = {"metadata": {"total_size": sum(v.nbytes for v in flat.values())}, "weight_map": {}}
        ext = ".safetensors" if (safe_serialization and is_safetensors_available()) else ".npz"
        for i, shard in enumerate(shards):
            name = f"model-{i + 1:05d}-of-{len(shards):05d}"
            save_array_dict(shard, os.path.join(save_directory, name), safe_serialization)
            for key in shard:
                index["weight_map"][key] = name + ext
        # HF-convention index name for safetensors
        # (`model.safetensors.index.json`: what merge-weights and the
        # device-map checkpoint reader consume — reference
        # utils/modeling.py:1636-1794 reads the same file); npz shards
        # keep the legacy `model.index.json` every reader already probes
        index_name = "model.safetensors.index.json" if ext == ".safetensors" else "model.index.json"
        with open(os.path.join(save_directory, index_name), "w") as f:
            json.dump(index, f, indent=2)
    accelerator.wait_for_everyone()


def _parse_size(size) -> int:
    if isinstance(size, int):
        return size
    size = str(size).upper().strip()
    for unit, mul in (("GB", 2**30), ("MB", 2**20), ("KB", 2**10)):
        if size.endswith(unit):
            return int(float(size[: -len(unit)]) * mul)
    return int(size)


def _shard_flat_dict(flat: dict[str, np.ndarray], max_bytes: int) -> list[dict]:
    shards, current, size = [], {}, 0
    for key, value in flat.items():
        if current and size + value.nbytes > max_bytes:
            shards.append(current)
            current, size = {}, 0
        current[key] = value
        size += value.nbytes
    if current:
        shards.append(current)
    return shards


def save_object(obj, path, safe_serialization=False):
    """(Reference ``utils/other.py:182`` ``save``.)"""
    with open(path, "wb") as f:
        pickle.dump(obj, f)
