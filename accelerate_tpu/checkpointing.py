"""Checkpoint/resume.

Reference: ``/root/reference/src/accelerate/checkpointing.py`` (306 LoC) +
``Accelerator.save_state/load_state`` (``accelerator.py:2966,3132``).
Directory contract preserved (``checkpoint_<i>/`` rotation under
``project_dir/checkpoints`` with ``total_limit``; model/optimizer/scheduler/
sampler/RNG files per component) so reference users find the same layout.

TPU-native storage: parameters and optimizer state are saved as flat
``name → array`` dicts in **safetensors** when available (numpy fallback:
``.npz``), fetched from device with their shardings dropped — reload
re-places them onto the live arrays' shardings, so a checkpoint written on
one mesh restores onto any other (the GSPMD analog of the reference's
FSDP ``SHARDED_STATE_DICT``/rank-0 consolidation split).
"""

from __future__ import annotations

import atexit
import json
import os
import pickle
import random
import re
import shutil
import time
from pathlib import Path
from typing import Any

import jax
import numpy as np

from .diagnostics.tracing import traced
from .logging import get_logger
from .utils.imports import is_safetensors_available

logger = get_logger(__name__)

MODEL_NAME = "model"
OPTIMIZER_NAME = "optimizer"
SCHEDULER_NAME = "scheduler"
SAMPLER_NAME = "sampler"
RNG_STATE_NAME = "random_states"
CUSTOM_STATES_NAME = "custom_checkpoint"
SCALER_NAME = "scaler"  # reference saves GradScaler state as scaler.pt


# ---------------------------------------------------------------------------
# flat-dict array IO
# ---------------------------------------------------------------------------


def _fetch_leaf(leaf) -> np.ndarray:
    """Bring one (possibly multi-host-sharded) array to host. For
    non-fully-addressable arrays this is a COLLECTIVE — every process must
    call it, which is why flattening happens outside any main-process guard."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(jax.device_get(leaf))


def _flatten_tree(tree) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = ".".join(_path_part(p) for p in path)
        flat[key] = _fetch_leaf(leaf)
    return flat


def _path_part(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


def save_array_dict(flat: dict[str, np.ndarray], path: str, safe_serialization: bool = True):
    if safe_serialization and is_safetensors_available():
        # safetensors.NUMPY, deliberately: the flax backend round-trips
        # every array through jnp.asarray — i.e. through the attached
        # accelerator, a gratuitous device hop. The numpy backend stays
        # host-only and handles ml_dtypes bfloat16 natively.
        # ascontiguousarray is LOAD-BEARING: some TPU backends hand back
        # host arrays with device-chosen (non-C) strides, and safetensors
        # serialises the raw buffer without honouring them — silently
        # interleaving the tensor on disk.
        from safetensors.numpy import save_file

        def _c_order(v):
            v = np.asarray(v)
            # ascontiguousarray would promote 0-d scalars to shape (1,)
            if v.ndim == 0 or v.flags["C_CONTIGUOUS"]:
                return v
            return np.ascontiguousarray(v)

        out = {k: _c_order(v) for k, v in flat.items()}
        save_file(out, path if path.endswith(".safetensors") else path + ".safetensors")
        return path + ("" if path.endswith(".safetensors") else ".safetensors")
    np.savez(path + ".npz", **flat)
    return path + ".npz"


def load_array_dict(path: str) -> dict[str, np.ndarray]:
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return {k: np.asarray(v) for k, v in load_file(path).items()}
    if path.endswith(".npz"):
        data = np.load(path)
        return {k: data[k] for k in data.files}
    for suffix in (".safetensors", ".npz"):
        if os.path.exists(path + suffix):
            return load_array_dict(path + suffix)
    raise FileNotFoundError(path)


def _restore_tree_like(live_tree, flat: dict[str, np.ndarray]):
    """Rebuild a pytree with the structure+shardings of ``live_tree`` from a
    flat dict (cross-mesh restore: values are re-placed per the live
    arrays' shardings)."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(live_tree)
    leaves = []
    for path, leaf in paths:
        key = ".".join(_path_part(p) for p in path)
        if key not in flat:
            raise KeyError(f"checkpoint is missing tensor {key!r}")
        value = np.asarray(flat[key])
        if hasattr(leaf, "shape") and tuple(value.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"shape mismatch for {key}: checkpoint {value.shape} vs live {np.shape(leaf)}"
            )
        if isinstance(leaf, jax.Array):
            value = jax.device_put(value.astype(leaf.dtype), leaf.sharding)
        leaves.append(value)
    return jax.tree.unflatten(jax.tree.structure(live_tree), leaves)


# ---------------------------------------------------------------------------
# RNG bundles (reference ``checkpointing.py:144-161`` per-rank pickles)
# ---------------------------------------------------------------------------


def _collect_rng_state() -> dict[str, Any]:
    from .utils.random import jax_rng_state

    states = {"random_state": random.getstate(), "numpy_random_seed": np.random.get_state()}
    jax_key = jax_rng_state()
    if jax_key is not None:
        # the framework jax key — the xm-seed analog in the reference's
        # per-rank bundle (``checkpointing.py:144-161``)
        states["jax_key"] = jax_key
    try:
        import torch

        states["torch_manual_seed"] = torch.get_rng_state()
    except Exception:
        pass
    return states


def _restore_rng_state(states: dict[str, Any]):
    from .utils.random import set_jax_rng_state

    random.setstate(states["random_state"])
    np.random.set_state(states["numpy_random_seed"])
    if "jax_key" in states:
        set_jax_rng_state(states["jax_key"])
    if "torch_manual_seed" in states:
        try:
            import torch

            torch.set_rng_state(states["torch_manual_seed"])
        except Exception:
            pass


# ---------------------------------------------------------------------------
# accelerator-level save/load
# ---------------------------------------------------------------------------


#: in-flight async checkpoint write (single-worker: saves are ordered).
#: ``pending_commit`` is the (tmp_dir, final_dir, meta) of a written-but-not-
#: yet-committed async save; ``pending_dirs`` protects those directories
#: from rotation until the commit lands.
_ASYNC_SAVE: dict[str, Any] = {
    "executor": None,
    "future": None,
    "pending_commit": None,
    "pending_dirs": set(),
}


def _pending_checkpoint_dirs() -> set[str]:
    """Directories with an async write or commit still in flight — rotation
    must never delete these (the write would land in a deleted directory,
    or worse, resurrect it half-empty)."""
    return set(_ASYNC_SAVE["pending_dirs"])


def _commit_checkpoint_dir(tmp_dir: str, final_dir: str):
    """The commit step. Fresh ``final_dir`` (the automatic-naming /
    rotation path — the preemption-safety case): ONE atomic ``os.rename``,
    so the checkpoint exists completely or not at all. Existing
    ``final_dir`` (an explicitly reused directory, or the non-automatic
    default ``checkpoints/``): per-entry merge-overwrite — deleting the
    directory wholesale would take unrelated content (older ``checkpoint_N``
    dirs, a not-yet-consumed sentinel, user files kept alongside) with it,
    which the pre-manifest code never did."""
    from .resilience.retry import run_with_retries

    def _commit():
        if not os.path.isdir(final_dir):
            os.rename(tmp_dir, final_dir)
            return
        from .resilience.manifest import MANIFEST_NAME

        # the manifest moves LAST: a crash mid-merge leaves old-manifest-
        # vs-new-files (or no manifest), which validation fails closed
        entries = sorted(os.listdir(tmp_dir), key=lambda e: e == MANIFEST_NAME)
        for entry in entries:
            src = os.path.join(tmp_dir, entry)
            dst = os.path.join(final_dir, entry)
            if os.path.isdir(dst):
                shutil.rmtree(dst)
            os.replace(src, dst)
        shutil.rmtree(tmp_dir, ignore_errors=True)

    run_with_retries(_commit, what=f"commit {final_dir}")
    # fsync the parent so the rename itself survives a host crash
    try:
        parent_fd = os.open(os.path.dirname(os.path.abspath(final_dir)) or ".", os.O_RDONLY)
        try:
            os.fsync(parent_fd)
        finally:
            os.close(parent_fd)
    except OSError:
        pass


def _finish_pending_commit(cross_process_safe: bool):
    """Perform a deferred async-save commit. Multi-process commits need the
    cross-host barrier first (every host's writer joined) — callers that
    barriered pass ``cross_process_safe=True``; single-process commits are
    always safe."""
    pending = _ASYNC_SAVE["pending_commit"]
    if pending is None:
        return
    tmp_dir, final_dir, meta = pending
    if not cross_process_safe:
        try:
            from .state import PartialState

            if PartialState().num_processes > 1:
                return  # the next barriered join point commits
        except Exception:
            pass
    _ASYNC_SAVE["pending_commit"] = None
    try:
        if meta.get("is_main", True):
            if meta.get("build_manifest", True):
                _write_checkpoint_manifest(tmp_dir, meta)
            _commit_checkpoint_dir(tmp_dir, final_dir)
            _record_checkpoint_telemetry("save", final_dir, meta)
        logger.info(f"Committed checkpoint {final_dir}")
    finally:
        _ASYNC_SAVE["pending_dirs"].discard(final_dir)
        _ASYNC_SAVE["pending_dirs"].discard(tmp_dir)


def wait_for_checkpoint():
    """Block until a pending ``async_save`` finished writing AND (when this
    process can do so safely) committed its directory (orbax-style
    contract: training continues while files land; the next save/load —
    or an explicit call — joins the writer). Multi-process note: this
    joins the LOCAL writer; ``load_accelerator_state`` additionally
    barriers so no process reads files another process is still writing."""
    future = _ASYNC_SAVE["future"]
    if future is not None:
        try:
            future.result()
        except BaseException:
            # the write failed: NEVER promote its half-written tmp dir —
            # abort the commit (the .tmp stays on disk for diagnosis)
            _abort_pending_commit()
            raise
        finally:
            # a failed write must not poison every later save/load — the
            # exception surfaces once, then the slot clears
            _ASYNC_SAVE["future"] = None
    _finish_pending_commit(cross_process_safe=False)


def _atexit_drain_async_saves():
    """Clean interpreter exit must not silently abandon an in-flight async
    save: join the writer, finish the commit, and say what happened — a
    lost checkpoint at exit is exactly the failure this subsystem exists
    to prevent."""
    future = _ASYNC_SAVE["future"]
    pending = _ASYNC_SAVE["pending_commit"]
    if future is None and pending is None:
        return
    try:
        wait_for_checkpoint()
        # Single-process: commit — a fully-written local save must not be
        # stranded as a .tmp forever. Multi-process: there is NO barrier
        # available at exit, and committing would let the manifest certify
        # a checkpoint other hosts are still writing — leave the .tmp
        # uncommitted (auto-resume falls back to the previous checkpoint).
        try:
            from .state import PartialState

            multi = PartialState().num_processes > 1
        except Exception:
            multi = False
        if multi and _ASYNC_SAVE["pending_commit"] is not None:
            logger.warning(
                "multi-host async checkpoint save left UNCOMMITTED (.tmp) at "
                "interpreter exit — no cross-host barrier is available here; "
                "resume will use the previous committed checkpoint"
            )
        else:
            _finish_pending_commit(cross_process_safe=True)
        logger.info("joined in-flight async checkpoint save at interpreter exit")
    except Exception:
        logger.error(
            "in-flight async checkpoint save FAILED during interpreter exit "
            "— the last checkpoint may be lost",
            exc_info=True,
        )
    finally:
        executor = _ASYNC_SAVE["executor"]
        if executor is not None:
            executor.shutdown(wait=True)
            _ASYNC_SAVE["executor"] = None


def _async_executor():
    if _ASYNC_SAVE["executor"] is None:
        from concurrent.futures import ThreadPoolExecutor

        _ASYNC_SAVE["executor"] = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="checkpoint-writer"
        )
        atexit.register(_atexit_drain_async_saves)
    return _ASYNC_SAVE["executor"]


def _abort_pending_commit():
    """Drop the pending-commit bookkeeping without promoting the ``.tmp``
    (a torn save must stay invisible to checkpoint discovery)."""
    pending = _ASYNC_SAVE["pending_commit"]
    _ASYNC_SAVE["pending_commit"] = None
    if pending is not None:
        _ASYNC_SAVE["pending_dirs"].discard(pending[0])
        _ASYNC_SAVE["pending_dirs"].discard(pending[1])


def _join_writer_then_barrier(accelerator):
    """Join the local async writer, ALWAYS reach the cross-process barrier,
    then surface any local write failure — raising before the barrier would
    leave the other processes hanging in it forever.

    A deferred multi-process async commit lands here. The commit decision
    is COLLECTIVE: after the barrier, the hosts all-reduce "did any writer
    fail?" — if yes, every process aborts the commit (the torn save stays a
    ``.tmp``; committing would let the manifest certify whatever subset of
    shard files happens to exist); if no, the main process renames and a
    second barrier makes the rename visible before anyone reads. Any
    commit-side failure is parked until after that barrier too, so no
    process ever raises while the others still wait."""
    # symmetric across processes (every process submitted the same async
    # save) — safe to branch the collectives on
    had_async = (
        _ASYNC_SAVE["future"] is not None or _ASYNC_SAVE["pending_commit"] is not None
    )
    error = None
    try:
        wait_for_checkpoint()
    except Exception as e:  # noqa: BLE001 — surfaced after the barrier
        error = e
    accelerator.wait_for_everyone()
    if had_async and accelerator.num_processes > 1:
        from .state import PartialState

        any_failed = PartialState().consensus_any(error is not None)
        commit_error = None
        if any_failed:
            _abort_pending_commit()
        elif _ASYNC_SAVE["pending_commit"] is not None:
            try:
                _finish_pending_commit(cross_process_safe=True)
            except Exception as e:  # noqa: BLE001 — surfaced after the barrier
                commit_error = e
        accelerator.wait_for_everyone()
        if error is None:
            error = commit_error
    if error is not None:
        raise error


def _record_checkpoint_telemetry(kind: str, path: str, meta: dict):
    from .telemetry import get_active_recorder

    recorder = get_active_recorder()
    if not recorder:
        return
    # async saves commit at the NEXT join point — wall time since t0 would
    # count arbitrary intervening training; the writer stamps its true
    # duration into write_seconds when the files land
    seconds = meta.get("write_seconds")
    if seconds is None and "t0" in meta:
        seconds = time.perf_counter() - meta["t0"]
    recorder.record_checkpoint(
        kind=kind,
        seconds=seconds,
        bytes_written=meta.get("bytes"),
        shard_count=meta.get("shard_count"),
        is_async=meta.get("is_async", False),
        path=path,
    )


def _write_checkpoint_manifest(tmp_dir: str, meta: dict):
    """Merge per-host piece tables (sharded saves) and write the manifest —
    the last file before the commit rename."""
    from .resilience.manifest import build_manifest, write_manifest

    arrays = None
    shard_count = 0
    if meta.get("sharded"):
        from .resilience.distributed import merge_piece_tables

        arrays = {}
        tables_by_component: dict[str, list] = {}
        for entry in sorted(os.listdir(tmp_dir)):
            table_path = os.path.join(tmp_dir, entry, "piece_table.json")
            if not (entry.startswith("shard_") and os.path.exists(table_path)):
                continue
            shard_count += 1
            with open(table_path) as f:
                for component, table in json.load(f).items():
                    tables_by_component.setdefault(component, []).append(table)
        for component, tables in tables_by_component.items():
            arrays[component] = merge_piece_tables(tables)
    manifest = build_manifest(
        tmp_dir,
        kind="sharded" if meta.get("sharded") else "gathered",
        step=meta.get("step"),
        iteration=meta.get("iteration"),
        host_count=meta.get("host_count", 1),
        arrays=arrays,
    )
    meta["bytes"] = sum(f["bytes"] for f in manifest["files"].values())
    meta["shard_count"] = shard_count
    write_manifest(tmp_dir, manifest)


def _resolve_sharded(accelerator, sharded) -> bool:
    if sharded is not None:
        return bool(sharded)
    plugin = getattr(accelerator, "fault_tolerance_plugin", None)
    return bool(plugin is not None and getattr(plugin, "sharded_io", False))


def _rotate_checkpoints(checkpoints_dir: str, total_limit: int, incoming: int = 1):
    """Delete oldest committed checkpoints so that ``incoming`` more fit
    under ``total_limit``. Checkpoints with a pending async write/commit
    are NEVER deleted — rotation must not race the writer."""
    existing = _sorted_checkpoints(checkpoints_dir)
    pending = _pending_checkpoint_dirs()
    pending_paths = {os.path.abspath(p) for p in pending}
    excess = len(existing) + incoming - total_limit
    for path in existing:
        if excess <= 0:
            break
        if os.path.abspath(path) in pending_paths:
            logger.warning(
                "rotation: keeping %s (async checkpoint write in flight)", path
            )
            continue
        shutil.rmtree(path, ignore_errors=True)
        excess -= 1


# diagnostics spans around the checkpoint entry points (an async save's
# span covers the snapshot+dispatch half; the background writes report
# through the checkpoint telemetry record at commit time)
@traced("checkpoint/save")
def save_accelerator_state(
    accelerator,
    output_dir: str | None = None,
    safe_serialization: bool = True,
    async_save: bool = False,
    sharded: bool | None = None,
):
    """(Reference ``save_accelerator_state`` ``checkpointing.py:53`` +
    rotation ``accelerator.py:3004-3028``.)

    Every save is **atomic**: files land in ``<output_dir>.tmp``, a
    manifest (per-file sizes + CRC32s — see ``resilience/manifest.py``) is
    written last, and the directory is ``os.rename``'d into place after a
    cross-host barrier. A crash mid-save leaves only a ``.tmp`` that
    checkpoint discovery ignores.

    ``async_save=True`` → the device→host snapshot (a collective in
    gathered mode, main-thread only) runs now, the file writes land on a
    background worker, and the call returns immediately; see
    :func:`wait_for_checkpoint`.

    ``sharded=True`` (default when the Accelerator carries a
    ``FaultTolerancePlugin(sharded_io=True)``) → each host writes only its
    addressable shards into ``shard_<host>/`` instead of gathering every
    array to the main host — no full-gather OOM/wall-clock spike on
    multi-host FSDP.
    """
    t0 = time.perf_counter()
    # join the previous writer, then barrier — saves are ordered, and the
    # barrier bounds cross-process skew to ONE in-flight checkpoint (the
    # rotation below deletes directories other processes may otherwise
    # still be writing into). A local write failure must surface AFTER the
    # barrier, or the other processes hang in it while this one raises.
    _join_writer_then_barrier(accelerator)
    sharded = _resolve_sharded(accelerator, sharded)
    if output_dir is None:
        if accelerator.project_dir is None:
            raise ValueError("pass output_dir or set project_dir on the Accelerator")
        checkpoints_dir = os.path.join(accelerator.project_dir, "checkpoints")
        config = accelerator.project_configuration
        if config.automatic_checkpoint_naming:
            output_dir = os.path.join(checkpoints_dir, f"checkpoint_{config.iteration}")
            if accelerator.is_main_process and config.total_limit is not None:
                _rotate_checkpoints(checkpoints_dir, config.total_limit)
        else:
            output_dir = checkpoints_dir
    output_dir = os.path.normpath(output_dir)
    tmp_dir = output_dir + ".tmp"
    if accelerator.is_main_process and os.path.isdir(tmp_dir):
        shutil.rmtree(tmp_dir)  # leftover from an interrupted save
    accelerator.wait_for_everyone()
    os.makedirs(tmp_dir, exist_ok=True)

    from .resilience.retry import run_with_retries

    is_main = accelerator.is_main_process
    process_index = accelerator.process_index

    # Snapshot device state NOW, on the calling thread…
    model_pieces: list = []
    opt_pieces: list = []
    model_flats: list = []
    opt_flats: list = []
    if sharded:
        # …local addressable shards only: no gather, no collective
        from .resilience.distributed import collect_addressable_pieces

        model_pieces = [collect_addressable_pieces(m.params) for m in accelerator._models]
        opt_pieces = [collect_addressable_pieces(o.opt_state) for o in accelerator._optimizers]
    else:
        # …full arrays on the main host (collective for multi-host shards)
        model_flats = [_flatten_tree(m.params) for m in accelerator._models]
        opt_flats = [_flatten_tree(o.opt_state) for o in accelerator._optimizers]
        if not is_main:  # only the main process touches the array files
            model_flats, opt_flats = [], []

    # …and every host-side state (the background writer must see this
    # step's values, not whatever the training loop mutates next)
    sched_states = [s.state_dict() for s in accelerator._schedulers]
    # deep sampler/loader state: epoch + mid-epoch position, so load_state
    # resumes without a manual skip_first_batches (reference saves
    # sampler/dataloader state_dicts, ``checkpointing.py:116-143``)
    dl_states = [dl.state_dict() for dl in accelerator._dataloaders]
    custom_states = [obj.state_dict() for obj in accelerator._custom_objects]
    scaler_state = (
        accelerator._loss_scale.state_dict()
        if getattr(accelerator, "_loss_scale", None) is not None
        else None
    )
    meta = {"step": accelerator.step, "iteration": accelerator.save_iteration}
    rng_state = _collect_rng_state()
    num_processes = accelerator.num_processes

    commit_meta = {
        "t0": t0,
        "is_main": is_main,
        "is_async": bool(async_save),
        "sharded": sharded,
        "step": meta["step"],
        "iteration": meta["iteration"],
        "host_count": num_processes,
    }

    def _pickle_to(path: str, state):
        def _write():
            with open(path, "wb") as f:
                pickle.dump(state, f)

        run_with_retries(_write, what=f"write {path}")

    def _write_files():
        from .resilience.distributed import shard_dirname

        if sharded:
            shard_dir = os.path.join(tmp_dir, shard_dirname(process_index))
            os.makedirs(shard_dir, exist_ok=True)
            piece_tables: dict[str, Any] = {}
            for name, per_obj in ((MODEL_NAME, model_pieces), (OPTIMIZER_NAME, opt_pieces)):
                for i, (pieces, table) in enumerate(per_obj):
                    suffix = "" if i == 0 else f"_{i}"
                    written = run_with_retries(
                        lambda p=pieces, s=suffix, n=name: save_array_dict(
                            p, os.path.join(shard_dir, f"{n}{s}"), safe_serialization
                        ),
                        what=f"write {name}{suffix} shard",
                    )
                    rel = os.path.relpath(written, tmp_dir).replace(os.sep, "/")
                    for entry in table.values():
                        for piece in entry["pieces"]:
                            piece["file"] = rel
                    piece_tables[f"{name}_{i}"] = table
            with open(os.path.join(shard_dir, "piece_table.json"), "w") as f:
                json.dump(piece_tables, f)
        else:
            for i, flat in enumerate(model_flats):
                suffix = "" if i == 0 else f"_{i}"
                run_with_retries(
                    lambda fl=flat, s=suffix: save_array_dict(
                        fl, os.path.join(tmp_dir, f"{MODEL_NAME}{s}"), safe_serialization
                    ),
                    what=f"write {MODEL_NAME}{suffix}",
                )
            for i, flat in enumerate(opt_flats):
                suffix = "" if i == 0 else f"_{i}"
                run_with_retries(
                    lambda fl=flat, s=suffix: save_array_dict(
                        fl, os.path.join(tmp_dir, f"{OPTIMIZER_NAME}{s}"), safe_serialization
                    ),
                    what=f"write {OPTIMIZER_NAME}{suffix}",
                )
        if is_main:
            for i, state in enumerate(sched_states):
                _pickle_to(os.path.join(tmp_dir, f"{SCHEDULER_NAME}{'' if i == 0 else f'_{i}'}.bin"), state)
            for i, state in enumerate(dl_states):
                _pickle_to(os.path.join(tmp_dir, f"{SAMPLER_NAME}{'' if i == 0 else f'_{i}'}.bin"), state)
            for i, state in enumerate(custom_states):
                _pickle_to(os.path.join(tmp_dir, f"{CUSTOM_STATES_NAME}_{i}.pkl"), state)
            if scaler_state is not None:
                _pickle_to(os.path.join(tmp_dir, f"{SCALER_NAME}.bin"), scaler_state)
            with open(os.path.join(tmp_dir, "accelerator_state.json"), "w") as f:
                json.dump(meta, f)
        # per-process RNG bundle (every process writes its own, like the
        # reference's random_states_{i}.pkl)
        _pickle_to(os.path.join(tmp_dir, f"{RNG_STATE_NAME}_{process_index}.pkl"), rng_state)
        # async: stamp the true write duration (snapshot → files on disk)
        # now — the commit (and telemetry record) may happen much later.
        # Sync saves keep the full save_state duration measured at record
        # time (manifest + commit included).
        if commit_meta["is_async"]:
            commit_meta["write_seconds"] = time.perf_counter() - t0
        logger.info(f"Saved state to {tmp_dir} (pending commit to {output_dir})")

    accelerator.project_configuration.iteration += 1
    if async_save:
        _ASYNC_SAVE["pending_dirs"].update({output_dir, tmp_dir})
        _ASYNC_SAVE["pending_commit"] = (tmp_dir, output_dir, commit_meta)
        _ASYNC_SAVE["future"] = _async_executor().submit(_write_files)
        return output_dir

    _write_files()
    accelerator.wait_for_everyone()
    if is_main:
        _write_checkpoint_manifest(tmp_dir, commit_meta)
        _commit_checkpoint_dir(tmp_dir, output_dir)
    accelerator.wait_for_everyone()
    _record_checkpoint_telemetry("save", output_dir, commit_meta)
    return output_dir


_CHECKPOINT_DIR_RE = re.compile(r"^checkpoint_(\d+)$")


def _sorted_checkpoints(checkpoints_dir: str) -> list[str]:
    """Committed ``checkpoint_<i>`` dirs, oldest→newest. Entries with a
    non-numeric suffix — e.g. a ``checkpoint_12.tmp`` left by an
    interrupted save — are NOT checkpoints and are skipped instead of
    crashing the listing with a ``ValueError``."""
    if not os.path.isdir(checkpoints_dir):
        return []
    entries = []
    for d in os.listdir(checkpoints_dir):
        match = _CHECKPOINT_DIR_RE.match(d)
        if match:
            entries.append((int(match.group(1)), os.path.join(checkpoints_dir, d)))
    return [path for _, path in sorted(entries)]


def _piece_loader(input_dir: str):
    """``piece_entry → np.ndarray`` with a per-call cache of opened shard
    files (several pieces usually share one file)."""
    cache: dict[str, dict[str, np.ndarray]] = {}

    def load_piece(piece: dict) -> np.ndarray:
        rel = piece["file"]
        if rel not in cache:
            cache[rel] = load_array_dict(os.path.join(input_dir, rel))
        return cache[rel][piece["piece"]]

    return load_piece


@traced("checkpoint/restore")
def load_accelerator_state(accelerator, input_dir: str | None = None, **kwargs):
    """(Reference ``load_accelerator_state`` ``checkpointing.py:165``.)

    With ``input_dir=None`` the newest checkpoint whose manifest
    **validates** is selected — corrupt or partial ones (and ``.tmp`` dirs
    from interrupted saves) are skipped. Sharded checkpoints (see
    ``resilience/distributed.py``) are reassembled from their per-host
    shard files, onto the live arrays' shardings.
    """
    t0 = time.perf_counter()
    # an in-flight async save must land on EVERY process before ANY
    # process reads (each joins its own writer, then all meet)
    _join_writer_then_barrier(accelerator)
    if input_dir is None:
        from .resilience.manifest import find_latest_valid_checkpoint

        if accelerator.project_dir is None:
            raise ValueError("pass input_dir or set project_dir on the Accelerator")
        checkpoints_dir = os.path.join(accelerator.project_dir, "checkpoints")
        input_dir = find_latest_valid_checkpoint(checkpoints_dir)
        if input_dir is None:
            raise FileNotFoundError(f"no valid checkpoints under {checkpoints_dir}")

    from .resilience.manifest import read_manifest

    manifest = read_manifest(input_dir)
    if manifest is not None and manifest.get("kind") == "sharded":
        from .resilience.distributed import restore_tree_from_pieces

        load_piece = _piece_loader(input_dir)
        arrays = manifest.get("arrays", {})
        for i, model in enumerate(accelerator._models):
            model.params = restore_tree_from_pieces(
                model.params, arrays[f"{MODEL_NAME}_{i}"], load_piece
            )
        for i, opt in enumerate(accelerator._optimizers):
            opt.opt_state = restore_tree_from_pieces(
                opt.opt_state, arrays[f"{OPTIMIZER_NAME}_{i}"], load_piece
            )
    else:
        for i, model in enumerate(accelerator._models):
            suffix = "" if i == 0 else f"_{i}"
            flat = load_array_dict(os.path.join(input_dir, f"{MODEL_NAME}{suffix}"))
            model.params = _restore_tree_like(model.params, flat)
        for i, opt in enumerate(accelerator._optimizers):
            suffix = "" if i == 0 else f"_{i}"
            flat = load_array_dict(os.path.join(input_dir, f"{OPTIMIZER_NAME}{suffix}"))
            opt.opt_state = _restore_tree_like(opt.opt_state, flat)
    for i, sched in enumerate(accelerator._schedulers):
        path = os.path.join(input_dir, f"{SCHEDULER_NAME}{'' if i == 0 else f'_{i}'}.bin")
        with open(path, "rb") as f:
            sched.load_state_dict(pickle.load(f))
    for i, dl in enumerate(accelerator._dataloaders):
        path = os.path.join(input_dir, f"{SAMPLER_NAME}{'' if i == 0 else f'_{i}'}.bin")
        if os.path.exists(path):
            with open(path, "rb") as f:
                state = pickle.load(f)
            dl.load_state_dict(state)
    for i, obj in enumerate(accelerator._custom_objects):
        with open(os.path.join(input_dir, f"{CUSTOM_STATES_NAME}_{i}.pkl"), "rb") as f:
            obj.load_state_dict(pickle.load(f))
    scaler_file = os.path.join(input_dir, f"{SCALER_NAME}.bin")
    if getattr(accelerator, "_loss_scale", None) is not None and os.path.exists(scaler_file):
        with open(scaler_file, "rb") as f:
            accelerator._loss_scale.load_state_dict(pickle.load(f))
    state_file = os.path.join(input_dir, "accelerator_state.json")
    if os.path.exists(state_file):
        with open(state_file) as f:
            meta = json.load(f)
        accelerator.step = meta.get("step", 0)
        if "iteration" in meta:
            # resume the rotation counter past the loaded checkpoint so the
            # next save doesn't clobber history (reference ``load_state``
            # sets iteration = loaded + 1, ``accelerator.py:3227``)
            accelerator.project_configuration.iteration = meta["iteration"] + 1
    base = os.path.basename(os.path.normpath(input_dir))
    if base.startswith("checkpoint_"):
        accelerator.project_configuration.iteration = int(base.rsplit("_", 1)[-1]) + 1

    rng_file = os.path.join(input_dir, f"{RNG_STATE_NAME}_{accelerator.process_index}.pkl")
    if not os.path.exists(rng_file):
        rng_file = os.path.join(input_dir, f"{RNG_STATE_NAME}_0.pkl")
    if os.path.exists(rng_file):
        with open(rng_file, "rb") as f:
            _restore_rng_state(pickle.load(f))
    logger.info(f"Loaded state from {input_dir}")
    _record_checkpoint_telemetry(
        "restore",
        input_dir,
        {
            "t0": t0,
            "bytes": sum(f["bytes"] for f in manifest["files"].values()) if manifest else None,
            "shard_count": (
                sum(1 for d in os.listdir(input_dir) if d.startswith("shard_"))
                if manifest is not None and manifest.get("kind") == "sharded"
                else 0
            ),
            "is_async": False,
        },
    )
    return input_dir


# ---------------------------------------------------------------------------
# standalone model save (reference ``save_model`` ``accelerator.py:2823``)
# ---------------------------------------------------------------------------


def save_model_weights(accelerator, model, save_directory: str, max_shard_size="10GB", safe_serialization=True):
    os.makedirs(save_directory, exist_ok=True)
    from .modules import Model, PreparedModel

    if isinstance(model, (PreparedModel, Model)):
        flat = _flatten_tree(model.params)  # collective on all processes
    else:
        raise TypeError(f"cannot save {type(model)}")
    if not accelerator.is_main_process:
        accelerator.wait_for_everyone()
        return
    max_bytes = _parse_size(max_shard_size)
    shards = _shard_flat_dict(flat, max_bytes)
    if len(shards) == 1:
        save_array_dict(shards[0], os.path.join(save_directory, "model"), safe_serialization)
    else:
        index = {"metadata": {"total_size": sum(v.nbytes for v in flat.values())}, "weight_map": {}}
        ext = ".safetensors" if (safe_serialization and is_safetensors_available()) else ".npz"
        for i, shard in enumerate(shards):
            name = f"model-{i + 1:05d}-of-{len(shards):05d}"
            save_array_dict(shard, os.path.join(save_directory, name), safe_serialization)
            for key in shard:
                index["weight_map"][key] = name + ext
        # HF-convention index name for safetensors
        # (`model.safetensors.index.json`: what merge-weights and the
        # device-map checkpoint reader consume — reference
        # utils/modeling.py:1636-1794 reads the same file); npz shards
        # keep the legacy `model.index.json` every reader already probes
        index_name = "model.safetensors.index.json" if ext == ".safetensors" else "model.index.json"
        with open(os.path.join(save_directory, index_name), "w") as f:
            json.dump(index, f, indent=2)
    accelerator.wait_for_everyone()


def _parse_size(size) -> int:
    if isinstance(size, int):
        return size
    size = str(size).upper().strip()
    for unit, mul in (("GB", 2**30), ("MB", 2**20), ("KB", 2**10)):
        if size.endswith(unit):
            return int(float(size[: -len(unit)]) * mul)
    return int(size)


def _shard_flat_dict(flat: dict[str, np.ndarray], max_bytes: int) -> list[dict]:
    shards, current, size = [], {}, 0
    for key, value in flat.items():
        if current and size + value.nbytes > max_bytes:
            shards.append(current)
            current, size = {}, 0
        current[key] = value
        size += value.nbytes
    if current:
        shards.append(current)
    return shards


def save_object(obj, path, safe_serialization=False):
    """(Reference ``utils/other.py:182`` ``save``.)"""
    with open(path, "wb") as f:
        pickle.dump(obj, f)
