"""Device-mesh construction over TPU topology (ICI within slice, DCN across).

This module is the TPU-native replacement for the reference's backend
selection + process-group init (``/root/reference/src/accelerate/state.py:710-767``
and ``state.py:194-252``): instead of picking a torch.distributed backend and
calling ``init_process_group``, we call ``jax.distributed.initialize`` (when
multi-host) and build a named ``jax.sharding.Mesh`` whose axes —
``('dp', 'fsdp', 'ep', 'cp', 'tp')`` — are the only parallelism vocabulary
the rest of the framework speaks.

Axis-order rationale (the scaling-book recipe): the leftmost mesh dimension
changes slowest across the physical device order, so putting ``dp`` first
keeps pure-replica traffic on the slice boundary (DCN-tolerant) while
``tp``/``cp`` — which carry per-layer collectives — map onto adjacent
chips' ICI links.
"""

from __future__ import annotations

import logging
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .utils.dataclasses import MESH_AXIS_ORDER, MeshPlugin

logger = logging.getLogger(__name__)

P = PartitionSpec


def device_topology() -> dict:
    """Probe the attached JAX topology (reference analog: the env-var rank
    bookkeeping in ``state.py:254-275``)."""
    devices = jax.devices()
    return {
        "num_devices": len(devices),
        "local_device_count": jax.local_device_count(),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "platform": devices[0].platform if devices else "none",
        "device_kind": devices[0].device_kind if devices else "none",
    }


def build_mesh(plugin: MeshPlugin | None = None, devices: Sequence | None = None) -> Mesh:
    """Build the named mesh from a :class:`MeshPlugin` shape declaration.

    Uses ``mesh_utils.create_device_mesh`` so the physical ICI torus is
    respected where possible; falls back to a plain reshape for host
    platforms / odd shapes.
    """
    plugin = plugin or MeshPlugin()
    if devices is None:
        devices = plugin.devices if plugin.devices is not None else jax.devices()
    devices = list(devices)
    sizes = plugin.axis_sizes(len(devices))
    shape = tuple(sizes[ax] for ax in MESH_AXIS_ORDER)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            shape, devices=np.asarray(devices),
            allow_split_physical_axes=plugin.allow_split_physical_axes,
        )
    except (ValueError, AssertionError, TypeError) as e:  # host platform / exotic shapes
        logger.debug("create_device_mesh failed (%s); falling back to reshape", e)
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXIS_ORDER)


def single_device_mesh(device=None) -> Mesh:
    """Degenerate 1-device mesh so single-chip code paths are shape-identical
    to sharded ones (everything is a NamedSharding; no special cases)."""
    device = device or jax.devices()[0]
    dev_array = np.asarray([device]).reshape((1,) * len(MESH_AXIS_ORDER))
    return Mesh(dev_array, MESH_AXIS_ORDER)


def data_sharding(mesh: Mesh, *, extra_axes: tuple[str, ...] = ("fsdp",)) -> NamedSharding:
    """Sharding for a global batch: leading (batch) dim split over every
    data-like axis — ``dp`` plus ``fsdp`` (and ``ep`` when experts act as
    data parallel for the dense parts). This is the TPU-native equivalent of
    the reference's per-rank ``BatchSamplerShard`` slice."""
    axes = tuple(ax for ax in ("dp",) + tuple(extra_axes) if mesh.shape[ax] >= 1)
    return NamedSharding(mesh, P(axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_axis_sizes(mesh: Mesh, trivial: bool = False) -> dict[str, int]:
    """``{axis: size}`` for the mesh — by default only the non-trivial axes
    (size > 1), the form telemetry/serving stats record so a reader sees
    "fsdp=2, tp=2" instead of five 1s."""
    return {
        str(ax): int(n)
        for ax, n in mesh.shape.items()
        if trivial or int(n) > 1
    }


def device_hbm_bytes(device=None) -> int | None:
    """Per-device accelerator memory limit in bytes, or ``None`` when the
    backend doesn't report one (CPU; some older runtimes). The shard-check
    capacity model's default budget: on a real TPU ``serve --auto-blocks``
    can size the pool without the operator looking up the chip's HBM."""
    try:
        device = device or jax.local_devices()[0]
        stats = device.memory_stats() or {}
    except Exception:
        return None
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    return int(limit) if limit else None


def batch_axis_size(mesh: Mesh, extra_axes: tuple[str, ...] = ("fsdp",)) -> int:
    """Number of ways the global batch is split (the 'dp world size')."""
    n = mesh.shape["dp"]
    for ax in extra_axes:
        n *= mesh.shape[ax]
    return n


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bring-up — the ``init_process_group`` analog. Reads the
    same env contract the launcher writes (``ACCELERATE_COORDINATOR_ADDR``
    etc.; reference: MASTER_ADDR/RANK envs consumed at ``state.py:214-249``).
    No-op when single-host or already initialized."""
    coordinator_address = coordinator_address or os.environ.get("ACCELERATE_COORDINATOR_ADDR")
    if num_processes is None:
        env = os.environ.get("ACCELERATE_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("ACCELERATE_PROCESS_ID")
        process_id = int(env) if env else None
    if coordinator_address is None:
        # No coordinator: the only recoverable multi-process case is a real
        # TPU pod, where jax.distributed.initialize() with all-None args
        # auto-detects the rendezvous from the TPU metadata server. Anywhere
        # else (stale ACCELERATE_NUM_PROCESSES export, CPU repro of a pod
        # config) stay a single-process no-op as before.
        on_tpu_vm = os.path.exists("/dev/accel0") or any(
            k in os.environ for k in ("TPU_WORKER_ID", "CLOUD_TPU_TASK_ID", "TPU_WORKER_HOSTNAMES")
        )
        if not (num_processes and num_processes > 1 and on_tpu_vm):
            return
        if jax._src.distributed.global_state.client is not None:  # already up
            return
        jax.distributed.initialize()
        return
    if jax._src.distributed.global_state.client is not None:  # already up
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
