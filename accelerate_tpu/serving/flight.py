"""Per-iteration flight recorder: host/device time attribution for the
decode loop.

Every :meth:`InferenceEngine.step` iteration is decomposed into
**exclusive, telescoping phases** — consecutive ``perf_counter`` stamps,
so the phase durations sum to the measured iteration wall time *exactly*
(modulo float ulp; :meth:`FlightRecorder.record` asserts the invariant
rather than logging it):

``schedule``
    admission, eviction, radix lookups, deadline sweeps — pure host work.
``prefill``
    chunked prefill dispatch + its harvest for every prefilling slot.
``dispatch``
    building the decode operands and handing the (single) compiled decode
    executable to the runtime — host work again.
``device_wait``
    the blocking ``device_get`` in ``_harvest_inflight`` — the *residual*
    sync the host could not hide behind its own work, and (together with
    ``overlap_hidden_s``) the denominator of every "is the accelerator
    actually busy?" question.
``harvest``
    token emission, finish bookkeeping, telemetry — host work.

``host_fraction`` = 1 − (device_wait + overlap_hidden) / wall over the
recorded window: the ROADMAP item-5 measurement ("host-scheduling time
leaving the per-token critical path"). Under the double-buffered engine
host phases can run *while a decode round is in flight on device*; such
intervals are still attributed to their phase (the vocabulary stays
exclusive and telescoping) but are additionally accumulated into the
per-iteration ``overlap_hidden_s`` stat, because they are off the
critical path — the device was busy the whole time. ``device_wait`` is
then only the *residual* sync the host could not hide. With the
synchronous engine ``overlap_hidden_s`` is identically 0.0 and the
formula reduces to the old 1 − device_wait / wall.

The recorder is a process-global active object with the same discipline
as ``get_tracer()``: the engine holds a direct reference (zero reads per
iteration when armed), external consumers (watchdog HANG_REPORT, the
``/profile`` window dump) take ONE :func:`get_active_flight_recorder`
read, and the disabled path is a single ``is None`` check per iteration.

This module imports **no jax** at module scope — the diagnostics readers
and the jax-free ``accelerate-tpu profile`` CLI may import it from any
host. Only :func:`capture_profile_window` (the on-demand profiler) pulls
jax in, lazily, inside the serving process that already has it.
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque

#: the exclusive phases, in stamp order — ``record()`` requires exactly
#: these keyword arguments and the metrics/trace surfaces label by them
ITERATION_PHASES = ("schedule", "prefill", "dispatch", "device_wait", "harvest")

_active_flight_recorder = None


def get_active_flight_recorder():
    """The process-global recorder (None when no engine armed one) — the
    single read external consumers (watchdog, profiler dump) pay."""
    return _active_flight_recorder


def set_active_flight_recorder(recorder) -> None:
    global _active_flight_recorder
    _active_flight_recorder = recorder


class FlightRecorder:
    """Bounded ring of per-iteration phase breakdowns + cumulative
    totals. Ring entries answer "what were the last K iterations doing"
    (HANG_REPORT, ``trace tail --iterations`` windows, the ``/profile``
    dump); the cumulative totals answer "what is the run's host share"
    (``stats()['host_fraction']``) without rescanning the ring."""

    def __init__(self, history: int = 256):
        self.history = max(1, int(history))
        self._ring: deque[dict] = deque(maxlen=self.history)
        #: what the engine is doing *right now* — updated at phase
        #: boundaries so a wedged engine's HANG_REPORT names the phase it
        #: died in, not just the last completed iteration
        self.current_phase = "idle"
        self.reset()

    def reset(self) -> None:
        """Zero the measurement window (``reset_stats()`` folds this in:
        a warmup→reset→measure cycle reports only post-reset
        iterations for both the ring and the cumulative fractions)."""
        self._ring.clear()
        self.iterations = 0
        self.wall_total_s = 0.0
        self.overlap_hidden_total_s = 0.0
        self.phase_totals_s = {p: 0.0 for p in ITERATION_PHASES}
        self.current_phase = "idle"

    def record(self, iteration: int, t_start: float, wall_s: float,
               overlap_hidden_s: float = 0.0, **phases: float) -> dict:
        """Append one iteration. ``phases`` must cover exactly
        :data:`ITERATION_PHASES` and sum to ``wall_s`` — the stamps
        telescope (each phase is the diff of consecutive perf_counter
        reads), so a mismatch means a stamp was dropped or double-counted
        and the attribution is garbage. Asserted, not logged.

        ``overlap_hidden_s`` is *not* a sixth phase: it re-counts the
        portion of the host phases that ran under an in-flight dispatch
        (double-buffered engine), so it is bounded by
        ``wall_s − device_wait`` — also asserted."""
        if set(phases) != set(ITERATION_PHASES):
            raise AssertionError(
                f"flight phases {sorted(phases)} != {sorted(ITERATION_PHASES)}"
            )
        total = sum(phases.values())
        # telescoping stamps sum exactly; the tolerance only absorbs float
        # ulp on the subtraction chain, never a real accounting hole
        if not math.isclose(total, wall_s, rel_tol=1e-9, abs_tol=1e-6):
            raise AssertionError(
                f"flight phase sum {total!r} != iteration wall {wall_s!r} "
                f"({ {p: phases[p] for p in ITERATION_PHASES} })"
            )
        overlap_hidden_s = float(overlap_hidden_s)
        host_s = wall_s - phases["device_wait"]
        if not (-1e-6 <= overlap_hidden_s <= host_s + 1e-6):
            raise AssertionError(
                f"overlap_hidden_s {overlap_hidden_s!r} outside "
                f"[0, wall - device_wait = {host_s!r}]"
            )
        entry = {"iteration": int(iteration), "t_start": float(t_start),
                 "wall_s": float(wall_s),
                 "overlap_hidden_s": overlap_hidden_s}
        for p in ITERATION_PHASES:
            entry[f"{p}_s"] = float(phases[p])
            self.phase_totals_s[p] += float(phases[p])
        self._ring.append(entry)
        self.iterations += 1
        self.wall_total_s += float(wall_s)
        self.overlap_hidden_total_s += overlap_hidden_s
        return entry

    def __len__(self) -> int:
        return len(self._ring)

    def tail(self, k: int = 8) -> list[dict]:
        """Newest-last last-``k`` ring entries (crash forensics)."""
        if k <= 0:
            return []
        return list(self._ring)[-k:]

    def window(self, since_perf_t: float) -> list[dict]:
        """Ring entries whose iteration started at/after a perf_counter
        stamp — the ``/profile?seconds=N`` capture window."""
        return [e for e in self._ring if e["t_start"] >= since_perf_t]

    def host_fraction(self) -> float:
        """1 − (device_wait + overlap_hidden)/wall over everything
        recorded since reset — host time *on the critical path*. Hidden
        overlap counts as device time: the accelerator was busy under it.
        Cumulative, so it matches ``trace tail --iterations`` computed
        over the same iterations."""
        if self.wall_total_s <= 0.0:
            return 0.0
        hidden = (
            self.phase_totals_s["device_wait"] + self.overlap_hidden_total_s
        )
        return max(0.0, 1.0 - hidden / self.wall_total_s)

    def _percentiles(self, values: list[float]) -> dict:
        # no numpy on purpose: jax-free consumers import this module
        vs = sorted(values)
        n = len(vs)

        def pct(q: float) -> float:
            if n == 1:
                return vs[0]
            pos = q * (n - 1)
            lo = int(pos)
            hi = min(lo + 1, n - 1)
            return vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)

        return {"p50": pct(0.50), "p99": pct(0.99)}

    def telemetry_fields(self) -> dict:
        """Flat fields for the telemetry step row (and via ingest, the
        metrics gauges) — cheap cumulative reads only."""
        if not self._ring:
            return {}
        walls = [e["wall_s"] for e in self._ring]
        pw = self._percentiles(walls)
        return {
            "host_fraction": self.host_fraction(),
            "iteration_p50_s": pw["p50"],
            "iteration_p99_s": pw["p99"],
            "overlap_hidden_s": self.overlap_hidden_total_s,
            "flight_phase": self.current_phase,
        }

    def summary(self) -> dict:
        """``stats()`` fields: the flat telemetry keys plus per-phase
        p50/p99 over the ring window. Empty when nothing recorded."""
        if not self._ring:
            return {}
        out = self.telemetry_fields()
        out["flight_window"] = len(self._ring)
        out["iteration_phases_s"] = {
            p: self._percentiles([e[f"{p}_s"] for e in self._ring])
            for p in ITERATION_PHASES
        }
        return out


def capture_profile_window(logging_dir: str, seconds: float,
                           engine=None) -> dict:
    """On-demand windowed profiling: run ``jax.profiler`` for
    ``seconds`` against the live process and dump the flight-recorder
    entries that landed inside the window, both under
    ``<logging_dir>/profiles/profile_<stamp>_<pid>/``. The engine (when
    passed) keeps serving from its own thread — this call only sleeps.

    Returns a manifest dict (also written as ``manifest.json``) naming
    the artifacts so ``trace merge`` / the ``profile`` CLI can report
    them without globbing jax's internal layout."""
    import jax  # lazy: this is the only jax touch in the module

    seconds = float(seconds)
    stamp = time.strftime("%Y%m%d_%H%M%S")
    profile_dir = os.path.join(
        logging_dir, "profiles", f"profile_{stamp}_{os.getpid()}"
    )
    os.makedirs(profile_dir, exist_ok=True)

    fl = None
    if engine is not None:
        fl = getattr(engine, "_flight", None)
    if fl is None:
        fl = get_active_flight_recorder()

    start_perf = time.perf_counter()
    iters_before = fl.iterations if fl is not None else 0
    jax.profiler.start_trace(profile_dir)
    try:
        time.sleep(seconds)
    finally:
        jax.profiler.stop_trace()
    elapsed = time.perf_counter() - start_perf

    window = fl.window(start_perf) if fl is not None else []
    flight_path = os.path.join(profile_dir, "flight_window.json")
    with open(flight_path, "w") as f:
        json.dump(
            {
                "seconds_requested": seconds,
                "seconds_measured": elapsed,
                "iterations": len(window),
                "iterations_before": iters_before,
                "host_fraction": fl.host_fraction() if fl is not None else None,
                "phases": list(ITERATION_PHASES),
                "entries": window,
            },
            f, indent=2,
        )

    artifacts = [flight_path]
    for root, _dirs, files in os.walk(profile_dir):
        for name in files:
            p = os.path.join(root, name)
            if p not in artifacts:
                artifacts.append(p)

    manifest = {
        "profile_dir": profile_dir,
        "seconds": elapsed,
        "flight_iterations": len(window),
        "host_fraction": fl.host_fraction() if fl is not None else None,
        "artifacts": sorted(artifacts),
    }
    with open(os.path.join(profile_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)

    from ..telemetry import get_active_recorder

    tel = get_active_recorder()
    if tel:
        tel.record_serving(
            kind="profile", profile_dir=profile_dir, seconds=elapsed,
            flight_iterations=len(window),
        )
    return manifest
