"""Radix prefix-sharing cache + host-DRAM swap pool for the serving engine.

Production chat traffic shares long prompt prefixes (system prompts,
few-shot preambles, multi-turn history). The block-paged KV pool is the
natural substrate for SGLang/RadixAttention-style sharing: KV for a token
prefix depends only on the prefix's tokens, so two requests whose prompts
agree on the first ``k`` blocks can *read the same pool blocks* — admission
maps the shared blocks into the new request's block table at refcount+1 and
chunk-prefills only the tail.

:class:`RadixCache` owns the host-side bookkeeping (pure Python, no JAX —
the engine performs the device ops it requests):

* a **radix trie** over full token blocks: each node is one ``block_size``
  token span keyed by its exact token tuple (dict hashing of the tuple is
  the "per-block token hash"; matching is exact, never probabilistic);
* **refcounts** (:class:`~.blocks.BlockAllocator`): every cached block
  carries the cache's own reference, plus one per live request mapping it —
  a block leaves the pool only when the last holder lets go;
* **copy-on-write on partial-block divergence**: when a prompt agrees with
  a cached child for only the first ``p`` tokens of a block, the matched
  rows are reused by *copying* the cached block into a freshly allocated
  private block (the engine runs the device copy) — the cached block is
  pinned (incref) across the copy so concurrent eviction can never free it
  first, and the diverging request then overwrites its private copy's tail;
* **LRU eviction**: cached blocks whose only holder is the cache
  (refcount 1) are reclaimable; eviction walks trie *leaves* in
  least-recently-matched order back to the freelist, so hot shared prefixes
  survive pool pressure and admission/decode growth only fails when the
  pool is genuinely full of live data.

A request's matched prefix is capped at ``prompt_len - 1`` tokens: the
engine derives the first output token from the final prompt position's
logits, so at least one prompt token is always prefilled even on a 100% hit.

:class:`SwapPool` is the preemption tier: a capacity-bounded host-DRAM
(NumPy) mirror of the device pool's block layout. Under pool exhaustion the
scheduler's victim has its unshared blocks ``jax.device_get``-swapped here,
its slot is released, and it re-queues at the front of its priority class;
re-admission swaps the rows back into freshly allocated blocks. This is the
HBM↔host-DRAM tier walk ``big_modeling`` applies to params, with the KV
cache as the second tenant — ``finish_reason="out_of_blocks"`` becomes the
last resort for when even swap capacity is gone.
"""

from __future__ import annotations

import heapq

import numpy as np

from .blocks import BlockAllocator


class RadixNode:
    """One cached full block: ``tokens`` (exact ``block_size`` ids),
    ``block`` (pool id), children keyed by their token tuples."""

    __slots__ = ("tokens", "block", "parent", "children", "last_used")

    def __init__(self, tokens: tuple, block: int, parent: "RadixNode | None"):
        self.tokens = tokens
        self.block = block
        self.parent = parent
        self.children: dict[tuple, RadixNode] = {}
        self.last_used = 0


class RadixCache:
    """Refcounted prefix trie over the block pool (see module docstring).

    The cache holds exactly one reference on every cached block; requests
    add theirs via :meth:`acquire` and drop them through the scheduler's
    normal ``decref`` release. ``match`` is a pure query; ``acquire`` is
    the committing form (increfs + LRU touch) and must be paired with
    :meth:`release_acquired` if admission backs out."""

    def __init__(self, allocator: BlockAllocator, block_size: int):
        self.allocator = allocator
        self.block_size = int(block_size)
        self.root = RadixNode((), -1, None)
        self._cached_blocks: set[int] = set()
        self._tick = 0
        # cache-churn counters, surfaced via engine.stats() — hit tokens
        # live on the scheduler (the admission-time source of truth), not
        # here, so there is exactly one counter to trust
        self.evicted_blocks = 0
        self.inserted_blocks = 0

    # -- queries -------------------------------------------------------------

    def _nodes(self) -> list[RadixNode]:
        out, stack = [], list(self.root.children.values())
        while stack:
            n = stack.pop()
            out.append(n)
            stack.extend(n.children.values())
        return out

    @property
    def cached_block_count(self) -> int:
        return len(self._nodes())

    def exclusive_block_count(self) -> int:
        """Blocks held *only* by the cache (refcount 1) — the evictable
        set, and the idle-engine complement of the freelist."""
        return sum(1 for n in self._nodes() if self.allocator.refcount(n.block) == 1)

    def is_cached(self, block: int) -> bool:
        """True while ``block`` backs a trie node. The engine's swap path
        uses this to tell "shared with the cache only" (swappable: drop
        the request's ref, the cache's evictable copy stays) from "shared
        with another live request" (stays resident)."""
        return block in self._cached_blocks

    def match(self, tokens) -> tuple[list[int], int, int | None]:
        """Longest cached prefix of ``tokens``, capped at ``len - 1``:
        returns ``(full_blocks, matched_tokens, cow_src_block)`` without
        side effects. ``cow_src_block`` is the cached block a partial-block
        match would copy from (None when the match is block-aligned)."""
        bs = self.block_size
        limit = len(tokens) - 1  # final prompt token is always prefilled
        node, blocks, matched = self.root, [], 0
        while matched + bs <= limit:
            child = node.children.get(tuple(int(t) for t in tokens[matched : matched + bs]))
            if child is None:
                break
            node = child
            blocks.append(child.block)
            matched += bs
        # partial-block divergence: reuse the longest common prefix of one
        # child via copy-on-write (p < block_size by construction)
        cow_src = None
        best_p = 0
        room = min(bs, limit - matched)
        if room > 0:
            tail = [int(t) for t in tokens[matched : matched + bs]]
            for key, child in node.children.items():
                p = 0
                for a, b in zip(key, tail):
                    if a != b or p >= room:
                        break
                    p += 1
                if p > best_p:
                    best_p, cow_src = p, child.block
        if best_p > 0:
            matched += best_p
        else:
            cow_src = None
        return blocks, matched, cow_src

    # -- admission-side commits ----------------------------------------------

    def acquire(self, tokens) -> tuple[list[int], int, int | None]:
        """Committing :meth:`match`: increfs the matched full blocks for
        the request AND pins the CoW source (one extra ref the engine drops
        after the device copy), and touches the matched path's LRU clock.
        Back out with :meth:`release_acquired` if admission fails."""
        blocks, matched, cow_src = self.match(tokens)
        self._tick += 1
        bs = self.block_size
        node = self.root
        for i in range(len(blocks)):
            node = node.children[tuple(int(t) for t in tokens[i * bs : (i + 1) * bs])]
            node.last_used = self._tick
        self.allocator.incref(blocks)
        if cow_src is not None:
            self.allocator.incref([cow_src])
            # the CoW source is a HIT too: touch its clock, or a prefix
            # that always ends mid-block (hit on every admission) looks
            # least-recently-used to evict() and dies first
            for child in node.children.values():
                if child.block == cow_src:
                    child.last_used = self._tick
                    break
        return blocks, matched, cow_src

    def release_acquired(self, blocks: list[int], cow_src: int | None = None) -> None:
        self.allocator.decref(list(blocks))
        if cow_src is not None:
            self.allocator.decref([cow_src])

    def insert(self, tokens, blocks: list[int]) -> int:
        """Adopt a prefilled request's full prompt blocks into the trie.
        ``blocks`` is the request's block list; every block fully covered
        by ``tokens`` is cacheable. Existing nodes are kept (the request's
        duplicate block stays private); new nodes take the request's block
        at refcount+1 (the cache's own reference). Returns the number of
        newly cached blocks."""
        bs = self.block_size
        self._tick += 1
        node, added = self.root, 0
        for i in range(len(tokens) // bs):
            key = tuple(int(t) for t in tokens[i * bs : (i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                child = RadixNode(key, blocks[i], node)
                node.children[key] = child
                self.allocator.incref([blocks[i]])
                self._cached_blocks.add(blocks[i])
                added += 1
            child.last_used = self._tick
            node = child
        self.inserted_blocks += added
        return added

    # -- eviction ------------------------------------------------------------

    def evict(self, want_blocks: int) -> int:
        """Free up to ``want_blocks`` cached blocks back to the freelist,
        least-recently-matched leaves first (a parent is only reclaimable
        once its children are gone — the trie stays a valid prefix tree).
        Blocks any live request still maps (refcount > 1) are skipped.
        Returns how many blocks were actually freed.

        One trie walk seeds a min-heap of evictable leaves; a parent whose
        last child falls joins the heap then — O(n + k log n) per call,
        not a rescan per freed block (refcounts cannot change mid-call:
        eviction runs between engine iterations, on one thread)."""
        if want_blocks <= 0:
            return 0
        heap = [
            (n.last_used, id(n), n)
            for n in self._nodes()
            if not n.children and self.allocator.refcount(n.block) == 1
        ]
        heapq.heapify(heap)
        freed = 0
        while freed < want_blocks and heap:
            _, _, victim = heapq.heappop(heap)
            victim.parent.children.pop(victim.tokens, None)
            self._cached_blocks.discard(victim.block)
            self.allocator.decref([victim.block])
            freed += 1
            parent = victim.parent
            if (
                parent is not self.root
                and not parent.children
                and self.allocator.refcount(parent.block) == 1
            ):
                heapq.heappush(heap, (parent.last_used, id(parent), parent))
        self.evicted_blocks += freed
        return freed


class SwapPool:
    """Capacity-bounded host-DRAM mirror of the device pool's block layout:
    one K row and one V row of shape ``[layers, block_size, n_kv, hd]`` per
    slot, same dtype as the device pool (bf16 rides ``ml_dtypes``). The
    engine ``jax.device_get``s a victim's unshared blocks in, and scatters
    them back out on re-admission; ``capacity_gb`` bounds the mirror so a
    preemption storm degrades to the old truncation behaviour instead of
    OOM-ing the host."""

    def __init__(
        self,
        num_layers: int,
        block_size: int,
        num_kv_heads: int,
        head_dim: int,
        dtype,
        capacity_gb: float,
        quantized: bool = False,
    ):
        self.block_shape = (int(num_layers), int(block_size), int(num_kv_heads), int(head_dim))
        self.dtype = np.dtype(dtype)
        self.quantized = bool(quantized)
        per_block = 2 * self.dtype.itemsize * int(np.prod(self.block_shape))  # K + V
        if self.quantized:
            # f32 amax scale rows ([layers, bs, n_kv] for K and V) park
            # beside the payload: a quantized block is meaningless without
            # them, and they must survive the round trip byte-exact
            self.scale_shape = self.block_shape[:-1]
            per_block += 2 * 4 * int(np.prod(self.scale_shape))
        self.bytes_per_block = per_block
        self.capacity_blocks = max(0, int(capacity_gb * (1 << 30)) // per_block)
        self._k = np.zeros((self.capacity_blocks, *self.block_shape), self.dtype)
        self._v = np.zeros_like(self._k)
        if self.quantized:
            self._ks = np.zeros((self.capacity_blocks, *self.scale_shape), np.float32)
            self._vs = np.zeros_like(self._ks)
        self._free = list(range(self.capacity_blocks - 1, -1, -1))
        self._held: set[int] = set()

    @property
    def used_blocks(self) -> int:
        return len(self._held)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def can_hold(self, n_blocks: int) -> bool:
        return n_blocks <= len(self._free)

    def store(self, k_rows, v_rows, k_scale_rows=None, v_scale_rows=None) -> int:
        """Park one block's K/V rows (+ scale rows when quantized);
        returns the swap handle."""
        if not self._free:
            raise RuntimeError(
                f"swap pool exhausted ({self.capacity_blocks} blocks, "
                f"{self.bytes_per_block} B each): raise swap_gb"
            )
        if self.quantized and (k_scale_rows is None or v_scale_rows is None):
            raise ValueError("quantized swap pool needs scale rows on store()")
        slot = self._free.pop()
        self._k[slot] = np.asarray(k_rows, self.dtype)
        self._v[slot] = np.asarray(v_rows, self.dtype)
        if self.quantized:
            self._ks[slot] = np.asarray(k_scale_rows, np.float32)
            self._vs[slot] = np.asarray(v_scale_rows, np.float32)
        self._held.add(slot)
        return slot

    def load(self, handle: int):
        """``(k, v, k_scale, v_scale)`` — the scale pair is ``None`` for
        non-quantized pools."""
        if handle not in self._held:
            raise ValueError(f"swap handle {handle} is not held")
        if self.quantized:
            return self._k[handle], self._v[handle], self._ks[handle], self._vs[handle]
        return self._k[handle], self._v[handle], None, None

    def release(self, handle: int) -> None:
        if handle not in self._held:
            raise ValueError(f"double release of swap handle {handle}")
        self._held.remove(handle)
        self._free.append(handle)
