"""Seeded fault-injection harness for the serving fleet.

Chaos engineering for the router/replica boundary: a replica started with
``accelerate-tpu serve --chaos-spec SPEC`` (or ``ACCELERATE_CHAOS_SPEC``)
injects a *deterministic* schedule of faults keyed on its own ``/generate``
request ordinal — the same spec against the same trace produces the same
failure sequence, so a chaos run is a regression test, not a dice roll.
Faults land at the replica boundary (the HTTP front end), never inside the
engine: the engine's invariants are what the chaos run is *checking*, so
the harness must not reach around them.

Fault grammar (``;``- or ``,``-separated entries; ``rK:`` scopes an entry
to the replica whose ``--replica-id`` is ``K``, unscoped entries apply to
every replica)::

    seed=7              # seeds the jittered-delay RNG (default 0)
    r0:kill@5           # SIGKILL self when generate request #5 arrives
    r0:stop@3           # SIGSTOP self at request #3 (wedged until killed)
    r0:stop@3:2.5       # same, but a detached helper SIGCONTs after 2.5s
    r1:delay@4:0.25     # sleep 0.25s before serving request #4
    r1:delay@4:0.1..0.5 # seeded uniform delay in [0.1, 0.5) at request #4
    err503@2:3          # answer HTTP 503 to requests #2, #3, #4
    blackout@6:1.5      # /healthz goes dark for 1.5s once request #6 lands
    blackout@0:4.0      # /healthz dark for the first 4.0s after startup

Ordinals are 1-based over the requests the front end *receives* (``@0`` is
"at startup", meaningful only for ``blackout``). ``kill`` and ``stop``
fire before the request is admitted, so the router observes exactly what a
production crash looks like: a torn connection with requests in flight.

The module is pure stdlib and jax-free, like the rest of the router side —
``benchmarks/chaos_smoke.py`` and ``tests/test_chaos.py`` drive real serve
processes with these specs and assert the fleet invariants (every request
answered exactly once, no orphaned processes, recovery to the target
replica count) that make the self-healing story honest.
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass

#: fault kinds the injector knows how to apply
FAULT_KINDS = ("kill", "stop", "delay", "err503", "blackout")

#: environment variables the serve front end consults when --chaos-spec is
#: absent (the route CLI forwards the flag; a fleet can also flip chaos on
#: without touching any command line)
CHAOS_SPEC_ENV = "ACCELERATE_CHAOS_SPEC"
CHAOS_SEED_ENV = "ACCELERATE_CHAOS_SEED"


@dataclass(frozen=True)
class Fault:
    """One scheduled fault. ``at_request`` is the 1-based ordinal of the
    triggering ``/generate`` request (0 = at startup); ``arg``/``arg2`` are
    the kind-specific parameters (seconds, counts, or a delay range)."""

    kind: str
    at_request: int
    arg: float | None = None
    arg2: float | None = None
    replica: int | None = None  # None = applies to every replica


class ChaosSpecError(ValueError):
    """Malformed chaos spec — raised at parse time so a typo fails the
    bring-up loudly instead of silently running a clean (faultless) test."""


def _parse_entry(entry: str) -> Fault:
    replica = None
    body = entry
    if body[:1] == "r":
        scope, sep, rest = body.partition(":")
        if sep and scope[1:].isdigit():
            replica = int(scope[1:])
            body = rest
    kind, at, args = body, None, []
    if "@" in body:
        kind, _, tail = body.partition("@")
        parts = tail.split(":")
        at = parts[0]
        args = parts[1:]
    if kind not in FAULT_KINDS:
        raise ChaosSpecError(
            f"unknown chaos fault {kind!r} in {entry!r}: expected one of {FAULT_KINDS}"
        )
    try:
        at_request = int(at)
        if at_request < 0:
            raise ValueError
    except (TypeError, ValueError):
        raise ChaosSpecError(
            f"chaos fault {entry!r} needs a non-negative request ordinal after '@'"
        ) from None
    arg = arg2 = None
    if args:
        if len(args) > 1:
            raise ChaosSpecError(f"too many ':' arguments in chaos fault {entry!r}")
        raw = args[0]
        try:
            if ".." in raw:  # seeded uniform range, delay only
                lo, hi = raw.split("..", 1)
                arg, arg2 = float(lo), float(hi)
                if not (0 <= arg <= arg2):
                    raise ValueError
            else:
                arg = float(raw)
                if arg < 0:
                    raise ValueError
        except ValueError:
            raise ChaosSpecError(
                f"chaos fault {entry!r}: malformed argument {raw!r}"
            ) from None
    if kind in ("delay", "err503", "blackout") and arg is None:
        raise ChaosSpecError(f"chaos fault {entry!r} needs an argument (':X')")
    if arg2 is not None and kind != "delay":
        raise ChaosSpecError(f"chaos fault {entry!r}: ranges only apply to delay")
    if kind != "blackout" and at_request == 0:
        raise ChaosSpecError(
            f"chaos fault {entry!r}: ordinal 0 (startup) only applies to blackout"
        )
    return Fault(kind=kind, at_request=at_request, arg=arg, arg2=arg2, replica=replica)


def parse_chaos_spec(spec: str) -> tuple[int, list[Fault]]:
    """Parse a spec string into ``(seed, faults)``. Raises
    :class:`ChaosSpecError` on any malformed entry."""
    seed, faults = 0, []
    for raw in spec.replace(",", ";").split(";"):
        entry = raw.strip()
        if not entry:
            continue
        if entry.startswith("seed="):
            try:
                seed = int(entry[5:])
            except ValueError:
                raise ChaosSpecError(f"malformed chaos seed {entry!r}") from None
            continue
        faults.append(_parse_entry(entry))
    return seed, faults


class ChaosInjector:
    """Applies one replica's slice of a chaos schedule.

    The serve front end calls :meth:`on_generate` once per received
    ``/generate`` request (before admission) and
    :meth:`healthz_blackout` on every ``/healthz`` probe. Everything is
    counted under a lock — the HTTP server is threaded — and the RNG is
    seeded with ``seed`` folded with the replica id, so two replicas
    sharing a spec draw distinct but reproducible jitter.
    """

    def __init__(self, faults: list[Fault], seed: int = 0, replica_id: int | None = None):
        self.replica_id = replica_id
        mine = [
            f for f in faults
            if f.replica is None or replica_id is None or f.replica == replica_id
        ]
        # fold the replica id into the seed: replicas sharing a spec draw
        # distinct but reproducible jitter streams
        from ..analysis.lockwatch import maybe_watch

        self._rng = random.Random(int(seed) * 1_000_003 + (replica_id or 0))
        self._lock = maybe_watch(threading.Lock(), "ChaosInjector._lock")
        self._requests = 0
        self._kills = {f.at_request for f in mine if f.kind == "kill"}
        self._stops = {f.at_request: f.arg for f in mine if f.kind == "stop"}
        self._delays = {
            f.at_request: (f.arg, f.arg2) for f in mine if f.kind == "delay"
        }
        # err503@N:K covers ordinals N .. N+K-1
        self._err503: set[int] = set()
        for f in mine:
            if f.kind == "err503":
                self._err503.update(range(f.at_request, f.at_request + int(f.arg)))
        self._blackouts = {f.at_request: f.arg for f in mine if f.kind == "blackout"}
        self._blackout_until = 0.0
        if 0 in self._blackouts:  # startup blackout arms immediately
            self._blackout_until = time.monotonic() + self._blackouts[0]
        self.injected = {"kill": 0, "stop": 0, "delay": 0, "err503": 0, "blackout": 0}

    @classmethod
    def from_spec(
        cls, spec: str | None, replica_id: int | None = None, seed: int | None = None
    ) -> "ChaosInjector | None":
        """Build from a spec string (or the ``ACCELERATE_CHAOS_*`` env
        vars when ``spec`` is None). Returns None when no chaos is
        configured — the disabled path is a single falsy check at every
        hook site, like the telemetry/sanitizer null objects."""
        spec = spec if spec is not None else os.environ.get(CHAOS_SPEC_ENV)
        if not spec or not spec.strip():
            return None
        parsed_seed, faults = parse_chaos_spec(spec)
        if seed is None:
            env_seed = os.environ.get(CHAOS_SEED_ENV)
            if env_seed and env_seed.strip():
                try:
                    seed = int(env_seed)
                except ValueError:
                    # same loud-refusal contract as a malformed spec entry:
                    # the serve front end answers this with an error row +
                    # exit 2 instead of a traceback
                    raise ChaosSpecError(
                        f"malformed {CHAOS_SEED_ENV}={env_seed!r} (want an int)"
                    ) from None
            else:
                seed = parsed_seed
        return cls(faults, seed=seed, replica_id=replica_id)

    # -- hook sites ----------------------------------------------------------

    def on_generate(self) -> str | None:
        """Account one received ``/generate`` request and apply its faults.
        Returns ``"err503"`` when the front end should answer 503; ``None``
        to proceed (possibly after an injected delay). ``kill``/``stop``
        never return — the process is gone or frozen."""
        with self._lock:
            self._requests += 1
            n = self._requests
            if n in self._blackouts:
                self._blackout_until = max(
                    self._blackout_until, time.monotonic() + self._blackouts[n]
                )
                self.injected["blackout"] += 1
            kill = n in self._kills
            stop_wake = self._stops.get(n) if n in self._stops else None
            has_stop = n in self._stops
            delay = self._delays.get(n)
            err = n in self._err503
            if kill:
                self.injected["kill"] += 1
            if has_stop:
                self.injected["stop"] += 1
            if delay:
                self.injected["delay"] += 1
            if err:
                self.injected["err503"] += 1
        if kill:
            print(f"chaos: kill -9 self at request {n}", file=sys.stderr, flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        if has_stop:
            self._stop_self(n, stop_wake)
        if delay is not None:
            lo, hi = delay
            seconds = lo if hi is None else self._rng.uniform(lo, hi)
            time.sleep(seconds)
        return "err503" if err else None

    def healthz_blackout(self) -> bool:
        """True while an injected health-check blackout is active — the
        probe should be answered with a torn connection (no payload)."""
        with self._lock:
            return time.monotonic() < self._blackout_until

    def _stop_self(self, ordinal: int, wake_after: float | None) -> None:
        pid = os.getpid()
        print(
            f"chaos: SIGSTOP self at request {ordinal}"
            + (f" (SIGCONT in {wake_after}s)" if wake_after else " (until killed)"),
            file=sys.stderr, flush=True,
        )
        if wake_after:
            # a stopped process cannot wake itself: a detached helper sends
            # the SIGCONT. start_new_session so the helper survives the
            # router SIGKILLing this (now-unresponsive) replica.
            subprocess.Popen(
                ["/bin/sh", "-c", f"sleep {wake_after}; kill -CONT {pid} 2>/dev/null"],
                start_new_session=True,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
        os.kill(pid, signal.SIGSTOP)
