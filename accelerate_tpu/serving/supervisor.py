"""Replica supervisor: the fleet owns its own lifecycle, continuously.

PR 7's router *consumes* failure — kill -9 rescue, drain, dead-fleet
fail-fast — but never repairs it: a dead replica was gone forever and the
fleet shrank monotonically. This module closes the loop. When the router
marks a replica dead, the supervisor respawns it through the same
``replica.spawn_replica`` machinery the CLI used at bring-up, under an
**exponential crash-loop backoff**:

* each death arriving within ``rapid_death_s`` of the incarnation's spawn
  bumps a consecutive-death counter; the respawn delay doubles per
  consecutive death (seeded jitter so a pod of supervisors never
  thundering-herds a shared dependency) up to ``backoff_max_s``;
* after ``quarantine_after`` consecutive rapid deaths the replica is
  **quarantined**: it keeps backing off, and when it does respawn it
  rejoins dispatch **half-open** (``probation``) — the router routes it at
  most one request at a time until ``probation_successes`` completions
  prove it, after which the death counter resets and it is a full member
  again. A flapping box therefore converges to near-zero dispatch share
  instead of churning the fleet;
* a respawned process that dies (or never reports ready within
  ``ready_timeout``) re-enters the same loop with a deeper backoff.

The supervisor also **scales** between ``min_replicas`` and
``max_replicas`` off the router's own congestion signals — the PR 5
alerts/metrics machinery closing its loop: sustained queue depth above
``scale_up_queue_per_replica`` per ready replica spawns a new member;
a sustained idle fleet above ``min_replicas`` drains its highest-numbered
member (SIGTERM → the serve front end's own drain path → ``terminated``).
With an ``slo_fn`` wired (``route`` does this whenever a logging dir and
armed ``ACCELERATE_SLO_*`` objectives exist), scaling becomes
**SLO-driven**: a firing breach whose dominant tail phase is ``queued``
scales up, a device-/swap-bound breach holds with a ``WRONG_REMEDY``
decision row (capacity is not the fix), and scale-down requires the error
budget to be intact — with every verdict logged as a
``kind:"scale_decision"`` fleet-trail row carrying the evidence.

Pure stdlib and jax-free like the rest of the router side. Disabled
(``Router(supervisor=None)``, the default) the router behaves exactly as
before — the dead-fleet fail-fast regression tests pin that.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass

from ..analysis.lockwatch import maybe_watch
from ..logging import get_logger
from ..metrics.slo import NON_SCALABLE_PHASES

logger = get_logger(__name__)


@dataclass
class SupervisorConfig:
    """Knobs for respawn, backoff, quarantine, and autoscale."""

    #: the fleet size the supervisor restores after deaths (scale-down floor)
    min_replicas: int = 1
    #: autoscale ceiling (never spawns past this; == min disables scaling)
    max_replicas: int = 1
    #: respawn dead replicas at all (False = supervision observes only,
    #: preserving the PR 7 dead-fleet behaviour)
    respawn: bool = True
    #: first respawn delay; doubles per consecutive rapid death
    backoff_base_s: float = 0.25
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    #: +/- fraction of jitter on every backoff delay (seeded — deterministic
    #: per (seed, replica, death-count))
    jitter: float = 0.25
    #: a death within this many seconds of the incarnation's spawn counts
    #: as *consecutive* (crash loop); later deaths restart the count at 1
    rapid_death_s: float = 5.0
    #: consecutive rapid deaths before the replica is quarantined and its
    #: next incarnation rejoins half-open (probation)
    quarantine_after: int = 3
    #: completed requests a probation replica must serve before it becomes
    #: a full dispatch member again (and its death counter resets)
    probation_successes: int = 1
    #: seconds a respawned replica may sit in ``starting`` before the
    #: supervisor declares the bring-up dead and backs off again
    ready_timeout: float = 120.0
    #: autoscale evaluation period
    scale_interval_s: float = 1.0
    #: scale up when router queue depth exceeds this many requests per
    #: ready replica (0 disables scale-up)
    scale_up_queue_per_replica: int = 8
    #: consecutive idle scale ticks (no queue, nothing outstanding) before
    #: one replica above min_replicas is drained
    scale_down_idle_ticks: int = 30
    #: seeds the backoff jitter RNG
    seed: int = 0


class ReplicaSupervisor:
    """Respawn/backoff/quarantine/scale loop over a :class:`~.router.Router`.

    Args:
        spawn_fn: ``spawn_fn(replica_id) -> ReplicaHandle`` — spawns one
            serve process with the fleet's engine arguments (the route CLI
            builds this closure; tests inject stubs).
        config: :class:`SupervisorConfig`.
        slo_fn: optional ``() -> {"firing": [...], "objectives": {...}}``
            (the :func:`~accelerate_tpu.metrics.slo.evaluate_from_dir`
            shape) — arms the SLO scaling policy: scale up on a breach
            whose dominant tail phase is ``queued``, hold with a
            ``WRONG_REMEDY`` decision row when it is device- or swap-bound
            (more replicas would not help), and scale down only while the
            error budget is intact. Every verdict lands in the fleet trail
            as a ``kind:"scale_decision"`` row with the evidence attached.
    """

    def __init__(
        self, spawn_fn, config: SupervisorConfig | None = None, slo_fn=None
    ):
        self.spawn_fn = spawn_fn
        self.cfg = config or SupervisorConfig()
        self.slo_fn = slo_fn
        self._rng = random.Random(self.cfg.seed)
        self._router = None
        self._lock = maybe_watch(threading.Lock(), "ReplicaSupervisor._lock")
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        #: replica_id -> {"deaths", "restarts", "quarantined", "backoff_s",
        #: "respawn_at", "last_spawn"} — survives handle replacement, so the
        #: fleet trail can show restart counts and quarantine state
        self._meta: dict[int, dict] = {}
        self._pending: dict[int, float] = {}  # replica_id -> respawn_at
        self._idle_ticks = 0
        self._last_scale = 0.0
        self._last_decision_sig: tuple | None = None
        self.respawns = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.decisions = 0

    # -- lifecycle -----------------------------------------------------------

    def bind(self, router) -> None:
        """Attach to a router (the router calls this from ``__init__``)
        and start the supervision thread. Locked even though the thread
        starts below: ``bind`` is reachable from any caller's thread, and
        race-check holds every ``_meta``/``replicas`` touch to the same
        discipline."""
        self._router = router
        now = time.monotonic()
        with router._lock:
            fleet = list(router.replicas)
        with self._lock:
            for r in fleet:
                self._meta[r.replica_id] = self._fresh_meta(now)
        self._thread = threading.Thread(
            target=self._loop, name="replica-supervisor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Stop respawning/scaling (drain and close call this FIRST, so a
        respawn never races the teardown kill loop)."""
        self._stopped.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=10.0)

    def will_respawn(self) -> bool:
        """True while dead replicas will be replaced — the router's
        dead-fleet fail-fast stands down when this holds."""
        return self.cfg.respawn and not self._stopped.is_set()

    # -- death / recovery notifications (called by the router) ---------------

    def notify_death(self, replica) -> None:
        """A replica was marked dead: schedule its respawn with crash-loop
        backoff. Called by ``Router._mark_dead`` outside the router lock."""
        if not self.will_respawn():
            return
        if replica.process is None:
            return  # attached replicas are not ours to respawn
        cfg = self.cfg
        now = time.monotonic()
        with self._lock:
            meta = self._meta.setdefault(replica.replica_id, self._fresh_meta(now))
            rapid = now - meta["last_spawn"] <= cfg.rapid_death_s
            meta["deaths"] = meta["deaths"] + 1 if rapid else 1
            backoff = min(
                cfg.backoff_base_s * cfg.backoff_factor ** (meta["deaths"] - 1),
                cfg.backoff_max_s,
            )
            if cfg.jitter:
                backoff *= 1.0 + cfg.jitter * self._rng.uniform(-1.0, 1.0)
            meta["backoff_s"] = backoff
            meta["quarantined"] = meta["deaths"] >= cfg.quarantine_after
            meta["respawn_at"] = now + backoff
            self._pending[replica.replica_id] = meta["respawn_at"]
        logger.warning(
            "supervisor: replica %d death #%d — respawn in %.2fs%s",
            replica.replica_id, meta["deaths"], backoff,
            " (quarantined: next incarnation rejoins half-open)"
            if meta["quarantined"] else "",
        )

    def notify_recovery(self, replica) -> None:
        """A probation replica served its probe quota: full member again,
        consecutive-death counter resets."""
        with self._lock:
            meta = self._meta.get(replica.replica_id)
            if meta is not None:
                meta["deaths"] = 0
                meta["quarantined"] = False
                meta["backoff_s"] = 0.0
        logger.info(
            "supervisor: replica %d cleared probation — full dispatch member",
            replica.replica_id,
        )

    # -- observability -------------------------------------------------------

    def row_fields(self, replica_id: int) -> dict:
        """Supervisor state merged into this replica's fleet-trail row."""
        now = time.monotonic()
        with self._lock:
            meta = self._meta.get(replica_id)
            if meta is None:
                return {}
            out = {
                "restarts": meta["restarts"],
                "consecutive_deaths": meta["deaths"],
                "quarantined": bool(meta["quarantined"]),
                "backoff_s": round(meta["backoff_s"], 3),
            }
            if replica_id in self._pending:
                out["respawn_in_s"] = round(
                    max(0.0, self._pending[replica_id] - now), 3
                )
            return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "respawns": self.respawns,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "pending_respawns": len(self._pending),
                "quarantined": sum(
                    1 for m in self._meta.values() if m["quarantined"]
                ),
                "min_replicas": self.cfg.min_replicas,
                "max_replicas": self.cfg.max_replicas,
                "scale_decisions": self.decisions,
            }

    # -- internals -----------------------------------------------------------

    @staticmethod
    def _fresh_meta(now: float) -> dict:
        return {
            "deaths": 0,
            "restarts": 0,
            "quarantined": False,
            "backoff_s": 0.0,
            "respawn_at": None,
            "last_spawn": now,
            # True once THIS supervisor spawned the current incarnation
            # (respawn or scale-up): those bring-ups are ours to deadline;
            # the CLI's initial spawns belong to wait_until_ready
            "supervised_spawn": False,
        }

    def _loop(self) -> None:
        while not self._stopped.wait(0.05):
            router = self._router
            if router is None or router._teardown_started():
                continue  # teardown owns the fleet now
            try:
                self._respawn_due()
                self._reap_stuck_bringups()
                now = time.monotonic()
                if now - self._last_scale >= self.cfg.scale_interval_s:
                    self._last_scale = now
                    self._autoscale()
            except Exception:
                logger.warning("supervisor tick failed", exc_info=True)

    def _respawn_due(self) -> None:
        if not self.will_respawn():
            return
        now = time.monotonic()
        with self._lock:
            due = [rid for rid, at in self._pending.items() if at <= now]
        for rid in due:
            self._respawn_one(rid)

    def _respawn_one(self, replica_id: int) -> None:
        router = self._router
        try:
            handle = self.spawn_fn(replica_id)
        except Exception:
            logger.warning(
                "supervisor: spawning replica %d failed — backing off again",
                replica_id, exc_info=True,
            )
            with self._lock:
                meta = self._meta[replica_id]
                meta["respawn_at"] = time.monotonic() + max(meta["backoff_s"], 1.0)
                self._pending[replica_id] = meta["respawn_at"]
            return
        with self._lock:
            meta = self._meta.setdefault(
                replica_id, self._fresh_meta(time.monotonic())
            )
            meta["restarts"] += 1
            meta["last_spawn"] = time.monotonic()
            meta["supervised_spawn"] = True
            meta["respawn_at"] = None
            self._pending.pop(replica_id, None)
            handle.restarts = meta["restarts"]
            # quarantined history ⇒ half-open rejoin: the router dispatches
            # at most one concurrent probe request until it proves itself
            handle.probation = bool(meta["quarantined"])
            self.respawns += 1
        with router._lock:
            for i, r in enumerate(router.replicas):
                if r.replica_id == replica_id:
                    router.replicas[i] = handle
                    break
            else:
                router.replicas.append(handle)
            router._work.notify_all()
        logger.info(
            "supervisor: respawned replica %d (pid %s, restart #%d%s)",
            replica_id, handle.pid, meta["restarts"],
            ", probation" if handle.probation else "",
        )

    def _reap_stuck_bringups(self) -> None:
        """A respawned replica stuck in ``starting`` past ``ready_timeout``
        never answers /healthz, so the health loop's bring-up grace would
        wait on it forever — the supervisor owns the bring-up deadline for
        its own spawns (``wait_until_ready`` owns the CLI's)."""
        router = self._router
        now = time.monotonic()
        stuck = []
        # snapshot the fleet under ITS lock (never nested inside ours:
        # sequential acquisition keeps the order graph acyclic)
        with router._lock:
            fleet = list(router.replicas)
        with self._lock:
            for r in fleet:
                meta = self._meta.get(r.replica_id)
                if (
                    meta is not None
                    and meta.get("supervised_spawn")
                    and r.state == "starting"
                    and r.process is not None
                    and now - meta["last_spawn"] > self.cfg.ready_timeout
                ):
                    stuck.append(r)
        for r in stuck:
            logger.warning(
                "supervisor: replica %d never reported ready after %.0fs — killing",
                r.replica_id, self.cfg.ready_timeout,
            )
            router._mark_dead(r)  # kills the process and calls notify_death

    # -- SLO scaling policy ---------------------------------------------------

    def _read_slo(self) -> dict | None:
        """One throttled SLO verdict from ``slo_fn`` (route wires a
        windowed :func:`~accelerate_tpu.metrics.slo.evaluate_from_dir`
        closure; tests inject synthetic streams). Errors degrade to None —
        a broken trail must never stall the supervision loop."""
        if self.slo_fn is None:
            return None
        try:
            verdict = self.slo_fn()
        except Exception:
            logger.warning("supervisor: slo_fn failed", exc_info=True)
            return None
        return verdict if isinstance(verdict, dict) else None

    def _decide(
        self, action, reason, breach=None, queue_depth=0, ready_count=0
    ) -> None:
        """Log one scaling verdict to the fleet trail. Holds are throttled
        on their (action, reason, objective) signature — a steady-state
        verdict lands once, not once per scale tick — while actual scale
        actions always land."""
        breach = breach or {}
        sig = (action, reason, breach.get("objective"))
        if action == "hold" and sig == self._last_decision_sig:
            return
        self._last_decision_sig = sig
        router = self._router
        writer = getattr(router, "write_decision_row", None)
        with self._lock:
            self.decisions += 1
        if writer is None:
            return
        writer(
            {
                "action": action,
                "reason": reason,
                "objective": breach.get("objective"),
                "burn_rate": breach.get("burn_rate"),
                "dominant_phase": breach.get("dominant_phase"),
                "budget_remaining": breach.get("budget_remaining"),
                "queue_depth": queue_depth,
                "ready_replicas": ready_count,
            }
        )

    def _budget_intact(self, slo: dict | None) -> bool:
        """True when no objective is firing and every armed objective with
        evidence still has budget left — the only state scale-down is
        allowed in when the SLO policy is armed."""
        if not slo:
            return True
        if slo.get("firing"):
            return False
        for row in (slo.get("objectives") or {}).values():
            remaining = row.get("budget_remaining")
            if isinstance(remaining, (int, float)) and remaining <= 0:
                return False
        return True

    def _scale_up(self, next_id, queue_depth, ready_count, reason, breach=None):
        router = self._router
        self._idle_ticks = 0
        try:
            handle = self.spawn_fn(next_id)
        except Exception:
            logger.warning("supervisor: scale-up spawn failed", exc_info=True)
            return
        with self._lock:
            meta = self._fresh_meta(time.monotonic())
            meta["supervised_spawn"] = True  # this bring-up is ours to deadline
            self._meta[next_id] = meta
            self.scale_ups += 1
        with router._lock:
            router.replicas.append(handle)
        self._decide(
            "scale_up", reason, breach=breach,
            queue_depth=queue_depth, ready_count=ready_count,
        )
        logger.info(
            "supervisor: scaled up — replica %d spawned (%s; queue %d over %d ready)",
            next_id, reason, queue_depth, ready_count,
        )

    def _autoscale(self) -> None:
        cfg = self.cfg
        router = self._router
        with router._lock:
            queue_depth = len(router._queue)
            outstanding = router._outstanding
            ready = [r for r in router.replicas if r.state == "ready"]
            live = [
                r for r in router.replicas
                if r.state in ("starting", "ready", "draining")
            ]
            next_id = 1 + max((r.replica_id for r in router.replicas), default=-1)
        with self._lock:
            planned = len(live) + len(self._pending)
        slo = self._read_slo()
        breach = (slo or {}).get("firing") or None
        if breach:
            # evaluate() sorts worst-first: act on the breach burning
            # budget fastest, and let its dominant tail phase pick the
            # remedy — capacity only fixes *queueing*
            worst = breach[0]
            phase = worst.get("dominant_phase")
            self._idle_ticks = 0
            if phase == "queued":
                if planned < cfg.max_replicas:
                    self._scale_up(
                        next_id, queue_depth, len(ready), "slo_breach", worst
                    )
                else:
                    self._decide(
                        "hold", "at_max_replicas", breach=worst,
                        queue_depth=queue_depth, ready_count=len(ready),
                    )
            elif phase in NON_SCALABLE_PHASES:
                # the tail is device- or HBM-bound: another replica is
                # another waiting device — say so instead of scaling
                self._decide(
                    "hold", "WRONG_REMEDY", breach=worst,
                    queue_depth=queue_depth, ready_count=len(ready),
                )
            else:
                self._decide(
                    "hold", f"phase_{phase or 'unattributed'}", breach=worst,
                    queue_depth=queue_depth, ready_count=len(ready),
                )
            return
        # scale up: sustained congestion per ready member
        if (
            cfg.scale_up_queue_per_replica > 0
            and planned < cfg.max_replicas
            and queue_depth > cfg.scale_up_queue_per_replica * max(len(ready), 1)
        ):
            self._scale_up(next_id, queue_depth, len(ready), "queue_depth")
            return
        # scale down: sustained idleness above the floor — and, when the
        # SLO policy is armed, only with the error budget intact
        if queue_depth == 0 and outstanding == 0 and len(ready) > cfg.min_replicas:
            if not self._budget_intact(slo):
                self._idle_ticks = 0
                self._decide(
                    "hold", "budget_spent",
                    queue_depth=queue_depth, ready_count=len(ready),
                )
                return
            self._idle_ticks += 1
            if self._idle_ticks >= cfg.scale_down_idle_ticks:
                self._idle_ticks = 0
                victim = max(
                    (r for r in ready if r.process is not None),
                    key=lambda r: r.replica_id,
                    default=None,
                )
                if victim is None:
                    return
                with router._lock:
                    if victim.state != "ready" or victim.in_flight:
                        return  # raced a dispatch; try again next tick
                    victim.state = "draining"
                with self._lock:
                    self.scale_downs += 1
                victim.drain()  # SIGTERM → serve's own drain → exit 0
                self._decide(
                    "scale_down",
                    "budget_intact_idle" if self.slo_fn is not None else "idle",
                    queue_depth=queue_depth, ready_count=len(ready),
                )
                logger.info(
                    "supervisor: scaled down — replica %d draining (idle fleet "
                    "above min_replicas=%d)", victim.replica_id, cfg.min_replicas,
                )
        else:
            self._idle_ticks = 0
