"""Seeded, replayable workload suite for the serving fleet.

The observability stack (goodput ledger, tail attribution, flight
recorder, the windowed SLO engine in :mod:`accelerate_tpu.metrics.slo`)
is only as good as the traffic it is measured against — and until now
there was no reproducible traffic. This module generates deterministic
arrival schedules from one seed: the same ``SPEC`` produces a
byte-identical schedule (asserted at generation time), so an SLO
scorecard is a regression test, not a weather report.

A schedule is a list of ``{"t": <seconds-from-start>, "payload": {...}}``
entries sorted by ``t``; payloads are exactly the request dicts the
``serve``/``route`` JSONL protocol accepts (``prompt`` token ids,
``max_new_tokens``, optional ``session_id``/``priority``/``deadline_ms``).
Scenario catalogue (``serve --trace SPEC`` / ``route --trace SPEC``,
``SPEC = name:seed:duration:rps[:tenants=N]`` — the optional ``tenants=N``
stamps each payload with a seeded tenant id for usage attribution; a
malformed spec is a bring-up refusal — exit 2 — exactly like
``--chaos-spec``):

``bursty-diurnal``    sinusoid-modulated Poisson arrivals (a compressed
                      diurnal cycle: troughs and rush hours in one run)
``longctx-flood``     a storm of long-prompt summarization-shaped
                      requests — prefill pressure, block-pool pressure
``agentic``           many-turn sticky-session chains with shared
                      prefixes — session affinity + radix-cache traffic
``overbudget-storm``  adversarial mix of tight ``deadline_ms`` budgets,
                      ``batch``-class bulk and oversized decodes — the
                      shed/deadline/queue pressure scenario

``replay:<path>`` replays a schedule captured from real traffic: the
route front end records live arrivals (``--trace-record``) into the same
schedule format under ``<logging_dir>/workload/recorded.jsonl``.

Pure stdlib and jax-free, like the rest of the router side.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import random
import time
from dataclasses import dataclass

__all__ = [
    "SCENARIOS",
    "TraceSpec",
    "TraceSpecError",
    "WORKLOAD_FILENAME",
    "WorkloadRecorder",
    "generate_schedule",
    "load_schedule",
    "parse_trace_spec",
    "run_schedule",
    "schedule_bytes",
    "schedule_digest",
    "write_workload_manifest",
]

#: generator names a ``--trace`` SPEC may request (``replay`` is the
#: capture-driven pseudo-scenario)
SCENARIOS = ("bursty-diurnal", "longctx-flood", "agentic", "overbudget-storm")

#: manifest written next to a traced run's artifacts — `slo report` reads
#: it to label the scorecard's scenario axis
WORKLOAD_FILENAME = "WORKLOAD.json"

#: subdir of logging_dir where --trace-record captures live arrivals
RECORD_SUBDIR = "workload"
RECORD_FILENAME = "recorded.jsonl"

#: schema stamp on manifests and recorded rows
WORKLOAD_SCHEMA = 1


class TraceSpecError(ValueError):
    """Malformed ``--trace`` spec — raised at parse time so a typo'd
    scenario refuses the bring-up loudly instead of silently measuring
    nothing (the ``--chaos-spec`` contract)."""


@dataclass(frozen=True)
class TraceSpec:
    """One parsed ``--trace`` spec. ``path`` is set only for ``replay``.
    ``tenants`` > 0 stamps each payload with a deterministic tenant id
    (``t0``..``t{N-1}``) for usage-attribution scenarios."""

    name: str
    seed: int = 0
    duration_s: float = 10.0
    rps: float = 4.0
    path: str | None = None
    tenants: int = 0

    def as_text(self) -> str:
        if self.name == "replay":
            return f"replay:{self.path}"
        text = f"{self.name}:{self.seed}:{self.duration_s:g}:{self.rps:g}"
        if self.tenants:
            text += f":tenants={self.tenants}"
        return text


def parse_trace_spec(spec: str) -> TraceSpec:
    """Parse ``name:seed:duration:rps`` (or ``replay:<path>``)."""
    if not isinstance(spec, str) or not spec.strip():
        raise TraceSpecError("empty --trace spec")
    spec = spec.strip()
    name, _, rest = spec.partition(":")
    if name == "replay":
        if not rest:
            raise TraceSpecError(
                "replay spec needs a schedule path: replay:<path>"
            )
        return TraceSpec(name="replay", path=rest)
    if name not in SCENARIOS:
        raise TraceSpecError(
            f"unknown workload scenario {name!r}: expected one of "
            f"{SCENARIOS} or replay:<path>"
        )
    parts = rest.split(":") if rest else []
    tenants = 0
    if len(parts) == 4 and parts[3].startswith("tenants="):
        try:
            tenants = int(parts[3][len("tenants="):])
            if tenants < 0:
                raise ValueError
        except ValueError:
            raise TraceSpecError(
                f"--trace spec {spec!r}: tenants= must be a non-negative "
                f"integer"
            ) from None
        parts = parts[:3]
    if len(parts) != 3:
        raise TraceSpecError(
            f"--trace spec {spec!r} must be name:seed:duration:rps"
            f"[:tenants=N]"
        )
    try:
        seed = int(parts[0])
        if seed < 0:
            raise ValueError
    except ValueError:
        raise TraceSpecError(
            f"--trace spec {spec!r}: seed must be a non-negative integer"
        ) from None
    try:
        duration_s = float(parts[1])
        rps = float(parts[2])
        if not (duration_s > 0 and rps > 0):  # also rejects NaN
            raise ValueError
    except ValueError:
        raise TraceSpecError(
            f"--trace spec {spec!r}: duration and rps must be positive numbers"
        ) from None
    return TraceSpec(
        name=name, seed=seed, duration_s=duration_s, rps=rps, tenants=tenants
    )


# ---------------------------------------------------------------------------
# generators — every arrival time and payload field comes from one
# random.Random(seed); nothing reads the clock or global RNG state
# ---------------------------------------------------------------------------


def _prompt(rng: random.Random, length: int) -> list[int]:
    return [rng.randrange(1, 32) for _ in range(length)]


def _poisson_arrivals(rng, duration_s, rate_fn, rate_max):
    """Thinning (Lewis-Shedler) sampler of an inhomogeneous Poisson
    process — deterministic for a given rng state."""
    out, t = [], 0.0
    while True:
        t += rng.expovariate(rate_max)
        if t >= duration_s:
            return out
        if rng.random() <= rate_fn(t) / rate_max:
            out.append(t)


def _gen_bursty_diurnal(rng, spec):
    """Sinusoid-modulated Poisson: one compressed diurnal cycle —
    ``rate(t) = rps * (1 + 0.8 sin(2πt/duration))`` — so one run holds
    both the trough and the rush hour."""
    rate = lambda t: spec.rps * (1.0 + 0.8 * math.sin(2 * math.pi * t / spec.duration_s))  # noqa: E731
    schedule = []
    for i, t in enumerate(
        _poisson_arrivals(rng, spec.duration_s, rate, spec.rps * 1.8)
    ):
        schedule.append({
            "t": round(t, 6),
            "payload": {
                "id": f"bursty-{i}",
                "prompt": _prompt(rng, rng.randint(4, 12)),
                "max_new_tokens": rng.randint(4, 12),
            },
        })
    return schedule


def _gen_longctx_flood(rng, spec):
    """Long-prompt summarization storm: prompts an order of magnitude
    longer than the bursty mix, short answers — prefill and block-pool
    pressure, the TTFT-tail scenario."""
    schedule, t = [], 0.0
    i = 0
    while True:
        t += rng.expovariate(spec.rps)
        if t >= spec.duration_s:
            return schedule
        schedule.append({
            "t": round(t, 6),
            "payload": {
                "id": f"longctx-{i}",
                "prompt": _prompt(rng, rng.randint(40, 72)),
                "max_new_tokens": rng.randint(2, 6),
            },
        })
        i += 1


def _gen_agentic(rng, spec):
    """Many-turn agent chains: a few sticky sessions, each a sequence of
    turns sharing the session's prompt prefix (radix-cache + session-
    affinity traffic). Turn k arrives a think-time gap after turn k-1."""
    n_sessions = max(2, int(round(spec.rps)))
    mean_gap = max(0.05, 2.0 / spec.rps)
    schedule = []
    for s in range(n_sessions):
        base = _prompt(rng, rng.randint(16, 24))  # shared session prefix
        t = rng.uniform(0.0, min(1.0, spec.duration_s / 4))
        turn = 0
        while t < spec.duration_s:
            suffix = _prompt(rng, rng.randint(2, 6))
            schedule.append({
                "t": round(t, 6),
                "payload": {
                    "id": f"agentic-{s}-{turn}",
                    "session_id": f"agent-{s}",
                    "prompt": base + suffix,
                    "max_new_tokens": rng.randint(4, 10),
                },
            })
            turn += 1
            t += rng.expovariate(1.0 / mean_gap)
    schedule.sort(key=lambda e: (e["t"], e["payload"]["id"]))
    return schedule


def _gen_overbudget_storm(rng, spec):
    """Adversarial deadline/over-budget mix: interactive requests with
    tight (sometimes impossible) ``deadline_ms`` budgets interleaved with
    ``batch``-class bulk decodes — the scenario that exercises shed,
    deadline expiry, and queue growth (the ``queued``-dominated breach)."""
    schedule, t, i = [], 0.0, 0
    while True:
        t += rng.expovariate(spec.rps)
        if t >= spec.duration_s:
            return schedule
        roll = rng.random()
        payload = {
            "id": f"storm-{i}",
            "prompt": _prompt(rng, rng.randint(4, 16)),
        }
        if roll < 0.4:  # tight-budget interactive: some budgets impossible
            payload["max_new_tokens"] = rng.randint(4, 8)
            payload["deadline_ms"] = rng.choice((5, 25, 100, 400, 1500))
            payload["priority"] = "interactive"
        elif roll < 0.7:  # bulk batch decode: queue + shed pressure
            payload["max_new_tokens"] = rng.randint(24, 48)
            payload["priority"] = "batch"
        else:  # plain interactive filler
            payload["max_new_tokens"] = rng.randint(8, 16)
        schedule.append({"t": round(t, 6), "payload": payload})
        i += 1


_GENERATORS = {
    "bursty-diurnal": _gen_bursty_diurnal,
    "longctx-flood": _gen_longctx_flood,
    "agentic": _gen_agentic,
    "overbudget-storm": _gen_overbudget_storm,
}


def schedule_bytes(schedule: list[dict]) -> bytes:
    """Canonical serialization — the determinism contract is *byte*
    identity of this form, not merely ``==`` of the structures."""
    return (
        "\n".join(
            json.dumps(entry, sort_keys=True, separators=(",", ":"))
            for entry in schedule
        )
    ).encode()


def schedule_digest(schedule: list[dict]) -> str:
    return hashlib.sha256(schedule_bytes(schedule)).hexdigest()


def generate_schedule(spec: TraceSpec) -> list[dict]:
    """The spec's deterministic schedule. Generated twice from fresh RNGs
    and asserted byte-identical — a generator that sneaks in ambient state
    (clock, global RNG, dict order) fails here, at the source, not as an
    unexplainable scorecard diff two runs later."""
    if spec.name == "replay":
        return load_schedule(spec.path)
    gen = _GENERATORS[spec.name]
    schedule = gen(random.Random(spec.seed), spec)
    again = gen(random.Random(spec.seed), spec)
    assert schedule_bytes(schedule) == schedule_bytes(again), (
        f"workload generator {spec.name!r} is non-deterministic for "
        f"seed {spec.seed}"
    )
    if spec.tenants:
        # tenant assignment is a post-process on the arrival schedule —
        # its own seeded stream, so `tenants=N` changes WHO each request
        # bills, never when it arrives or what it asks for
        rng = random.Random(spec.seed * 1_000_003 + spec.tenants)
        for entry in schedule:
            entry["payload"]["tenant"] = f"t{rng.randrange(spec.tenants)}"
    return schedule


# ---------------------------------------------------------------------------
# record / replay
# ---------------------------------------------------------------------------


def load_schedule(path: str) -> list[dict]:
    """Read a recorded (or hand-written) schedule JSONL; malformed lines
    are skipped, entries are re-sorted by ``t``."""
    schedule = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (
                    isinstance(row, dict)
                    and isinstance(row.get("t"), (int, float))
                    and isinstance(row.get("payload"), dict)
                ):
                    schedule.append({"t": float(row["t"]), "payload": row["payload"]})
    except OSError as e:
        raise TraceSpecError(f"replay: cannot read schedule {path!r}: {e}") from e
    if not schedule:
        raise TraceSpecError(f"replay: no schedule entries in {path!r}")
    schedule.sort(key=lambda e: e["t"])
    return schedule


class WorkloadRecorder:
    """Capture live traffic into the schedule format (``route
    --trace-record``): each observed payload lands as one
    ``{"t": <offset-from-first>, "payload": ...}`` line under
    ``<logging_dir>/workload/recorded.jsonl``, immediately replayable via
    ``--trace replay:<path>``. Append + flush per row, crash-safe like
    every other trail in the logging dir."""

    def __init__(self, logging_dir: str):
        subdir = os.path.join(logging_dir, RECORD_SUBDIR)
        os.makedirs(subdir, exist_ok=True)
        self.path = os.path.join(subdir, RECORD_FILENAME)
        self._f = open(self.path, "a")
        self._t0: float | None = None
        self.recorded = 0

    def observe(self, payload: dict) -> None:
        now = time.monotonic()
        if self._t0 is None:
            self._t0 = now
        if self._f is None or not isinstance(payload, dict):
            return
        # the router stamps trace_id into submitted payloads in place; a
        # replay must mint fresh ids, so strip the one this run minted
        clean = {k: v for k, v in payload.items() if k != "trace_id"}
        try:
            self._f.write(json.dumps({
                "schema": WORKLOAD_SCHEMA,
                "t": round(now - self._t0, 6),
                "payload": clean,
            }) + "\n")
            self._f.flush()
            self.recorded += 1
        except (OSError, ValueError, TypeError):
            pass

    def close(self) -> None:
        f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass


def write_workload_manifest(
    logging_dir: str, spec: TraceSpec, schedule: list[dict]
) -> str | None:
    """``WORKLOAD.json`` next to the run's artifacts (atomic replace):
    the scenario identity + schedule digest that makes two runs
    comparable — ``slo report`` reads it, and the smoke asserts digest
    equality across repeated runs."""
    if not logging_dir:
        return None
    path = os.path.join(logging_dir, WORKLOAD_FILENAME)
    payload = {
        "schema": WORKLOAD_SCHEMA,
        "ts": time.time(),
        "spec": spec.as_text(),
        "scenario": spec.name,
        "seed": spec.seed,
        "duration_s": spec.duration_s,
        "rps": spec.rps,
        "requests": len(schedule),
        "schedule_sha256": schedule_digest(schedule),
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return path


def run_schedule(schedule, submit, should_stop=None, speed: float = 1.0) -> int:
    """Drive ``submit(payload)`` at the schedule's arrival offsets
    (best-effort sleeps; the *schedule* is the deterministic artifact,
    wall-clock jitter on dispatch is measurement noise like any other).
    ``should_stop()`` (e.g. a preemption flag) aborts between arrivals.
    Payloads are copied before submission — the router stamps trace ids
    into its payloads in place, and the schedule must stay pristine for
    the next replay. Returns the number submitted."""
    t0 = time.monotonic()
    submitted = 0
    for entry in schedule:
        target = t0 + entry["t"] / max(speed, 1e-9)
        while True:
            if should_stop is not None and should_stop():
                return submitted
            remaining = target - time.monotonic()
            if remaining <= 0:
                break
            time.sleep(min(remaining, 0.05))
        submit(dict(entry["payload"]))
        submitted += 1
    return submitted
