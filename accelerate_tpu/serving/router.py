"""Multi-replica request router: least-loaded + session-affinity dispatch
over health-checked engine replicas.

The engine scales *up* by sharding its decode step over the mesh
(:mod:`.engine` with ``mesh=``); it scales *out* by replication — N engine
processes, each with its own compiled executable, behind this router. The
router is deliberately model-blind and jax-free: it speaks the serve front
end's HTTP protocol (``POST /generate``, ``GET /healthz``) and owns only
placement, affinity, retry, and drain:

* **least-loaded dispatch** — a request goes to the ``ready`` replica with
  the fewest in-flight + queued + decoding requests;
* **session affinity** — requests carrying a ``session_id`` stick to the
  replica that served the session before, so a multi-turn chat lands where
  its KV prefix is warm; affinity is *advisory* — a dead replica's
  sessions move on;
* **prefix affinity** — a free request (no ``session_id``) prefers the
  ready replica whose recent dispatches share its prompt's leading block
  hash (the first :data:`AFFINITY_PREFIX_TOKENS` token ids), so requests
  with a common system prompt land on the replica whose radix prefix
  cache is already warm for it; falls back to least-loaded. Affinity
  yields once the warm replica is more than ``affinity_load_slack``
  requests busier than the fleet's least-loaded member — the spillover
  replica then records the prefix on its own first dispatch and becomes
  warm too, so a dominant system prompt scales across the fleet instead
  of starving it onto one box;
* **failure requeue** — a transport-level dispatch failure (the replica
  was killed mid-stream) re-enqueues the request at the *front* of the
  queue for a different replica; each request is delivered to its caller
  exactly once, so a kill -9 loses and duplicates nothing;
* **drain** — stop admission, let in-flight requests finish, then SIGTERM
  every spawned replica (the serve front end's PreemptionHandler drain)
  and wait for clean exits;
* **self-healing** (``supervisor=``, :mod:`.supervisor`) — a dead replica
  is respawned with exponential crash-loop backoff, flapping replicas are
  quarantined and rejoin half-open (one probe request at a time), and the
  fleet scales between min/max replicas off its own queue-depth signal;
* **request lifecycle** — a payload ``deadline_ms`` rides the ticket: the
  router answers expired tickets with a deadline-exceeded error row
  instead of dispatching or retrying them, and forwards the *remaining*
  budget so the engine evicts the slot when it runs out; a bounded queue
  (``max_queue_depth=``) sheds ``batch``-class submissions before
  ``interactive`` with explicit over-capacity error rows. All of it is
  guarded: deadline-free, unbounded, unsupervised routing pays a few
  None-checks per dispatch (the telemetry null-path rule).

Per-replica health is appended to ``<logging_dir>/router/replicas.jsonl``
(one row per replica per health tick) — the fleet panel in
``accelerate-tpu monitor`` reads only this file, so fleet health survives
a dead router the same way training health survives a wedged host.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..analysis.lockwatch import get_active_lockwatch, maybe_watch
from ..diagnostics.tracing import ensure_trace_id, get_tracer
from ..logging import get_logger
from .replica import ReplicaError, ReplicaHandle, ReplicaTimeout
from .usage import DEFAULT_TOP_K, cap_by_key, normalize_tenant

logger = get_logger(__name__)

#: subdirectory of logging_dir holding the router's fleet trail
ROUTER_SUBDIR = "router"
#: schema stamp on every fleet row (readers skip newer-than-known rows)
ROUTER_SCHEMA = 1
#: leading token ids hashed into a request's prefix-affinity key — one
#: engine block at the default block_size, the granularity the radix cache
#: actually shares at
AFFINITY_PREFIX_TOKENS = 16


def _prefix_key(payload) -> tuple | None:
    """Leading-block hash key of a request's prompt (None when the payload
    has no usable prompt, or the prompt is too short to say anything about
    prefix reuse — sub-block prompts hit nothing in the radix cache)."""
    prompt = payload.get("prompt") if isinstance(payload, dict) else None
    if not isinstance(prompt, (list, tuple)) or len(prompt) < AFFINITY_PREFIX_TOKENS:
        return None
    try:
        return tuple(int(t) for t in prompt[:AFFINITY_PREFIX_TOKENS])
    except (TypeError, ValueError):
        return None


@dataclass(eq=False)  # identity semantics: tickets live in per-replica sets
class Ticket:
    """One request's lifetime inside the router. ``result`` is set exactly
    once; ``done`` fires after delivery (and after ``callback`` ran)."""

    payload: dict
    callback: object = None
    attempts: int = 0
    result: dict | None = None
    replica_id: int | None = None
    delivered: bool = False
    #: absolute ``time.monotonic`` expiry, set at submit from the payload's
    #: ``deadline_ms`` (None = no deadline — the zero-cost default path)
    deadline: float | None = None
    done: threading.Event = field(default_factory=threading.Event)

    @property
    def session_id(self):
        return self.payload.get("session_id") if isinstance(self.payload, dict) else None

    @property
    def priority(self) -> str:
        p = self.payload.get("priority") if isinstance(self.payload, dict) else None
        return p if isinstance(p, str) else "interactive"

    @property
    def tenant(self) -> str:
        """The request's accounting tenant (the usage-ledger dimension) —
        same payload-riding contract as ``priority``, same unknown-safe
        normalization the engine applies."""
        t = self.payload.get("tenant") if isinstance(self.payload, dict) else None
        return normalize_tenant(t)

    @property
    def req_id(self):
        """The caller's request id, echoed on every answer row."""
        return self.payload.get("id") if isinstance(self.payload, dict) else None

    @property
    def trace_id(self):
        """The request's distributed-trace identity (stamped into the
        payload at submit, so it rides the HTTP hop to the replica)."""
        return self.payload.get("trace_id") if isinstance(self.payload, dict) else None


class Router:
    """Dispatch loop + health loop over a fixed replica set.

    Args:
        replicas: :class:`~.replica.ReplicaHandle` list (spawned or attached).
        logging_dir: when set, per-replica JSONL health rows land under
            ``<logging_dir>/router/replicas.jsonl``.
        health_interval: seconds between ``/healthz`` sweeps.
        max_attempts: dispatch attempts per request before it is answered
            with an error (default: one try per replica + 1 retry).
        request_timeout: per-dispatch HTTP timeout (None = wait forever;
            a killed replica resets the connection immediately either way).
            Expiry on a slow-but-alive replica requeues the ticket WITHOUT
            marking the replica dead (:class:`~.replica.ReplicaTimeout`).
        affinity_load_slack: how many requests busier than the fleet's
            least-loaded replica a prefix-warm replica may be before
            affinity yields to load balance (~one slot set's worth).
        supervisor: a :class:`~.supervisor.ReplicaSupervisor` that respawns
            dead replicas with crash-loop backoff and scales the fleet;
            None (default) preserves the fixed-fleet PR 7 behaviour.
        max_queue_depth: bounded-queue admission control — when the queue
            holds this many tickets, a new ``interactive`` submission sheds
            the newest queued ``batch``-class ticket (answered with an
            over-capacity error row), and a ``batch`` submission is itself
            rejected; None (default) keeps the queue unbounded.
    """

    def __init__(
        self,
        replicas: list[ReplicaHandle],
        logging_dir: str | None = None,
        health_interval: float = 0.5,
        max_attempts: int | None = None,
        request_timeout: float | None = None,
        affinity_load_slack: int = 8,
        supervisor=None,
        max_queue_depth: int | None = None,
    ):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.logging_dir = logging_dir
        self.health_interval = float(health_interval)
        self.max_attempts = max_attempts or len(replicas) + 2
        self.request_timeout = request_timeout
        self.affinity_load_slack = int(affinity_load_slack)
        self.supervisor = supervisor
        self.max_queue_depth = max_queue_depth
        self._queue: deque[Ticket] = deque()
        # LockWatch (ACCELERATE_SANITIZE=1) wraps the fleet's locks in
        # order-graph shims; disabled, maybe_watch hands the raw lock back
        self._lock = maybe_watch(threading.Lock(), "Router._lock", logging_dir)
        self._work = threading.Condition(self._lock)
        # leaf lock serializing fleet-trail writes: the health tick and
        # _mark_dead both flush rows, and two threads interleaving write()
        # calls on one buffered file tear JSONL lines mid-row
        self._trail_lock = maybe_watch(
            threading.Lock(), "Router._trail_lock", logging_dir
        )
        self._sessions: dict = {}  # session_id -> replica_id
        # tickets currently POSTed to each replica: _mark_dead requeues these
        # (a wedged-but-alive replica never produces the transport error the
        # normal requeue path waits for)
        self._inflight: dict[int, set] = {}
        self._draining = False
        self._health_paused = False  # drain owns replica states once set
        self._stopped = threading.Event()
        self._outstanding = 0  # submitted, not yet delivered
        self._delivered = 0
        self._requeues = 0
        self._rejected = 0
        self._shed = 0
        self._deadline_expired = 0
        self._tokens = 0
        # per-tenant outcome counts (usage-ledger attribution at the fleet
        # seam: which tenant's traffic was delivered / shed / requeued /
        # expired). Written under _lock at the same sites as the scalar
        # counters; exported capped to top-K + "other" like every tenant
        # label surface
        self._by_tenant: dict[str, dict] = {}
        # earliest deadline among queued tickets (None = no deadlines):
        # the dispatch loop runs the expiry sweep only once this instant
        # passes, so deadline-free traffic pays one None-check per
        # iteration (the telemetry null-path rule) and deadline-heavy
        # backlog pays one clock read, not an O(queue) scan
        self._next_deadline: float | None = None
        self._trail = None
        if logging_dir:
            os.makedirs(os.path.join(logging_dir, ROUTER_SUBDIR), exist_ok=True)
            self._trail = open(
                os.path.join(logging_dir, ROUTER_SUBDIR, "replicas.jsonl"), "a"
            )
        self._threads = [
            threading.Thread(target=self._dispatch_loop, name="router-dispatch", daemon=True),
            threading.Thread(target=self._health_loop, name="router-health", daemon=True),
        ]
        for t in self._threads:
            t.start()
        if supervisor is not None:
            supervisor.bind(self)

    def _bump_tenant(self, tenant: str, outcome: str) -> None:
        """One per-tenant outcome count (caller holds ``_lock``, like the
        scalar counter the call sits beside)."""
        row = self._by_tenant.get(tenant)
        if row is None:
            row = self._by_tenant[tenant] = {
                "delivered": 0, "shed": 0, "requeued": 0, "deadline_expired": 0,
            }
        row[outcome] += 1

    # -- admission -----------------------------------------------------------

    def submit(self, payload: dict, callback=None) -> Ticket:
        """Enqueue one request; returns its ticket. While draining, the
        request is answered immediately with an error instead of being
        silently dropped (the caller always gets exactly one answer). A
        malformed ``deadline_ms`` is likewise an error *answer*, never a
        crash; a full bounded queue sheds ``batch`` before ``interactive``
        with explicit over-capacity error rows."""
        # the span's begin timestamp is captured BEFORE the ticket can
        # enter the queue: the event itself is emitted after the lock
        # releases, and by then the dispatcher may already have stamped
        # req/dispatch — an un-pinned begin would sort after it
        submit_ts = time.perf_counter()
        if isinstance(payload, dict):
            # the trace id is BORN here: a well-formed client-supplied
            # "trace_id" survives verbatim, anything else gets a generated
            # one — stamped into the payload so the HTTP dispatch carries
            # it into the replica (and its engine) unchanged
            payload["trace_id"] = ensure_trace_id(payload.get("trace_id"))
        ticket = Ticket(payload=payload, callback=callback)
        req_id = ticket.req_id
        rejected = None
        shed_victim = None
        raw_deadline = (
            payload.get("deadline_ms") if isinstance(payload, dict) else None
        )
        if raw_deadline is not None:
            try:
                deadline_ms = float(raw_deadline)
                if not deadline_ms > 0:  # also rejects NaN
                    raise ValueError
            except (TypeError, ValueError):
                rejected = {
                    "id": req_id,
                    "error": f"malformed deadline_ms {raw_deadline!r}: "
                    "want a positive number of milliseconds",
                }
            else:
                ticket.deadline = time.monotonic() + deadline_ms / 1000.0
        with self._lock:
            if rejected is not None:
                self._rejected += 1
            elif self._draining or self._stopped.is_set():
                self._rejected += 1
                rejected = {
                    "id": req_id,
                    "error": "router is draining: admission stopped",
                }
            elif (
                self.max_queue_depth is not None
                and len(self._queue) >= self.max_queue_depth
            ):
                # load shed, batch-class first: an interactive arrival may
                # displace the NEWEST queued batch ticket (it has waited the
                # least); a batch arrival — or an interactive one with no
                # batch ticket to displace — is itself shed
                if ticket.priority == "interactive":
                    for t in reversed(self._queue):
                        if t.priority == "batch":
                            shed_victim = t
                            break
                self._shed += 1
                self._bump_tenant(
                    (shed_victim or ticket).tenant, "shed"
                )
                if shed_victim is not None:
                    self._queue.remove(shed_victim)
                    self._outstanding += 1
                    self._arm_deadline(ticket.deadline)
                    self._queue.append(ticket)
                    self._work.notify()
                else:
                    self._rejected += 1
                    rejected = {
                        "id": req_id,
                        "error": f"over capacity: queue depth "
                        f"{len(self._queue)} at max_queue_depth "
                        f"{self.max_queue_depth} — request shed",
                    }
            else:
                self._outstanding += 1
                self._arm_deadline(ticket.deadline)
                self._queue.append(ticket)
                self._work.notify()
        tr = get_tracer()
        if tr and ticket.trace_id:  # events land OUTSIDE the dispatch lock
            tr.request_begin(
                ticket.trace_id, "req/submit", ts=submit_ts,
                request=str(req_id), priority=ticket.priority,
            )
            if shed_victim is not None and shed_victim.trace_id:
                tr.request_instant(shed_victim.trace_id, "req/shed")
        if shed_victim is not None:  # answered outside the lock
            self._finish(shed_victim, {
                "id": shed_victim.req_id,
                "error": "over capacity: shed from the queue to admit "
                "interactive traffic (batch class sheds first)",
            })
        if rejected is not None:  # deliver outside the lock
            self._finish(ticket, rejected, count_delivered=False)
        return ticket

    # -- dispatch ------------------------------------------------------------

    def _pick_replica(self, ticket: Ticket) -> ReplicaHandle | None:
        """Session affinity first, then prefix affinity (the replica whose
        recent requests share this prompt's leading block hash — its radix
        cache is warm for the prefix), least-loaded ready replica
        otherwise. Caller holds the lock. A ``probation`` (half-open)
        replica is a candidate only while it holds no in-flight request —
        one probe at a time until it proves itself."""
        candidates = [
            r for r in self.replicas
            if r.is_dispatchable() and (not r.probation or r.in_flight == 0)
        ]
        if not candidates:
            return None
        sid = ticket.session_id
        if sid is not None:
            mapped = self._sessions.get(sid)
            for r in candidates:
                if r.replica_id == mapped:
                    return r
        key = _prefix_key(ticket.payload)
        pool = candidates
        if key is not None:
            # affinity yields under skew: once every warm replica is more
            # than the slack busier than the least-loaded member, spill —
            # the spillover replica's own dispatch records the key, so a
            # dominant prefix warms the fleet instead of starving it
            floor = min(r.load for r in candidates)
            warm = [
                r for r in candidates
                if key in r.recent_prefixes
                and r.load <= floor + self.affinity_load_slack
            ]
            if warm:
                pool = warm  # least-loaded among the warm replicas
        chosen = min(pool, key=lambda r: (r.load, r.replica_id))
        if key is not None:
            # move-to-back on hit: recency must reflect USE, or a dominant
            # prefix dispatched constantly ages out of the window behind
            # 128 one-off prompts and affinity silently stops for exactly
            # the workload it targets
            try:
                chosen.recent_prefixes.remove(key)
            except ValueError:
                pass
            chosen.recent_prefixes.append(key)
        if sid is not None:
            self._sessions[sid] = chosen.replica_id
            chosen.sessions.add(sid)
        return chosen

    def _arm_deadline(self, deadline: float | None) -> None:
        """Fold one ticket's deadline into the earliest-deadline watermark
        (caller holds the lock). The dispatch loop sweeps only once the
        watermark passes — never per-iteration scans."""
        if deadline is not None and (
            self._next_deadline is None or deadline < self._next_deadline
        ):
            self._next_deadline = deadline

    def _expire_queued(self) -> list[Ticket]:
        """Pull every past-deadline ticket out of the queue and recompute
        the earliest-deadline watermark (caller holds the lock; caller
        answers the expired tickets outside it)."""
        now = time.monotonic()
        expired = [
            t for t in self._queue
            if t.deadline is not None and now > t.deadline and not t.delivered
        ]
        if expired:
            gone = set(map(id, expired))
            self._queue = deque(t for t in self._queue if id(t) not in gone)
            self._deadline_expired += len(expired)
            for t in expired:
                self._bump_tenant(t.tenant, "deadline_expired")
        self._next_deadline = min(
            (t.deadline for t in self._queue if t.deadline is not None),
            default=None,
        )
        return expired

    def _deadline_error(self, ticket: Ticket, where: str) -> dict:
        return {"id": ticket.req_id, "error": f"deadline_exceeded: {where}"}

    def _dispatch_loop(self):
        while not self._stopped.is_set():
            failed: list[Ticket] = []
            expired: list[Ticket] = []
            ticket = replica = None
            with self._lock:
                while not self._queue and not self._stopped.is_set():
                    self._work.wait(timeout=0.2)
                if self._stopped.is_set():
                    return
                if (
                    self._next_deadline is not None
                    and time.monotonic() >= self._next_deadline
                ):
                    expired = self._expire_queued()
                if self._queue:
                    ticket = self._queue[0]
                if ticket is not None and ticket.delivered:
                    # a rescued ticket whose wedged dispatch answered late:
                    # already delivered, nothing left to do
                    self._queue.popleft()
                    ticket = None
                if ticket is not None:
                    replica = self._pick_replica(ticket)
                    if replica is None:
                        # A spawned replica's death is permanent; if the
                        # whole fleet is spawned-and-gone, waiting would
                        # hang drain() for its full timeout with the
                        # tickets never answered. Attached replicas can
                        # come back, so a fleet with any attached member
                        # keeps waiting — and so does one whose supervisor
                        # will respawn the dead (tickets with deadlines
                        # still expire while they wait).
                        if all(
                            r.process is not None
                            and r.state in ("dead", "terminated")
                            for r in self.replicas
                        ) and not (
                            self.supervisor is not None
                            and self.supervisor.will_respawn()
                        ):
                            failed = list(self._queue)
                            self._queue.clear()
                    else:
                        self._queue.popleft()
                        replica.in_flight += 1
                        replica.dispatched += 1
                        ticket.replica_id = replica.replica_id
                        ticket.attempts += 1
                        self._inflight.setdefault(replica.replica_id, set()).add(ticket)
            for t in expired:
                self._finish(t, self._deadline_error(
                    t, "expired in the router queue before dispatch"
                ))
            if replica is None:
                for t in failed:
                    self._finish(t, {
                        "id": t.req_id,
                        "error": "every replica is dead: request cannot be served",
                    })
                if ticket is not None:
                    time.sleep(0.05)
                continue
            tr = get_tracer()
            if tr and ticket.trace_id:
                # the flow-arrow TAIL: merge draws router-dispatch →
                # replica-admit once both files land in one timeline
                tr.request_instant(
                    ticket.trace_id, "req/dispatch",
                    replica=replica.replica_id, attempt=ticket.attempts,
                )
                tr.flow(ticket.trace_id, "s")
            threading.Thread(
                target=self._dispatch_one, args=(ticket, replica),
                name=f"router-req-{replica.replica_id}", daemon=True,
            ).start()

    def _dispatch_one(self, ticket: Ticket, replica: ReplicaHandle):
        payload = ticket.payload
        if ticket.deadline is not None:
            # thread the REMAINING budget to the replica: queue wait already
            # spent part of it, and the engine enforces its share (evicting
            # the slot the moment the deadline passes)
            remaining_ms = (ticket.deadline - time.monotonic()) * 1000.0
            payload = dict(payload, deadline_ms=max(remaining_ms, 1.0))
        try:
            result = replica.generate(payload, timeout=self.request_timeout)
        except ReplicaError as e:
            # A request_timeout expiry means slow-but-alive: the ticket is
            # requeued, but neither the failure counter nor the death probe
            # runs — a dead replica resets the connection instantly, so a
            # timeout is never death evidence (the slow replica keeps its
            # `ready` state and its other in-flight work).
            timed_out = isinstance(e, ReplicaTimeout)
            with self._lock:
                replica.in_flight -= 1
                if not timed_out:
                    replica.consecutive_failures += 1
                # if _mark_dead already requeued this ticket (wedged-replica
                # rescue), this dispatch's failure is old news — a second
                # requeue would dispatch the request twice concurrently
                rescued = ticket not in self._inflight.get(replica.replica_id, ())
                self._inflight.get(replica.replica_id, set()).discard(ticket)
                if not rescued:
                    self._requeues += 1
                    self._bump_tenant(ticket.tenant, "requeued")
                stopped = self._stopped.is_set()
            if not timed_out:
                self._note_failure(replica)
            if rescued:
                return
            expired = ticket.deadline is not None and time.monotonic() > ticket.deadline
            if expired:
                # never retry an expired ticket: the caller stopped caring
                # at the deadline, and a retry would burn a replica slot on
                # an answer nobody reads
                with self._lock:
                    self._deadline_expired += 1
                    self._bump_tenant(ticket.tenant, "deadline_expired")
                self._finish(ticket, self._deadline_error(
                    ticket, f"expired after {ticket.attempts} dispatch attempt(s)"
                ))
            elif ticket.attempts >= self.max_attempts:
                self._finish(ticket, {
                    "id": ticket.req_id,
                    "error": f"gave up after {ticket.attempts} dispatch attempts: {e}",
                })
            elif stopped:
                # the dispatch loop is gone — a requeue would be silence;
                # an error row is still exactly one answer
                self._finish(ticket, {
                    "id": ticket.req_id,
                    "error": f"router stopped before the request could be retried: {e}",
                })
            else:
                with self._lock:
                    # front of the queue: a victim of a replica crash has
                    # already waited its turn once
                    self._queue.appendleft(ticket)
                    self._arm_deadline(ticket.deadline)
                    self._work.notify()
                tr = get_tracer()
                if tr and ticket.trace_id:
                    tr.request_instant(
                        ticket.trace_id, "req/requeue",
                        replica=replica.replica_id, attempt=ticket.attempts,
                        timeout=timed_out,
                    )
            return
        cleared_probation = False
        with self._lock:
            replica.in_flight -= 1
            replica.completed += 1
            self._inflight.get(replica.replica_id, set()).discard(ticket)
            if replica.probation:
                # half-open probe served: count it, and promote the replica
                # back to full membership once it has proven itself
                replica.probation_successes += 1
                needed = (
                    self.supervisor.cfg.probation_successes
                    if self.supervisor is not None else 1
                )
                if replica.probation_successes >= needed:
                    replica.probation = False
                    cleared_probation = True
        if cleared_probation and self.supervisor is not None:
            self.supervisor.notify_recovery(replica)
        self._finish(ticket, result)

    def _finish(self, ticket: Ticket, result: dict, count_delivered: bool = True):
        """Deliver exactly once — a retry racing a late first answer must
        not double-deliver."""
        if (
            isinstance(result, dict)
            and ticket.trace_id
            and "trace_id" not in result
        ):
            # router-originated answers (shed/deadline/dead-fleet error
            # rows) carry the trace id too — every answer row is
            # correlatable, not just the ones a replica produced
            result["trace_id"] = ticket.trace_id
        with self._lock:
            if ticket.delivered:
                return
            ticket.delivered = True
            ticket.result = result
            if count_delivered:
                self._delivered += 1
                self._outstanding -= 1
                self._bump_tenant(ticket.tenant, "delivered")
            # token accounting lives under the delivered guard: a late
            # answer from a wedged replica must not double-count
            if isinstance(result, dict) and isinstance(result.get("tokens"), list):
                self._tokens += len(result["tokens"])
        tr = get_tracer()
        if tr and ticket.trace_id:
            # under the delivered guard above we returned on a duplicate,
            # so exactly one end event closes the router-side span
            error = result.get("error") if isinstance(result, dict) else None
            tr.request_end(
                ticket.trace_id, "req/finish", ok=error is None,
                attempts=ticket.attempts, replica=ticket.replica_id,
                **({"error": str(error)[:200]} if error is not None else {}),
            )
        if ticket.callback is not None:
            try:
                ticket.callback(result)
            except Exception:
                logger.warning("router result callback raised", exc_info=True)
        ticket.done.set()

    # -- health --------------------------------------------------------------

    def _note_failure(self, replica: ReplicaHandle):
        """A dispatch failed at the transport level: if the process is gone
        (or an attached replica stopped answering), mark it dead *now* so
        the very next dispatch decision excludes it — waiting for the next
        health tick would bounce the requeued request straight back."""
        # 3s, not 1s: a dead replica refuses the connection instantly, so the
        # timeout only bites a slow-but-alive one — where marking dead is wrong
        if replica.process_exited() or replica.check_health(timeout=3.0) is None:
            self._mark_dead(replica)

    def _mark_dead(self, replica: ReplicaHandle):
        with self._lock:
            if self._health_paused:
                # drain/close owns the fleet now: its SIGTERM exits are
                # expected, and a death verdict racing the teardown would
                # kill a replica that is busy answering its last in-flight
                # requests (found while race-checking the drain path)
                return
            if replica.state == "dead":
                return
            replica.state = "dead"
            for sid in replica.sessions:
                if self._sessions.get(sid) == replica.replica_id:
                    del self._sessions[sid]
            replica.sessions.clear()
            replica.recent_prefixes.clear()  # its radix cache died with it
            # rescue the requests POSTed to it: a killed replica errors the
            # dispatch thread out on its own, but a wedged-alive one keeps
            # the socket open forever — requeue now, and the late dispatch
            # thread (which sees its ticket gone from _inflight) stands down.
            # A late *answer* still wins if it lands first: _finish delivers
            # exactly once either way.
            stranded = self._inflight.get(replica.replica_id, set())
            rescued = len(stranded)
            rescued_trace_ids = [t.trace_id for t in stranded if t.trace_id]
            for t in stranded:
                self._queue.appendleft(t)
                self._requeues += 1
                self._bump_tenant(t.tenant, "requeued")
                # re-arm the expiry watermark: a rescued deadline ticket
                # must be answered, never re-dispatched past its budget
                self._arm_deadline(t.deadline)
            stranded.clear()
            if rescued:
                self._work.notify()
        tr = get_tracer()
        if tr:  # outside the lock, like every other event site
            for tid in rescued_trace_ids:
                tr.request_instant(
                    tid, "req/requeue", replica=replica.replica_id,
                    rescued=True,
                )
        logger.warning(
            "replica %d (pid %s) is dead — %d in-flight request(s) requeued, "
            "sessions released", replica.replica_id, replica.pid, rescued,
        )
        # a spawned replica that is dead to the router is dead for real: a
        # wedged-but-alive process (SIGSTOP, engine deadlock) abandoned here
        # would leak forever — and hold its HBM — since drain() skips dead
        # replicas. SIGKILL works on stopped processes.
        if replica.process is not None and replica.process.poll() is None:
            logger.warning(
                "replica %d (pid %s) process still alive after death verdict "
                "(wedged) — killing", replica.replica_id, replica.pid,
            )
            replica.kill()
        self._write_fleet_rows()
        if self.supervisor is not None:
            self.supervisor.notify_death(replica)

    def _teardown_started(self) -> bool:
        """True once drain/close owns the fleet's states (written and read
        under the lock — race-check RC001 guards it like the rest)."""
        with self._lock:
            return self._health_paused

    def _probe_one(self, replica: ReplicaHandle):
        """One replica's health-tick logic (runs on its own probe thread —
        a sweep must not serialize N probe timeouts, or the fleet trail
        goes stale and monitor reads healthy replicas as dead)."""
        r = replica
        if self._teardown_started() or self._stopped.is_set():
            return  # drain/close started mid-sweep: its exits are expected
        if r.state in ("dead", "terminated"):
            if r.process is None and r.check_health() is not None:
                logger.info("attached replica %d is back", r.replica_id)
            return
        if r.state == "draining":
            # supervisor scale-down: the exit is intentional — record it as
            # `terminated`, never `dead` (which would trigger a respawn)
            if r.process_exited():
                r.state = "terminated"
            return
        if r.process_exited():
            self._mark_dead(r)  # stands down on its own once teardown owns us
        elif r.check_health(timeout=5.0) is None:
            if r.state == "starting" and r.process is not None:
                # bring-up: the HTTP server may not even be bound
                # yet — connection-refused here is not death
                # evidence (process_exited above is), and the
                # bring-up deadline is wait_until_ready's job
                return
            # For a spawned replica the process is the authoritative
            # liveness signal — missed probes there mean wedged, not
            # dead, and a busy box starves /healthz long before the
            # engine stops serving (tiny-shape decode holds the GIL),
            # so give spawned replicas a much longer horizon before
            # the irreversible mark. Attached replicas have no
            # process to ask: three strikes is all the signal there is.
            r.consecutive_failures += 1
            strikes = 3 if r.process is None else 10
            if r.consecutive_failures >= strikes:
                self._mark_dead(r)

    def _health_loop(self):
        while not self._stopped.wait(self.health_interval):
            self._health_sweep()

    def _health_sweep(self):
        """One probe sweep over a lock-held snapshot of the fleet. The
        supervisor appends/replaces replicas under the lock at runtime
        (respawn, scale-up) — iterating the live list lock-free here raced
        those edits (race-check RC001 finding, fixed)."""
        with self._lock:
            if self._health_paused:
                # drain is SIGTERM-ing replicas: their exits are *expected*
                # and must land as `terminated`, not `dead`
                return
            fleet = list(self.replicas)
        probes = [
            threading.Thread(
                target=self._probe_one, args=(r,),
                name=f"router-probe-{r.replica_id}", daemon=True,
            )
            for r in fleet
        ]
        for t in probes:
            t.start()
        for t in probes:
            t.join(timeout=6.0)
        if not self._teardown_started():
            self._write_fleet_rows()

    def _write_fleet_rows(self):
        if self.logging_dir is None:  # no trail configured at all
            return
        now = time.time()
        with self._lock:
            rows = [
                {  # built under the lock; written after releasing it so a
                   # slow disk never stalls admission/dispatch/delivery

                    "schema": ROUTER_SCHEMA,
                    "ts": now,
                    "replica_id": r.replica_id,
                    "state": r.state,
                    "base_url": r.base_url,
                    "pid": r.pid,
                    "queue_depth": r.queue_depth,
                    "active_slots": r.active_slots,
                    "num_slots": r.num_slots,
                    "in_flight": r.in_flight,
                    "dispatched": r.dispatched,
                    "completed": r.completed,
                    "deadline_expired": r.deadline_expired,
                    "sessions": len(r.sessions),
                    "restarts": r.restarts,
                    "probation": r.probation,
                    "heartbeat_age_s": (
                        round(now - r.last_heartbeat, 3)
                        if r.last_heartbeat is not None else None
                    ),
                }
                for r in self.replicas
            ]
            totals = {
                "schema": ROUTER_SCHEMA,
                "kind": "router",  # router-wide totals, one per tick
                "ts": now,
                # explicit Nones: readers that index per-replica keys on
                # every row (state checks, pid maps) stay correct without
                # knowing about aggregate rows
                "replica_id": None,
                "state": None,
                "pid": None,
                "queue_depth": len(self._queue),
                "outstanding": self._outstanding,
                "delivered": self._delivered,
                "requeues": self._requeues,
                "rejected": self._rejected,
                "shed": self._shed,
                "deadline_expired": self._deadline_expired,
                # fleet-wide expiry view: deadlines mostly expire *inside*
                # the replicas (slot evicted, partial answer still delivered)
                # because dispatch is uncapped and the router queue rarely
                # builds — the SLO error-rate feed reads this key so those
                # expiries count as breach evidence too. Replica counters
                # reset on restart; readers treat a negative delta as a seam.
                "fleet_deadline_expired": self._deadline_expired
                + sum(r.deadline_expired for r in self.replicas),
                # summed engine admission backlog: the "queued" pressure
                # signal when the router queue itself is empty
                "replica_queue_depth": sum(r.queue_depth for r in self.replicas),
                # per-tenant outcome attribution (usage ledger at the fleet
                # seam) — capped to top-K + "other" so a hostile tenant-id
                # stream cannot grow the trail rows or the scrape unbounded
                "by_tenant": cap_by_key(
                    self._by_tenant, DEFAULT_TOP_K, weight_field="delivered"
                ),
            }
        if self.supervisor is not None:
            sup = self.supervisor
            for row in rows:
                row.update(sup.row_fields(row["replica_id"]))
            totals.update(sup.stats())
        # totals lead the tick: readers tailing "the newest replica row"
        # (monitor, tests) keep seeing a replica row last
        rows.insert(0, totals)
        # _trail_lock is a leaf lock whose entire purpose is this file:
        # the health tick and _mark_dead both land here, and unsynchronized
        # write() calls from two threads tear JSONL rows mid-line (the
        # trail is the monitor's only view of the fleet). Nothing else is
        # ever acquired under it. The dispatch lock stays released — a
        # slow disk still never stalls admission/dispatch/delivery.
        with self._trail_lock:
            trail = self._trail  # _shutdown nulls it under this same lock
            if trail is None:
                return
            try:
                for row in rows:
                    # tpu-lint: ignore[RC003] — serializing this file IS the lock's job; leaf lock, nothing acquired under it
                    trail.write(json.dumps(row) + "\n")
                trail.flush()  # tpu-lint: ignore[RC003] — same leaf-lock rationale
            except (OSError, ValueError):
                pass

    def write_decision_row(self, fields: dict) -> None:
        """Append a ``kind:"scale_decision"`` row to the fleet trail — the
        supervisor's SLO policy logs every verdict (scale_up / scale_down /
        hold, with the breached objective, burn rate, and dominant phase as
        evidence) so a scaling action is auditable next to the fleet state
        it reacted to. Same None-field convention as the totals rows, same
        leaf-lock discipline."""
        row = {
            "schema": ROUTER_SCHEMA,
            "ts": time.time(),
            "kind": "scale_decision",
            "replica_id": None,
            "state": None,
            "pid": None,
            **fields,
        }
        with self._trail_lock:
            trail = self._trail  # _shutdown nulls it under this same lock
            if trail is None:
                return
            try:
                # tpu-lint: ignore[RC003] — leaf lock, serializing this file is its job
                trail.write(json.dumps(row, default=str) + "\n")
                trail.flush()  # tpu-lint: ignore[RC003] — same leaf-lock rationale
            except (OSError, ValueError):
                pass

    # -- drain / shutdown ----------------------------------------------------

    def stop_admission(self):
        """Flip to draining: every later ``submit`` is answered with an
        admission-stopped error instead of being queued."""
        with self._lock:
            self._draining = True

    def wait_idle(self, timeout: float | None = None, poll: float = 0.05) -> bool:
        """Block until every submitted request has been delivered."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                if self._outstanding == 0 and not self._queue:
                    return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(poll)

    def drain(self, timeout: float = 300.0) -> bool:
        """Stop admission, answer everything in flight, then SIGTERM the
        spawned replicas and wait for clean exits. Returns True when every
        request was answered and every spawned replica exited."""
        with self._lock:
            self._draining = True
        drained = self.wait_idle(timeout=timeout)
        # From here the replicas' exits are intentional: stop the supervisor
        # FIRST (a respawn racing the kill loop would leak a process), then
        # freeze the health loop so a SIGTERM'd replica is recorded as
        # `terminated`, not `dead`.
        if self.supervisor is not None:
            self.supervisor.stop()
        with self._lock:
            # under the lock: a _mark_dead racing the teardown must see the
            # flag (and stand down) or finish first — never interleave
            self._health_paused = True
            fleet = list(self.replicas)
        for r in fleet:
            if r.state not in ("dead", "terminated"):
                r.state = "draining"
        self._write_fleet_rows()
        for r in fleet:
            r.drain()
        clean = True
        deadline = time.monotonic() + timeout
        for r in fleet:
            if r.state == "dead":
                continue
            if r.process is None:
                # attached replicas have no process to wait on, but this
                # router session is over: a final `terminated` row keeps
                # monitor from reading the last `draining` row as a death
                r.state = "terminated"
                continue
            rc = r.wait(timeout=max(0.1, deadline - time.monotonic()))
            if rc is None:
                logger.warning("replica %d did not exit on SIGTERM; killing", r.replica_id)
                r.kill()
                r.wait(timeout=10.0)
                clean = False
            r.state = "terminated"
        self._write_fleet_rows()
        self._shutdown()
        return drained and clean

    def _shutdown(self):
        self._stopped.set()
        with self._lock:
            self._work.notify_all()
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=10.0)
        with self._trail_lock:
            if self._trail is not None:
                try:
                    self._trail.close()
                except OSError:
                    pass
                self._trail = None
        get_active_lockwatch().flush()  # hold-time histograms → telemetry

    def close(self):
        """Abrupt teardown (tests, error paths): kill what we spawned."""
        if self.supervisor is not None:
            self.supervisor.stop()  # no respawns behind the kill loop
        self._stopped.set()
        with self._lock:
            self._health_paused = True  # death verdicts stand down from here
            fleet = list(self.replicas)
            self._work.notify_all()
        for r in fleet:
            r.kill()
        for r in fleet:
            r.wait(timeout=10.0)  # reap: a killed child must not linger
        self._shutdown()

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = {
                "replicas": len(self.replicas),
                "ready": sum(r.state == "ready" for r in self.replicas),
                "dead": sum(r.state == "dead" for r in self.replicas),
                "probation": sum(r.probation for r in self.replicas),
                "queue_depth": len(self._queue),
                "outstanding": self._outstanding,
                "delivered": self._delivered,
                "requeues": self._requeues,
                "rejected": self._rejected,
                "shed": self._shed,
                "deadline_expired": self._deadline_expired,
                "tokens": self._tokens,
                "sessions": len(self._sessions),
                "by_tenant": cap_by_key(
                    self._by_tenant, DEFAULT_TOP_K, weight_field="delivered"
                ),
                "per_replica": {
                    r.replica_id: {
                        "state": r.state,
                        "dispatched": r.dispatched,
                        "completed": r.completed,
                        "in_flight": r.in_flight,
                        "restarts": r.restarts,
                        "probation": r.probation,
                    }
                    for r in self.replicas
                },
            }
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.stats()
        return out
