"""Per-slot sampling for the serving engine.

The continuous-batching engine compiles ONE decode executable and keeps it
for the life of the process (``decode_compiles == 1`` is pin-tested).  That
rules out the obvious way to support per-request sampling params — baking
them into the trace — so everything a request can vary rides in as *traced
lane inputs*: fixed-shape ``[num_slots]`` arrays (plus one ``[num_slots,
rep_window]`` ring for the repetition penalty) whose abstract signature
never changes no matter which requests occupy the slots.

Randomness is derived, never threaded: the per-slot key for output
position ``pos`` is ``fold_in(fold_in(fold_in(base_key, tag), seed),
pos)``.  Because the key depends only on (request seed, output position,
draw kind) — not on the slot index, the batch composition, or how many
bursts it took to get there — identical ``(seed, prompt)`` pairs reproduce
the same completion across admission orders and across preempt/swap/resume
cycles.  The ``tag`` separates the independent draws a speculative round
makes at the same position (draft proposal, accept/reject uniform,
residual resample).

The greedy fast path matters: when no live slot needs sampling, grammar
masking, repetition penalty, or min-token suppression, ``pick_tokens``
drops to a bare argmax under ``lax.cond`` — bit-identical to the pre-lane
engine and within the <1 % overhead bar ``bench.py sampling`` enforces.

Host-side bookkeeping (stop sequences, min/max tokens, the authoritative
DFA state) lives on the request object; this module only supplies the
pure helpers (:func:`match_stop`, :class:`SamplingParams`).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..generation import scale_logits

__all__ = [
    "NEG",
    "SamplingParams",
    "resolve_sampling",
    "blank_lanes",
    "set_slot_lane",
    "clear_slot_lane",
    "match_stop",
    "slot_keys",
    "categorical_per_slot",
    "uniform_per_slot",
    "apply_filters",
    "dist_logprobs",
    "pick_tokens",
    "rejection_accept",
    "TAG_SAMPLE",
    "TAG_DRAFT",
    "TAG_ACCEPT",
    "TAG_RESAMPLE",
]

#: large-but-finite mask fill.  Not -inf: a fully-masked row (a grammar's
#: terminal state, sampled only on discarded burst tails) must softmax to
#: uniform garbage, not NaN.
NEG = -1e30

# Draw kinds folded into the per-slot key so a speculative round's
# independent draws at the same output position don't collide.
TAG_SAMPLE = 0  # plain decode / prefill token pick
TAG_DRAFT = 1  # speculative draft proposal
TAG_ACCEPT = 2  # accept/reject uniform in the verify round
TAG_RESAMPLE = 3  # residual resample / bonus token


# --------------------------------------------------------------------------
# host-side request params
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs, validated once at admission.

    ``stop`` is a tuple of token-id tuples — the engine works in token
    ids; the OpenAI layer encodes string stops with the byte vocabulary
    before they get here.  ``logprobs`` asks for the top-N per-step
    logprobs and must be ≤ the engine's static ``logprobs_topn`` cap
    (the cap shapes the compiled harvest, the request only opts in).
    """

    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    seed: int = 0
    min_tokens: int = 0
    stop: tuple = ()
    logprobs: int = 0

    def validate(self):
        if self.temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {self.temperature}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if self.repetition_penalty <= 0:
            raise ValueError(
                f"repetition_penalty must be > 0, got {self.repetition_penalty}"
            )
        if self.min_tokens < 0:
            raise ValueError(f"min_tokens must be >= 0, got {self.min_tokens}")
        if self.logprobs < 0:
            raise ValueError(f"logprobs must be >= 0, got {self.logprobs}")
        for seq in self.stop:
            if not seq:
                raise ValueError("stop sequences must be non-empty")
        return self

    @property
    def inert(self):
        """True when this request is indistinguishable from bare greedy —
        lets the engine keep the argmax fast path for the whole batch."""
        return (
            not self.do_sample
            and self.repetition_penalty == 1.0
            and self.min_tokens == 0
            and self.logprobs == 0
        )


def resolve_sampling(obj, default=None):
    """Coerce ``None`` / dict / :class:`SamplingParams` into validated
    params.  ``None`` inherits the engine default (itself derived from the
    legacy engine-wide ``do_sample``/``temperature`` config)."""
    if obj is None:
        return default if default is not None else SamplingParams()
    if isinstance(obj, SamplingParams):
        return obj.validate()
    if isinstance(obj, dict):
        allowed = {f.name for f in dataclasses.fields(SamplingParams)}
        unknown = set(obj) - allowed
        if unknown:
            raise ValueError(
                f"unknown sampling params {sorted(unknown)} (allowed: {sorted(allowed)})"
            )
        kw = dict(obj)
        if "stop" in kw:
            stops = kw["stop"]
            if isinstance(stops, (list, tuple)) and stops and isinstance(
                stops[0], (int, np.integer)
            ):
                stops = [stops]  # one bare token-id sequence
            kw["stop"] = tuple(tuple(int(t) for t in s) for s in (stops or ()))
        return SamplingParams(**kw).validate()
    raise ValueError(f"sampling must be a dict or SamplingParams, got {type(obj)!r}")


# --------------------------------------------------------------------------
# lanes: the fixed-shape traced inputs
# --------------------------------------------------------------------------

_LANE_SPECS = (
    # name, dtype, inert default
    ("sample", np.bool_, False),
    ("temp", np.float32, 1.0),
    ("top_k", np.int32, 0),
    ("top_p", np.float32, 1.0),
    ("rep", np.float32, 1.0),
    ("seed", np.int32, 0),
    ("pos", np.int32, 0),
    ("min_tokens", np.int32, 0),
    ("grammar_row", np.int32, 0),
    ("dfa_state", np.int32, 0),
)


def blank_lanes(num_slots, rep_window):
    """All-inert lanes: every slot behaves exactly like the pre-lane
    greedy engine until :func:`set_slot_lane` arms it."""
    lanes = {
        name: np.full((num_slots,), default, dtype=dtype)
        for name, dtype, default in _LANE_SPECS
    }
    lanes["rep_ring"] = np.full((num_slots, rep_window), -1, dtype=np.int32)
    return lanes


def set_slot_lane(lanes, slot, params, pos, grammar_row=0, dfa_state=0, recent=()):
    """Arm one slot from its request state.  ``pos`` is the number of
    output tokens already emitted — the key-derivation position of the
    NEXT token, recomputed from the request on every dispatch so
    preemption/swap cannot desynchronise it.  ``recent`` is the tail of
    the output tokens feeding the repetition-penalty ring."""
    lanes["sample"][slot] = bool(params.do_sample)
    lanes["temp"][slot] = float(params.temperature)
    lanes["top_k"][slot] = int(params.top_k)
    lanes["top_p"][slot] = float(params.top_p)
    lanes["rep"][slot] = float(params.repetition_penalty)
    lanes["seed"][slot] = np.int32(np.uint32(int(params.seed) & 0xFFFFFFFF))
    lanes["pos"][slot] = int(pos)
    lanes["min_tokens"][slot] = int(params.min_tokens)
    lanes["grammar_row"][slot] = int(grammar_row)
    lanes["dfa_state"][slot] = int(dfa_state)
    ring = lanes["rep_ring"]
    ring[slot, :] = -1
    if recent is not None and params.repetition_penalty != 1.0:
        tail = list(recent)[-ring.shape[1] :]
        if tail:
            ring[slot, : len(tail)] = tail


def clear_slot_lane(lanes, slot):
    for name, dtype, default in _LANE_SPECS:
        lanes[name][slot] = dtype(default)
    lanes["rep_ring"][slot, :] = -1


def match_stop(tokens, stop_seqs):
    """Return the length of the stop sequence matched at the tail of
    ``tokens`` (so the caller can trim it), or 0."""
    for seq in stop_seqs:
        n = len(seq)
        if n and len(tokens) >= n and tuple(tokens[-n:]) == tuple(seq):
            return n
    return 0


# --------------------------------------------------------------------------
# traced helpers
# --------------------------------------------------------------------------


def slot_keys(base_key, seed_lane, pos_lane, tag):
    """Per-slot keys for one draw kind: fold the tag (static), then each
    slot's request seed, then its output position."""
    tagged = jax.random.fold_in(base_key, tag)

    def one(seed, pos):
        return jax.random.fold_in(jax.random.fold_in(tagged, seed), pos)

    return jax.vmap(one)(seed_lane, pos_lane)


def categorical_per_slot(keys, logits):
    """One categorical draw per slot, each under its own key (``logits``
    may be unnormalised log-probs)."""
    return jax.vmap(lambda k, l: jax.random.categorical(k, l))(keys, logits).astype(
        jnp.int32
    )


def uniform_per_slot(keys):
    return jax.vmap(lambda k: jax.random.uniform(k, ()))(keys)


def apply_filters(logits, lanes, dfa_state, pos, gmask, eos_id):
    """Everything that reshapes the distribution *before* temperature:
    repetition penalty, grammar allow-mask, min-token eos suppression.
    Greedy slots argmax the result, sampled slots feed it to
    :func:`dist_logprobs`, and the reported logprobs are its plain
    log-softmax — one definition of "the filtered distribution" shared by
    all three consumers.

    ``dfa_state`` is passed separately from ``lanes['dfa_state']``
    because mid-burst / mid-draft steps advance it in-trace; ``pos`` is
    likewise the per-step effective position (``lanes['pos'] + step``).
    """
    num_slots, vocab = logits.shape
    rows = jnp.arange(num_slots)[:, None]
    ring = lanes["rep_ring"]
    present = (
        jnp.zeros((num_slots, vocab), bool)
        .at[rows, jnp.clip(ring, 0, vocab - 1)]
        .max(ring >= 0)
    )
    rep = lanes["rep"][:, None]
    penalized = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(present & (rep != 1.0), penalized, logits)

    mask = gmask[lanes["grammar_row"], dfa_state]
    logits = jnp.where(mask, logits, NEG)

    if eos_id is not None:
        suppress = pos < lanes["min_tokens"]
        logits = logits.at[:, eos_id].add(jnp.where(suppress, NEG, 0.0))
    return logits


def dist_logprobs(filtered, lanes):
    """Per-slot temperature + top-k + top-p over already-filtered logits,
    returned as log-probs in original token order (``NEG`` where cut).
    Both the plain sampled pick and the speculative p/q distributions go
    through here, so draft and target probabilities are filtered by the
    exact same rule — a requirement for the rejection-sampling identity
    to hold."""
    num_slots, vocab = filtered.shape
    scaled = scale_logits(filtered, lanes["temp"][:, None])
    vals, idx = jax.lax.top_k(scaled, vocab)  # full descending sort
    k_eff = jnp.where(lanes["top_k"] <= 0, vocab, lanes["top_k"])
    keep_k = jnp.arange(vocab)[None, :] < k_eff[:, None]
    probs_sorted = jax.nn.softmax(vals, axis=-1)
    csum = jnp.cumsum(probs_sorted, axis=-1)
    # keep every token whose preceding cumulative mass is < top_p — the
    # highest-prob token always survives (its preceding mass is 0)
    keep_p = (csum - probs_sorted) < lanes["top_p"][:, None]
    keep = keep_k & keep_p
    kept = jnp.where(keep, vals, NEG)
    logp_sorted = jax.nn.log_softmax(kept, axis=-1)
    rows = jnp.arange(num_slots)[:, None]
    return (
        jnp.full((num_slots, vocab), NEG, filtered.dtype)
        .at[rows, idx]
        .set(jnp.where(keep, logp_sorted, NEG))
    )


def pick_tokens(logits, lanes, dfa_state, step, gmask, base_key, *, eos_id, logprobs_topn):
    """The per-slot decode-step pick.  Returns ``(tok [S], logp_tok [S],
    top_vals [S,N], top_ids [S,N])`` with ``N = max(logprobs_topn, 1)``
    (zeros when harvesting is off — the shapes must be static).

    When every lane is inert a ``lax.cond`` routes the whole batch to a
    bare argmax — token-identical to the pre-lane greedy engine and the
    reason the armed-but-idle overhead stays under the bench bar.
    """
    num_slots, vocab = logits.shape
    n = max(int(logprobs_topn), 1)
    pos = lanes["pos"] + step

    def plain(_):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (
            tok,
            jnp.zeros((num_slots,), jnp.float32),
            jnp.zeros((num_slots, n), jnp.float32),
            jnp.zeros((num_slots, n), jnp.int32),
        )

    def fancy(_):
        filtered = apply_filters(logits, lanes, dfa_state, pos, gmask, eos_id)
        greedy = jnp.argmax(filtered, axis=-1).astype(jnp.int32)
        logp_dist = dist_logprobs(filtered, lanes)
        keys = slot_keys(base_key, lanes["seed"], pos, TAG_SAMPLE)
        sampled = categorical_per_slot(keys, logp_dist)
        tok = jnp.where(lanes["sample"], sampled, greedy).astype(jnp.int32)
        # reported logprobs are the filtered distribution at temperature 1
        # (OpenAI semantics: the model's distribution, not the sampler's)
        lp = jax.nn.log_softmax(jnp.asarray(filtered, jnp.float32), axis=-1)
        logp_tok = jnp.take_along_axis(lp, tok[:, None], axis=1)[:, 0]
        top_vals, top_ids = jax.lax.top_k(lp, n)
        return tok, logp_tok, top_vals, top_ids.astype(jnp.int32)

    if logprobs_topn > 0:
        return fancy(None)

    work = (
        jnp.any(lanes["sample"])
        | jnp.any(lanes["grammar_row"] > 0)
        | jnp.any(lanes["rep"] != 1.0)
        | jnp.any(pos < lanes["min_tokens"])
    )
    return jax.lax.cond(work, fancy, plain, None)


# --------------------------------------------------------------------------
# speculative rejection sampling
# --------------------------------------------------------------------------


def rejection_accept(d, p, q, u, base_key, seed_lane, pos_lane):
    """Standard speculative-sampling acceptance for the sampled slots of a
    verify round.

    ``d [S, k]`` are the draft tokens, ``p [k+1, S, V]`` the target-model
    probabilities at each draft position (plus the bonus position), ``q
    [k, S, V]`` the draft-model probabilities the tokens were drawn from,
    ``u [S, k]`` the per-position accept uniforms.  Draft token ``j`` is
    accepted while ``u_j < min(1, p_j(d_j) / q_j(d_j))``; the first
    rejection resamples from the clamped residual ``max(p - q, 0)``, and a
    fully-accepted row draws its bonus token from ``p_k``.  The resample /
    bonus draw is keyed at the output position it lands on
    (``pos_lane + accept``, ``TAG_RESAMPLE``), so it is as
    admission-order- and preemption-independent as every other draw.
    Returns ``(accept [S], tok_seq [S, k+1])`` shaped exactly like
    :func:`accelerate_tpu.generation.spec_accept_tokens` so the engine can
    ``where`` the two per slot.

    Grammar masks are already inside ``p`` and ``q`` (both come out of
    :func:`dist_logprobs` over filtered logits), which is what makes the
    verify round re-check the mask: an out-of-language draft has target
    probability 0 and is rejected with certainty, and the residual is
    itself in-language.
    """
    num_slots, k = d.shape
    rows = jnp.arange(num_slots)

    p_d = jnp.stack([p[j, rows, d[:, j]] for j in range(k)], axis=1)
    q_d = jnp.stack([q[j, rows, d[:, j]] for j in range(k)], axis=1)
    ok = u < jnp.minimum(1.0, p_d / jnp.maximum(q_d, 1e-20))
    accept = jnp.where(
        ok.all(axis=1), k, jnp.argmin(ok.astype(jnp.int32), axis=1)
    ).astype(jnp.int32)

    p_a = p[accept, rows]  # [S, V] target dist at the first-reject position
    q_a = q[jnp.minimum(accept, k - 1), rows]
    resid = jnp.clip(p_a - q_a, 0.0, None)
    bonus = (accept == k)[:, None]
    dist = jnp.where(bonus, p_a, resid)
    degenerate = dist.sum(axis=-1, keepdims=True) <= 0.0
    dist = jnp.where(degenerate, p_a, dist)
    resample_keys = slot_keys(base_key, seed_lane, pos_lane + accept, TAG_RESAMPLE)
    corr = categorical_per_slot(resample_keys, jnp.log(dist + 1e-30))

    d_ext = jnp.concatenate([d, jnp.zeros((num_slots, 1), d.dtype)], axis=1)
    j = jnp.arange(k + 1)[None, :]
    a_col = accept[:, None]
    tok_seq = jnp.where(
        j < a_col, d_ext, jnp.where(j == a_col, corr[:, None], 0)
    ).astype(jnp.int32)
    return accept, tok_seq
