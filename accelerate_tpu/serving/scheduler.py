"""Iteration-level request scheduler for the continuous-batching engine.

Orca-style (OSDI '22) slot scheduling: the compiled decode step has a fixed
``num_slots`` batch dimension; this scheduler decides, *between* device
steps, which request occupies which slot. All decisions are host-side
Python — admission, eviction, and block accounting never touch the
compiled program, which is why the engine compiles exactly one decode
executable for its lifetime.

Policy (FCFS, no preemption):

* **evict** — finished requests release their slot and KV blocks first, so
  the capacity freed this iteration is admittable this iteration;
* **admit** — queued requests enter free slots in arrival order when the
  freelist covers their prompt (decode blocks are allocated incrementally
  as generation crosses block boundaries, so admission only reserves the
  prompt's footprint + one decode block);
* a request whose prompt is still being chunk-prefilled occupies its slot
  in ``PREFILL`` state; the engine advances one chunk per iteration so a
  long prompt never stalls in-flight decodes.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum

from .blocks import BlockAllocator, blocks_needed


class RequestState(Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    FINISHED = "finished"


_request_ids = itertools.count()


@dataclass
class Request:
    """One in-flight generation. ``prompt`` is a list of token ids;
    ``output_tokens`` grows as the engine emits. Timing fields are
    ``time.perf_counter`` seconds: ``ttft_s`` spans arrival → first emitted
    token (queue wait + prefill included), ``tpot_s`` is the mean
    inter-token interval after the first."""

    prompt: list[int]
    max_new_tokens: int
    request_id: int = field(default_factory=lambda: next(_request_ids))
    arrival_time: float = field(default_factory=time.perf_counter)
    state: RequestState = RequestState.QUEUED
    output_tokens: list[int] = field(default_factory=list)
    finish_reason: str | None = None  # "eos" | "length" | "out_of_blocks"
    slot: int | None = None
    blocks: list[int] = field(default_factory=list)
    prefill_pos: int = 0  # prompt tokens whose K/V are already cached
    first_token_time: float | None = None
    finish_time: float | None = None

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def context_len(self) -> int:
        """Tokens whose K/V sit in the cache (prompt + fed output)."""
        return self.prefill_pos + max(len(self.output_tokens) - 1, 0)

    @property
    def ttft_s(self) -> float | None:
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.arrival_time

    @property
    def tpot_s(self) -> float | None:
        n = len(self.output_tokens)
        if self.finish_time is None or self.first_token_time is None or n < 2:
            return None
        return (self.finish_time - self.first_token_time) / (n - 1)


class SlotScheduler:
    """Owns the waiting queue, the slot table, and the block allocator."""

    def __init__(self, num_slots: int, allocator: BlockAllocator, block_size: int,
                 max_seq_len: int):
        self.num_slots = int(num_slots)
        self.allocator = allocator
        self.block_size = int(block_size)
        self.max_seq_len = int(max_seq_len)
        self.waiting: deque[Request] = deque()
        self.slots: list[Request | None] = [None] * self.num_slots

    # -- queries -------------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    def active(self, state: RequestState | None = None) -> list[Request]:
        reqs = [r for r in self.slots if r is not None]
        if state is not None:
            reqs = [r for r in reqs if r.state is state]
        return reqs

    @property
    def occupancy(self) -> float:
        return sum(r is not None for r in self.slots) / self.num_slots

    def has_work(self) -> bool:
        return bool(self.waiting) or any(r is not None for r in self.slots)

    # -- transitions ---------------------------------------------------------

    def submit(self, request: Request) -> Request:
        total = request.prompt_len + request.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"request needs {total} cache positions "
                f"(prompt {request.prompt_len} + max_new {request.max_new_tokens}) "
                f"but the engine's max_seq_len is {self.max_seq_len}"
            )
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if request.prompt_len < 1:
            raise ValueError("empty prompt")
        usable = self.allocator.num_blocks - 1
        admit_need = max(blocks_needed(request.prompt_len + 1, self.block_size), 1)
        if admit_need > usable:
            # an unaffordable-forever head request would head-of-line block
            # admit() on every iteration and spin run_until_idle() for good
            raise ValueError(
                f"prompt needs {admit_need} KV blocks to admit but the pool "
                f"only has {usable}: raise num_blocks or shrink the prompt"
            )
        request.state = RequestState.QUEUED
        self.waiting.append(request)
        return request

    def evict_finished(self) -> list[Request]:
        """Release slots + blocks of finished requests (engine marks them)."""
        evicted = []
        for i, req in enumerate(self.slots):
            if req is not None and req.state is RequestState.FINISHED:
                self.allocator.free(req.blocks)
                req.blocks = []
                req.slot = None
                self.slots[i] = None
                evicted.append(req)
        return evicted

    def admit(self) -> list[Request]:
        """FCFS admission into free slots, bounded by the block freelist.
        Head-of-line blocking on blocks is intentional (no starvation of
        long prompts); a free slot with an unaffordable head request stays
        empty until eviction refills the freelist."""
        admitted = []
        free_slots = [i for i, r in enumerate(self.slots) if r is None]
        while free_slots and self.waiting:
            req = self.waiting[0]
            # prompt footprint + the first decode block, so a request can
            # always emit at least one token once admitted
            need = max(blocks_needed(req.prompt_len + 1, self.block_size), 1)
            if not self.allocator.can_allocate(need):
                break
            self.waiting.popleft()
            req.blocks = self.allocator.allocate(need)
            req.slot = free_slots.pop(0)
            req.state = RequestState.PREFILL
            req.prefill_pos = 0
            self.slots[req.slot] = req
            admitted.append(req)
        return admitted

    def grow_for_decode(self, req: Request, tokens_ahead: int = 1) -> bool:
        """Ensure blocks exist for the next ``tokens_ahead`` cache writes
        (a decode burst writes positions ``context_len ..
        context_len+tokens_ahead-1``). The span is capped at the request's
        own ``prompt + max_new`` budget (and the per-slot maximum): burst
        lane-steps past the budget may scatter into the null block, which
        is harmless, and allocating for them would truncate requests under
        pool pressure whose real remaining tokens already fit. False = the
        pool is exhausted; the engine force-finishes the request
        (truncation is observable via ``finish_reason="out_of_blocks"`` —
        with no preemption support, stalling could deadlock a full pool)."""
        need = blocks_needed(
            min(
                req.context_len + tokens_ahead,
                req.prompt_len + req.max_new_tokens,
                self.max_seq_len,
            ),
            self.block_size,
        )
        while len(req.blocks) < need:
            if not self.allocator.can_allocate(1):
                return False
            req.blocks.extend(self.allocator.allocate(1))
        return True
