"""Iteration-level request scheduler for the continuous-batching engine.

Orca-style (OSDI '22) slot scheduling: the compiled decode step has a fixed
``num_slots`` batch dimension; this scheduler decides, *between* device
steps, which request occupies which slot. All decisions are host-side
Python — admission, eviction, and block accounting never touch the
compiled program, which is why the engine compiles exactly one decode
executable for its lifetime.

Policy (priority classes, prefix sharing, swap preemption):

* **evict** — finished requests release their slot and KV blocks first, so
  the capacity freed this iteration is admittable this iteration; shared
  blocks are *decref'd* (the radix cache or other requests keep them),
  never hard-freed;
* **admit** — queued requests enter free slots in (priority class,
  arrival) order. Admission first maps the request's longest cached prefix
  from the :class:`~.radix.RadixCache` at refcount+1, then allocates only
  the tail; when the freelist is short, refcount-1 cached blocks are LRU
  evicted before admission gives up. Head-of-line blocking is per-fleet
  and intentional (no starvation of long prompts) — but the *engine* may
  preempt a lower-priority running request to unblock a higher-priority
  head (see ``InferenceEngine._admit_and_place``);
* **preemption** — under pool exhaustion the engine swaps a victim
  (:meth:`SlotScheduler.pick_victim`: lowest priority class first, latest
  arrival within it) to the host-DRAM swap pool and the victim re-queues
  at the *front* of its class via :meth:`requeue_preempted`;
* a request whose prompt is still being chunk-prefilled occupies its slot
  in ``PREFILL`` state; the engine advances one chunk per iteration so a
  long prompt never stalls in-flight decodes.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum

from .blocks import BlockAllocator, blocks_needed

#: admission-priority order, highest first (``interactive`` preempts
#: ``batch``, never the reverse)
PRIORITY_CLASSES = ("interactive", "batch")


def priority_rank(priority: str) -> int:
    """Smaller = more important. Unknown classes raise at submit()."""
    return PRIORITY_CLASSES.index(priority)


class RequestState(Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    FINISHED = "finished"


_request_ids = itertools.count()


@dataclass
class Request:
    """One in-flight generation. ``prompt`` is a list of token ids;
    ``output_tokens`` grows as the engine emits. Timing fields are
    ``time.perf_counter`` seconds: ``ttft_s`` spans arrival → first emitted
    token (queue wait + prefill included), ``tpot_s`` is the mean
    inter-token interval after the first.

    Prefix-sharing/preemption state: ``prefill_pos`` starts at the matched
    prefix length (cached tokens are never re-prefilled); ``cow`` is a
    ``(src_block, dst_block)`` device copy the engine owes before the first
    prefill chunk; ``swap_plan`` is ``[(block_index, swap_handle), ...]``
    for a preempted request's swapped-out rows, restored on re-admission."""

    prompt: list[int]
    max_new_tokens: int
    priority: str = "interactive"  # see PRIORITY_CLASSES
    #: accounting dimension, not an admission gate: any string is legal
    #: (the engine normalizes), unknown tenants never raise, and the key
    #: rides payload → Ticket → add_request → here exactly like
    #: ``priority``/``trace_id``, echoed on answer rows and usage rollups
    tenant: str = "default"
    request_id: int = field(default_factory=lambda: next(_request_ids))
    #: distributed-trace identity: born at the submit boundary (client-
    #: supplied or generated), echoed on every answer row, and stamped on
    #: every request-scoped trace event and latency exemplar — the one key
    #: that stitches a request's router hop, engine lifecycle, and metric
    #: buckets together
    trace_id: str | None = None
    arrival_time: float = field(default_factory=time.perf_counter)
    #: absolute ``time.perf_counter`` expiry (None = no deadline): the
    #: scheduler finishes the request with ``finish_reason=
    #: "deadline_exceeded"`` the first iteration after this passes, queued
    #: or running — freed blocks are admittable the same iteration
    deadline: float | None = None
    state: RequestState = RequestState.QUEUED
    output_tokens: list[int] = field(default_factory=list)
    finish_reason: str | None = None
    # "eos" | "length" | "stop" | "out_of_blocks" | "deadline_exceeded"
    slot: int | None = None
    #: resolved :class:`~.sampling.SamplingParams` (None on a
    #: per_slot_sampling=False engine). Lives on the request — not the
    #: slot — so preemption/swap/re-admission carries it for free and the
    #: lanes are rebuilt from it on every dispatch.
    sampling: object = None
    #: grammar table row this request holds a reference on (0 = the
    #: unconstrained sentinel row) and its authoritative DFA state — the
    #: host advances it per emitted token; in-trace advances only feed
    #: mid-burst masking and are discarded with the burst tail
    grammar_row: int = 0
    dfa_state: int = 0
    #: per-token logprob dicts when the request asked for them
    logprobs: list | None = None
    blocks: list[int] = field(default_factory=list)
    prefill_pos: int = 0  # prompt tokens whose K/V are already cached
    first_token_time: float | None = None
    finish_time: float | None = None
    matched_tokens: int = 0  # prefix-cache hit length at admission
    cow: tuple[int, int] | None = None  # (src, dst) pending device copy
    swap_plan: list[tuple[int, int]] = field(default_factory=list)
    preempted: bool = False
    preemptions: int = 0
    #: final cost summary (device_time_s / kv_block_seconds / swap_bytes)
    #: stamped by the usage ledger when the engine processes completion;
    #: None on a usage_accounting=False engine
    usage: dict | None = None

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def context_len(self) -> int:
        """Tokens whose K/V sit in the cache (prompt + fed output)."""
        return self.prefill_pos + max(len(self.output_tokens) - 1, 0)

    @property
    def ttft_s(self) -> float | None:
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.arrival_time

    @property
    def tpot_s(self) -> float | None:
        n = len(self.output_tokens)
        if self.finish_time is None or self.first_token_time is None or n < 2:
            return None
        return (self.finish_time - self.first_token_time) / (n - 1)


class SlotScheduler:
    """Owns the waiting queues (one per priority class), the slot table,
    the block allocator, and (optionally) the radix prefix cache."""

    def __init__(self, num_slots: int, allocator: BlockAllocator, block_size: int,
                 max_seq_len: int, radix=None, usage=None):
        self.num_slots = int(num_slots)
        self.allocator = allocator
        self.block_size = int(block_size)
        self.max_seq_len = int(max_seq_len)
        self.radix = radix
        #: the engine's :class:`~.usage.UsageLedger` (None = accounting
        #: off): block-ownership edges here are where per-request KV
        #: block-seconds accrue
        self.usage = usage
        self.waiting: dict[str, deque[Request]] = {p: deque() for p in PRIORITY_CLASSES}
        self.slots: list[Request | None] = [None] * self.num_slots
        #: cumulative prompt tokens of admitted (fresh) requests — the
        #: denominator of the prefix hit ratio
        self.prompt_tokens_admitted = 0
        self.prefix_hit_tokens = 0
        #: live requests carrying a deadline — the expiry sweep is guarded
        #: on this, so deadline-free serving pays one integer check per
        #: iteration (the telemetry/sanitizer null-path rule)
        self.deadline_live = 0

    # -- queries -------------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return sum(len(q) for q in self.waiting.values())

    def active(self, state: RequestState | None = None) -> list[Request]:
        reqs = [r for r in self.slots if r is not None]
        if state is not None:
            reqs = [r for r in reqs if r.state is state]
        return reqs

    @property
    def occupancy(self) -> float:
        return sum(r is not None for r in self.slots) / self.num_slots

    def has_work(self) -> bool:
        return self.queue_depth > 0 or any(r is not None for r in self.slots)

    def peek_head(self) -> Request | None:
        """The next request admission would consider (highest nonempty
        class, FCFS within it; preempted victims sit at the front)."""
        for p in PRIORITY_CLASSES:
            if self.waiting[p]:
                return self.waiting[p][0]
        return None

    # -- transitions ---------------------------------------------------------

    def submit(self, request: Request) -> Request:
        if request.priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority {request.priority!r}: "
                f"expected one of {PRIORITY_CLASSES}"
            )
        total = request.prompt_len + request.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"request needs {total} cache positions "
                f"(prompt {request.prompt_len} + max_new {request.max_new_tokens}) "
                f"but the engine's max_seq_len is {self.max_seq_len}"
            )
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if request.prompt_len < 1:
            raise ValueError("empty prompt")
        usable = self.allocator.num_blocks - 1
        admit_need = max(blocks_needed(request.prompt_len + 1, self.block_size), 1)
        if admit_need > usable:
            # an unaffordable-forever head request would head-of-line block
            # admit() on every iteration and spin run_until_idle() for good
            raise ValueError(
                f"prompt needs {admit_need} KV blocks to admit but the pool "
                f"only has {usable}: raise num_blocks or shrink the prompt"
            )
        if request.deadline is not None:
            self.deadline_live += 1
        request.state = RequestState.QUEUED
        self.waiting[request.priority].append(request)
        return request

    def requeue_preempted(self, request: Request) -> None:
        """A swapped-out victim goes back to the *front* of its class: it
        already waited its turn once, and its swap handles hold host DRAM
        that should drain as soon as capacity returns."""
        self.slots[request.slot] = None
        request.slot = None
        request.state = RequestState.QUEUED
        request.preempted = True
        request.preemptions += 1
        self.waiting[request.priority].appendleft(request)

    def evict_finished(self) -> list[Request]:
        """Release slots + blocks of finished requests (engine marks them).
        Blocks are decref'd: a block the radix cache (or another request)
        still holds stays resident; the rest return to the freelist."""
        evicted = []
        for i, req in enumerate(self.slots):
            if req is not None and req.state is RequestState.FINISHED:
                self.allocator.decref(req.blocks)
                req.blocks = []
                if self.usage is not None:
                    self.usage.update_blocks(req)
                req.slot = None
                self.slots[i] = None
                if req.deadline is not None:
                    self.deadline_live -= 1
                evicted.append(req)
        return evicted

    def expire_deadlines(
        self, now: float | None = None, skip_slots: set | None = None
    ) -> list[Request]:
        """Finish every queued or running request whose deadline has
        passed (``finish_reason="deadline_exceeded"``). Running requests
        keep their partial output; their blocks are freed by the
        ``evict_finished`` sweep the engine runs right after — same
        iteration, so the capacity a missed deadline was holding is
        admittable immediately (block tables only: the compiled decode
        executable never sees any of this). Queued requests leave the
        waiting deques directly (they hold no blocks; a *preempted* queued
        request's swap handles are the engine's to release — see
        ``InferenceEngine.step``). The caller only invokes this while
        ``deadline_live > 0``.

        ``skip_slots``: slots the sweep must leave alone this pass. The
        double-buffered engine passes the in-flight round's slots — those
        requests still have a token landing at this iteration's harvest
        (the token the synchronous engine emitted LAST iteration), so the
        engine defers their expiry to just after that harvest to keep the
        two loops token-identical."""
        now = time.perf_counter() if now is None else now
        expired: list[Request] = []
        for priority in PRIORITY_CLASSES:
            q = self.waiting[priority]
            if any(r.deadline is not None and now > r.deadline for r in q):
                keep: deque[Request] = deque()
                for r in q:
                    if r.deadline is not None and now > r.deadline:
                        r.finish_reason = "deadline_exceeded"
                        r.finish_time = now
                        r.state = RequestState.FINISHED
                        self.deadline_live -= 1
                        expired.append(r)
                    else:
                        keep.append(r)
                self.waiting[priority] = keep
        for req in self.slots:
            if (
                req is not None
                and req.state is not RequestState.FINISHED
                and req.deadline is not None
                and now > req.deadline
            ):
                if skip_slots is not None and req.slot in skip_slots:
                    continue
                req.finish_reason = "deadline_exceeded"
                req.finish_time = now
                req.state = RequestState.FINISHED
                # deadline_live drops at evict_finished, which releases the
                # slot+blocks this iteration
                expired.append(req)
        return expired

    def _ensure_free(self, need: int) -> bool:
        """Freelist coverage for ``need`` blocks, LRU-evicting refcount-1
        cached blocks to make room."""
        short = need - self.allocator.free_count
        if short > 0 and self.radix is not None:
            self.radix.evict(short)
        return self.allocator.can_allocate(need)

    def admit(self) -> list[Request]:
        """Priority-then-FCFS admission into free slots, bounded by the
        block freelist (after radix eviction). Fresh requests map their
        longest cached prefix at refcount+1 and allocate only the tail;
        preempted requests re-allocate exactly their swapped-out blocks
        (the engine restores the rows). Head-of-line blocking on blocks is
        intentional; a free slot with an unaffordable head request stays
        empty until eviction/preemption refills the freelist."""
        admitted = []
        free_slots = [i for i, r in enumerate(self.slots) if r is None]
        while free_slots:
            req = self.peek_head()
            if req is None:
                break
            if req.preempted:
                need = len(req.swap_plan)
                if not self._ensure_free(need):
                    break
                fresh = self.allocator.allocate(need)
                for (idx, _handle), nb in zip(req.swap_plan, fresh):
                    req.blocks[idx] = nb
                req.state = (
                    RequestState.PREFILL
                    if req.prefill_pos < req.prompt_len
                    else RequestState.DECODE
                )
            else:
                total_need = max(
                    blocks_needed(req.prompt_len + 1, self.block_size), 1
                )
                shared, matched, cow_src = [], 0, None
                if self.radix is not None:
                    shared, matched, cow_src = self.radix.acquire(req.prompt)
                need = total_need - len(shared)
                if not self._ensure_free(need):
                    if self.radix is not None:
                        self.radix.release_acquired(shared, cow_src)
                    break
                fresh = self.allocator.allocate(need)
                req.blocks = shared + fresh
                req.matched_tokens = matched
                req.prefill_pos = matched
                if cow_src is not None:
                    # the engine copies src -> the first private block
                    # before this request's first prefill chunk
                    req.cow = (cow_src, fresh[0])
                self.prompt_tokens_admitted += req.prompt_len
                self.prefix_hit_tokens += matched
                req.state = RequestState.PREFILL
            self.waiting[req.priority].popleft()
            req.slot = free_slots.pop(0)
            self.slots[req.slot] = req
            if self.usage is not None:
                # block-ownership edge: fresh admits start their integral
                # here; preempted re-admits resume at full holdings once
                # the engine clears swap_plan in _place_admitted
                self.usage.update_blocks(req)
            admitted.append(req)
        return admitted

    def pick_victim(self) -> Request | None:
        """Preemption order: lowest priority class first, latest arrival
        within it (the youngest request has the least sunk prefill/decode
        work and re-queues at the front of its class anyway)."""
        cands = [
            r for r in self.slots
            if r is not None and r.state is not RequestState.FINISHED
        ]
        if not cands:
            return None
        return max(cands, key=lambda r: (priority_rank(r.priority), r.arrival_time))

    def grow_for_decode(self, req: Request, tokens_ahead: int = 1) -> bool:
        """Ensure blocks exist for the next ``tokens_ahead`` cache writes
        (a decode burst writes positions ``context_len ..
        context_len+tokens_ahead-1``). The span is capped at the request's
        own ``prompt + max_new`` budget (and the per-slot maximum): burst
        lane-steps past the budget may scatter into the null block, which
        is harmless, and allocating for them would truncate requests under
        pool pressure whose real remaining tokens already fit. When the
        freelist is dry, refcount-1 cached blocks are LRU-evicted first.
        False = the pool is exhausted even after eviction; the engine
        preempts a victim to the swap pool (or, with swap off/full,
        force-finishes with ``finish_reason="out_of_blocks"``)."""
        need = blocks_needed(
            min(
                req.context_len + tokens_ahead,
                req.prompt_len + req.max_new_tokens,
                self.max_seq_len,
            ),
            self.block_size,
        )
        if len(req.blocks) >= need:
            return True
        while len(req.blocks) < need:
            if not self._ensure_free(1):
                if self.usage is not None:
                    self.usage.update_blocks(req)  # partial growth still held
                return False
            req.blocks.extend(self.allocator.allocate(1))
        if self.usage is not None:
            self.usage.update_blocks(req)
        return True
