"""KV block pool accounting for the serving engine.

The device-side cache is one pool of fixed-size blocks per layer
(``[num_blocks, block_size, n_kv, hd]``); this module owns the *host-side*
bookkeeping: which pool blocks are free, which belong to which request.
Pure Python, no JAX — the engine translates the per-request block lists
into the dense ``[num_slots, max_blocks]`` block-table array the compiled
step reads.

Block 0 is the reserved **null block**: free slots and the unfilled tail of
every block table point at it. It absorbs the padded decode lanes' writes
and is never inside any live slot's valid prefix, so it never needs to be
allocated, freed, or zeroed.
"""

from __future__ import annotations

from collections import deque

#: pool index of the reserved null block (see module docstring)
NULL_BLOCK = 0


class BlockAllocator:
    """Freelist over pool blocks ``1 .. num_blocks-1`` (0 is the null
    block). Strict accounting: allocating more than is free raises, freeing
    a block that is not currently allocated (double-free, the null block, an
    out-of-range id) raises — the engine's invariant tests lean on this.

    Blocks are **refcounted** for prefix sharing (:mod:`.radix`): ``allocate``
    hands a block out at refcount 1, ``incref`` adds a holder (a request
    mapping a cached prefix block, or the radix cache itself), ``decref``
    drops one and returns the block to the freelist only when the last
    holder lets go. ``free`` keeps its PR 4 strictness and additionally
    refuses a *shared* block (refcount > 1) — releasing a block other
    requests still read must go through ``decref``, never a hard free."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need at least 2 blocks (1 usable + the null block), got {num_blocks}"
            )
        self.num_blocks = int(num_blocks)
        self._free: deque[int] = deque(range(1, self.num_blocks))
        self._allocated: set[int] = set()
        self._refcounts: dict[int, int] = {}

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def allocated_count(self) -> int:
        return len(self._allocated)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> list[int]:
        """Pop ``n`` blocks from the freelist; all-or-nothing."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if not self.can_allocate(n):
            raise RuntimeError(
                f"out of KV blocks: requested {n}, free {len(self._free)} "
                f"(pool {self.num_blocks - 1} usable)"
            )
        blocks = [self._free.popleft() for _ in range(n)]
        self._allocated.update(blocks)
        for b in blocks:
            self._refcounts[b] = 1
        return blocks

    def refcount(self, block: int) -> int:
        """Current holder count (0 for free / never-allocated blocks)."""
        return self._refcounts.get(block, 0)

    def _check_allocated(self, b: int, verb: str) -> None:
        if b == NULL_BLOCK:
            raise ValueError(f"cannot {verb} the null block")
        if b not in self._allocated:
            raise ValueError(f"double free (or never allocated): block {b}")

    def incref(self, blocks: list[int]) -> None:
        """Add one holder to each (already-allocated) block — a request
        mapping a cached prefix, or the radix cache adopting a block."""
        for b in blocks:
            self._check_allocated(b, "share")
            self._refcounts[b] += 1

    def decref(self, blocks: list[int]) -> list[int]:
        """Drop one holder from each block; blocks whose last holder left
        return to the freelist. Returns the blocks actually freed. Dropping
        a holder from a free block raises (the double-free invariant holds
        for shared blocks too)."""
        freed = []
        for b in blocks:
            self._check_allocated(b, "release")
            self._refcounts[b] -= 1
            if self._refcounts[b] == 0:
                del self._refcounts[b]
                self._allocated.remove(b)
                self._free.append(b)
                freed.append(b)
        return freed

    def free(self, blocks: list[int]) -> None:
        """Return blocks to the freelist; rejects double-frees, the null
        block, and **shared** blocks (refcount > 1 — another holder still
        reads them; use :meth:`decref`) so leaks/corruption surface as
        exceptions, not wrong tokens."""
        for b in blocks:
            self._check_allocated(b, "free")
            if self._refcounts[b] > 1:
                raise ValueError(
                    f"cannot free shared block {b} "
                    f"(refcount {self._refcounts[b]}): use decref"
                )
            del self._refcounts[b]
            self._allocated.remove(b)
            self._free.append(b)


def blocks_needed(num_tokens: int, block_size: int) -> int:
    """Blocks covering ``num_tokens`` cache positions (ceil division)."""
    return max(0, -(-int(num_tokens) // int(block_size)))
