"""Constrained decoding: regexes and a JSON-schema subset compiled to
token-level DFAs.

The repo's serving stack works on a byte vocabulary (token id ``i`` is the
byte ``i``), so a grammar over characters IS a grammar over tokens: a
regex is parsed to a Thompson NFA over the byte alphabet, determinised,
and lowered to two dense tables —

* ``allow [num_states, vocab] bool`` — which tokens keep the prefix inside
  the language (i.e. lead to a *live* state, one from which an accepting
  state is still reachable), and
* ``trans [num_states, vocab] int32`` — the successor state per token.

Inside the engine those tables are rows of a device-resident
``[grammar_slots + 1, max_states, vocab]`` pair; the per-slot DFA state is
an int32 lane input, and mask application is one gathered
``jnp.where(mask, logits, NEG)`` inside the ONE compiled decode/verify
executable.  Row 0 is the unconstrained sentinel (mask all-True,
transitions all-0), so unconstrained slots pay a no-op gather.

Compilation is cached process-wide by grammar hash
(:func:`compile_grammar`), and a JSON-schema subset lowers onto the same
regex pipeline by generating the canonical (no-whitespace, all properties
required, declaration order) textual form of the schema
(:func:`schema_to_regex`).  :func:`validate_instance` is a matching
minimal validator used by tests and the smoke harness — the ``jsonschema``
package is deliberately not a dependency.

Host-side, the authoritative DFA state lives on the request and advances
in ``_emit_token``; the in-trace advance through ``trans`` only feeds
mid-burst / mid-draft masking, so a discarded burst tail can never corrupt
the request's real state.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from collections import OrderedDict

import numpy as np

__all__ = [
    "Grammar",
    "GrammarError",
    "compile_grammar",
    "compile_regex",
    "schema_to_regex",
    "validate_instance",
    "grammar_hash",
]


class GrammarError(ValueError):
    """Malformed grammar spec, unsupported construct, or a DFA that does
    not fit the engine's ``grammar_states`` budget."""


# --------------------------------------------------------------------------
# regex -> NFA (Thompson construction)
# --------------------------------------------------------------------------

_ESCAPE_CLASSES = {
    "d": frozenset(range(48, 58)),
    "w": frozenset(
        list(range(48, 58)) + list(range(65, 91)) + list(range(97, 123)) + [95]
    ),
    "s": frozenset(ord(c) for c in " \t\n\r\f\v"),
}


class _Nfa:
    def __init__(self):
        self.eps = []  # state -> set of eps targets
        self.edges = []  # state -> list of (charset, target)

    def new(self):
        self.eps.append(set())
        self.edges.append([])
        return len(self.eps) - 1


def _parse_regex(pattern, alphabet):
    """Recursive-descent parse of the supported subset: literals, ``.``,
    ``[...]`` classes (ranges, negation), ``\\d \\w \\s`` escapes, grouping
    ``()``, alternation ``|``, quantifiers ``* + ?``."""
    nfa = _Nfa()
    i = 0
    n = len(pattern)

    def peek():
        return pattern[i] if i < n else None

    def _escape_set():
        nonlocal i
        i += 1  # consume backslash
        if i >= n:
            raise GrammarError(f"dangling escape at end of regex {pattern!r}")
        c = pattern[i]
        i += 1
        if c in _ESCAPE_CLASSES:
            return _ESCAPE_CLASSES[c] & alphabet
        if c == "n":
            return frozenset({10}) & alphabet
        if c == "t":
            return frozenset({9}) & alphabet
        if c == "r":
            return frozenset({13}) & alphabet
        return frozenset({ord(c)}) & alphabet

    def _class_set():
        nonlocal i
        i += 1  # consume '['
        negate = peek() == "^"
        if negate:
            i += 1
        chars = set()
        while True:
            c = peek()
            if c is None:
                raise GrammarError(f"unterminated character class in {pattern!r}")
            if c == "]":
                i += 1
                break
            if c == "\\":
                chars |= _escape_set()
                continue
            i += 1
            if peek() == "-" and i + 1 < n and pattern[i + 1] != "]":
                hi = pattern[i + 1]
                i += 2
                if ord(hi) < ord(c):
                    raise GrammarError(f"bad range {c}-{hi} in {pattern!r}")
                chars |= set(range(ord(c), ord(hi) + 1))
            else:
                chars.add(ord(c))
        cs = frozenset(chars) & alphabet
        return (alphabet - cs) if negate else cs

    def _atom():
        nonlocal i
        c = peek()
        if c == "(":
            i += 1
            frag = _alt()
            if peek() != ")":
                raise GrammarError(f"unbalanced parenthesis in {pattern!r}")
            i += 1
            return frag
        if c == "[":
            cs = _class_set()
        elif c == ".":
            i += 1
            cs = alphabet - {10}
        elif c == "\\":
            cs = _escape_set()
        elif c in ")|*+?":
            raise GrammarError(f"unexpected {c!r} at position {i} in {pattern!r}")
        else:
            i += 1
            cs = frozenset({ord(c)}) & alphabet
        s, e = nfa.new(), nfa.new()
        nfa.edges[s].append((cs, e))
        return s, e

    def _rep():
        nonlocal i
        s, e = _atom()
        while peek() in ("*", "+", "?"):
            q = peek()
            i += 1
            ns, ne = nfa.new(), nfa.new()
            nfa.eps[ns].add(s)
            nfa.eps[e].add(ne)
            if q in ("*", "+"):
                nfa.eps[e].add(s)
            if q in ("*", "?"):
                nfa.eps[ns].add(ne)
            s, e = ns, ne
        return s, e

    def _concat():
        frags = []
        while peek() is not None and peek() not in ")|":
            frags.append(_rep())
        if not frags:
            s, e = nfa.new(), nfa.new()
            nfa.eps[s].add(e)
            return s, e
        for (_, a_end), (b_start, _) in zip(frags, frags[1:]):
            nfa.eps[a_end].add(b_start)
        return frags[0][0], frags[-1][1]

    def _alt():
        nonlocal i
        frags = [_concat()]
        while peek() == "|":
            i += 1
            frags.append(_concat())
        if len(frags) == 1:
            return frags[0]
        s, e = nfa.new(), nfa.new()
        for fs, fe in frags:
            nfa.eps[s].add(fs)
            nfa.eps[fe].add(e)
        return s, e

    start, end = _alt()
    if i != n:
        raise GrammarError(f"trailing {pattern[i:]!r} in regex {pattern!r}")
    return nfa, start, end


# --------------------------------------------------------------------------
# NFA -> DFA -> dense tables
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Grammar:
    """A compiled grammar: dense per-state tables sized for the engine.

    ``final[s]`` marks accepting states with no live continuation — the
    host finishes such a request immediately (``finish_reason="stop"``).
    When the engine has an eos token, accepting states additionally allow
    it (self-loop), so a model can terminate a still-extensible match.
    """

    hash: str
    vocab_size: int
    num_states: int
    start: int
    trans: np.ndarray  # [num_states, vocab] int32
    allow: np.ndarray  # [num_states, vocab] bool
    accepting: np.ndarray  # [num_states] bool
    final: np.ndarray  # [num_states] bool

    def advance(self, state, tok):
        """Host-side authoritative state transition."""
        return int(self.trans[state, tok])

    def allows(self, state, tok):
        return bool(self.allow[state, tok])

    def padded_tables(self, max_states):
        """(allow, trans) padded to ``[max_states, vocab]`` — unused rows
        are inert (all-allow, transition to 0) so a stale lane value can
        never produce an all-masked distribution."""
        if self.num_states > max_states:
            raise GrammarError(
                f"grammar needs {self.num_states} DFA states but the engine "
                f"budget is grammar_states={max_states}"
            )
        allow = np.ones((max_states, self.vocab_size), bool)
        trans = np.zeros((max_states, self.vocab_size), np.int32)
        allow[: self.num_states] = self.allow
        trans[: self.num_states] = self.trans
        return allow, trans


def compile_regex(pattern, vocab_size, eos_id=None, max_states=None, hash_=None):
    alphabet = frozenset(range(min(int(vocab_size), 0x110000)))
    nfa, nstart, nend = _parse_regex(pattern, alphabet)

    def closure(states):
        seen = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    start_set = closure({nstart})
    ids = {start_set: 0}
    worklist = [start_set]
    dfa_trans = []  # list of dict sym -> target id
    while worklist:
        cur = worklist.pop()
        move = {}
        for s in cur:
            for cs, t in nfa.edges[s]:
                for sym in cs:
                    move.setdefault(sym, set()).add(t)
        row = {}
        for sym, targets in move.items():
            tgt = closure(targets)
            if tgt not in ids:
                ids[tgt] = len(ids)
                dfa_trans.append(None)
                worklist.append(tgt)
            row[sym] = ids[tgt]
        idx = ids[cur]
        while len(dfa_trans) <= idx:
            dfa_trans.append(None)
        dfa_trans[idx] = row
    num_states = len(ids)
    if max_states is not None and num_states > max_states:
        raise GrammarError(
            f"regex {pattern!r} compiles to {num_states} DFA states, over the "
            f"grammar_states={max_states} budget"
        )

    accepting = np.zeros(num_states, bool)
    for sset, idx in ids.items():
        accepting[idx] = nend in sset

    # live = can still reach an accepting state
    live = accepting.copy()
    changed = True
    while changed:
        changed = False
        for s in range(num_states):
            if live[s]:
                continue
            if any(live[t] for t in (dfa_trans[s] or {}).values()):
                live[s] = True
                changed = True
    if not live[0]:
        raise GrammarError(f"regex {pattern!r} matches nothing over this vocabulary")

    vocab = int(vocab_size)
    trans = np.zeros((num_states, vocab), np.int32)
    allow = np.zeros((num_states, vocab), bool)
    for s in range(num_states):
        for sym, t in (dfa_trans[s] or {}).items():
            if sym < vocab and live[t]:
                allow[s, sym] = True
                trans[s, sym] = t
    final = accepting & ~allow.any(axis=1)
    if eos_id is not None and 0 <= int(eos_id) < vocab:
        e = int(eos_id)
        sel = accepting & ~allow[:, e]
        allow[sel, e] = True
        trans[sel, e] = np.arange(num_states)[sel]  # self-loop; host stops on eos

    return Grammar(
        hash=hash_ or hashlib.sha256(pattern.encode()).hexdigest()[:16],
        vocab_size=vocab,
        num_states=num_states,
        start=0,
        trans=trans,
        allow=allow,
        accepting=accepting,
        final=final,
    )


# --------------------------------------------------------------------------
# JSON-schema subset -> regex
# --------------------------------------------------------------------------

_REGEX_SPECIALS = set("\\.[](){}|*+?^$-")


def _lit(text):
    return "".join("\\" + c if c in _REGEX_SPECIALS else c for c in text)


# printable ASCII minus '"' and '\' — no escape sequences, no control
# bytes (they would make the emitted JSON unparseable); documented subset
_STRING_RE = '"[ !#-Z\\[\\]^-~]*"'
_INT_RE = "-?(0|[1-9][0-9]*)"
_NUMBER_RE = "-?(0|[1-9][0-9]*)(\\.[0-9]+)?"


def schema_to_regex(schema):
    """Lower the supported JSON-schema subset to a regex over the
    *canonical* textual form: no whitespace, every declared property
    present in declaration order, strings without escape sequences.

    Supported: ``enum`` (of scalars), ``type`` in string / integer /
    number / boolean / null, ``object`` with ``properties`` (all treated
    as required), ``array`` with ``items``.  Anything else raises
    :class:`GrammarError`.
    """
    if not isinstance(schema, dict):
        raise GrammarError(f"schema must be an object, got {type(schema).__name__}")
    if "enum" in schema:
        opts = schema["enum"]
        if not opts:
            raise GrammarError("enum must be non-empty")
        return "(" + "|".join(_lit(json.dumps(v, separators=(",", ":"))) for v in opts) + ")"
    t = schema.get("type")
    if t == "string":
        return _STRING_RE
    if t == "integer":
        return _INT_RE
    if t == "number":
        return _NUMBER_RE
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "object":
        props = schema.get("properties", {})
        if not props:
            return "\\{\\}"
        parts = [
            '"' + _lit(name) + '":' + schema_to_regex(sub)
            for name, sub in props.items()
        ]
        return "\\{" + ",".join(parts) + "\\}"
    if t == "array":
        items = schema.get("items")
        if items is None:
            raise GrammarError("array schemas need 'items'")
        it = schema_to_regex(items)
        return "\\[(" + it + "(," + it + ")*)?\\]"
    raise GrammarError(f"unsupported schema: {schema!r}")


def validate_instance(schema, value):
    """Minimal validator matching exactly the subset
    :func:`schema_to_regex` supports (the ``jsonschema`` package is not a
    dependency).  Raises :class:`GrammarError` on mismatch."""
    if "enum" in schema:
        if value not in schema["enum"]:
            raise GrammarError(f"{value!r} not in enum {schema['enum']!r}")
        return
    t = schema.get("type")
    if t == "string":
        if not isinstance(value, str):
            raise GrammarError(f"expected string, got {value!r}")
    elif t == "integer":
        if not isinstance(value, int) or isinstance(value, bool):
            raise GrammarError(f"expected integer, got {value!r}")
    elif t == "number":
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            raise GrammarError(f"expected number, got {value!r}")
    elif t == "boolean":
        if not isinstance(value, bool):
            raise GrammarError(f"expected boolean, got {value!r}")
    elif t == "null":
        if value is not None:
            raise GrammarError(f"expected null, got {value!r}")
    elif t == "object":
        if not isinstance(value, dict):
            raise GrammarError(f"expected object, got {value!r}")
        props = schema.get("properties", {})
        for name, sub in props.items():
            if name not in value:
                raise GrammarError(f"missing property {name!r}")
            validate_instance(sub, value[name])
    elif t == "array":
        if not isinstance(value, list):
            raise GrammarError(f"expected array, got {value!r}")
        for item in value:
            validate_instance(schema.get("items", {}), item)
    else:
        raise GrammarError(f"unsupported schema: {schema!r}")


# --------------------------------------------------------------------------
# cached front door
# --------------------------------------------------------------------------

_CACHE = OrderedDict()
_CACHE_MAX = 128


def grammar_hash(spec):
    """Stable hash of a grammar spec dict (the cache key and the engine's
    row-assignment key)."""
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()[:16]


def compile_grammar(spec, vocab_size, eos_id=None, max_states=None):
    """Compile a grammar spec — ``{"type": "regex", "pattern": ...}`` or
    ``{"type": "json_schema", "schema": {...}}`` — to a :class:`Grammar`,
    memoised by (spec hash, vocab, eos, budget)."""
    if not isinstance(spec, dict) or "type" not in spec:
        raise GrammarError(
            'grammar spec must be {"type": "regex"|"json_schema", ...}, got '
            f"{spec!r}"
        )
    key = (grammar_hash(spec), int(vocab_size), eos_id, max_states)
    hit = _CACHE.get(key)
    if hit is not None:
        _CACHE.move_to_end(key)
        return hit

    kind = spec["type"]
    if kind == "regex":
        pattern = spec.get("pattern")
        if not isinstance(pattern, str) or not pattern:
            raise GrammarError("regex grammar needs a non-empty 'pattern'")
    elif kind == "json_schema":
        schema = spec.get("schema")
        if not isinstance(schema, dict):
            raise GrammarError("json_schema grammar needs a 'schema' object")
        pattern = schema_to_regex(schema)
    else:
        raise GrammarError(f"unknown grammar type {kind!r}")

    g = compile_regex(
        pattern, vocab_size, eos_id=eos_id, max_states=max_states, hash_=key[0]
    )
    _CACHE[key] = g
    while len(_CACHE) > _CACHE_MAX:
        _CACHE.popitem(last=False)
    return g
