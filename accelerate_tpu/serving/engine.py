"""Continuous-batching inference engine: one compiled decode step, forever.

The static-batch ``generate()`` path compiles a prefill + decode program per
call and every request in the batch waits for the slowest one. This engine
inverts the design for serving (Orca-style iteration scheduling over a
vLLM-style block-paged cache):

* the decode step is **one** pjit-compiled program of static shape
  ``[num_slots, 1]`` against a block-paged KV pool — admitting, evicting,
  or resizing requests never recompiles (asserted by ``stats()``'s
  ``decode_compiles`` counter, which increments only when JAX re-traces);
* prompts are **chunk-prefilled**: ``prefill_chunk`` tokens of one prompt
  per engine iteration, interleaved with the decode step, so a long prompt
  bounds every in-flight request's inter-token latency by one chunk's
  forward instead of a whole prefill;
* KV memory is allocated in ``block_size``-token blocks from a freelist
  (:mod:`.blocks`) — padding waste is bounded by block granularity, and a
  finished short completion's blocks are serving a new request on the next
  iteration;
* **speculative decoding** (``spec_k > 0``): each dispatch becomes one
  compiled spec round — every active slot drafts ``k`` tokens from the
  early-exit draft (the target's own first layers, sharing the target
  pool's leading layers), ONE ``[num_slots, k+1]`` verify forward scores
  all drafts through the fused paged kernel, and the longest agreeing
  prefix + correction emit. Rollback is position bookkeeping only, so the
  one-executable contract and greedy token parity both survive;
* **double-buffered dispatch** (``async_dispatch``, the default): the
  decode round handed off at iteration *i* is harvested at iteration
  *i+1*, so admission, block growth, radix lookups, deadline sweeps, and
  lane edits run WHILE the device computes — the host leaves the
  per-token critical path (ROADMAP item 5) and ``device_wait`` shrinks to
  the residual sync the host could not hide. Dispatch *i+1* still happens
  strictly after harvest *i*, so output is token-identical to the
  synchronous loop (``async_dispatch=False`` / ``serve --sync-engine``);
* **per-slot sampling + constrained decoding** (``per_slot_sampling``,
  the default): temperature / top-k / top-p / repetition penalty / seed /
  grammar-DFA state ride as fixed-shape *lane inputs* of the same ONE
  decode executable (:mod:`.sampling`, :mod:`.grammar`) — per-request
  variation never recompiles, greedy slots take a ``lax.cond`` fast path
  that is bit-identical argmax, and the spec verify round accepts sampled
  slots by rejection sampling.

Sampling/eos semantics share one traced picker with ``generation.py``
(:func:`accelerate_tpu.generation.pick_next_token`), so greedy engine
output is token-for-token identical to ``generate(use_cache=True)`` — and
the spec round's greedy acceptance reuses
:func:`accelerate_tpu.generation.spec_accept_tokens`, so greedy slots of
the spec-armed engine stay token-identical to the non-spec engine.
"""

from __future__ import annotations

import time
import warnings
from collections import OrderedDict, deque
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.sanitizer import get_active_sanitizer as _get_sanitizer
from ..diagnostics.tracing import ensure_trace_id, get_tracer, trace_span, valid_trace_id
from ..generation import _pick_traced
from ..metrics.ingest import observe_flight
from ..metrics.registry import get_active_registry
from ..telemetry import get_active_recorder
from .blocks import NULL_BLOCK, BlockAllocator, blocks_needed
from .flight import ITERATION_PHASES, FlightRecorder, set_active_flight_recorder
from .grammar import compile_grammar
from .radix import RadixCache, SwapPool
from .sampling import (
    TAG_ACCEPT,
    TAG_DRAFT,
    SamplingParams,
    apply_filters,
    blank_lanes,
    categorical_per_slot,
    dist_logprobs,
    match_stop,
    pick_tokens,
    rejection_accept,
    resolve_sampling,
    set_slot_lane,
    slot_keys,
    uniform_per_slot,
)
from .scheduler import Request, RequestState, SlotScheduler, priority_rank
from .usage import UsageLedger, normalize_tenant


@dataclass
class EngineConfig:
    """Engine geometry. ``num_blocks`` defaults to full residency
    (``num_slots`` × the per-slot maximum + the null block) — set it lower
    to exercise freelist contention."""

    num_slots: int = 8
    block_size: int = 16
    #: per-request cap on prompt + generated tokens; also sizes the block
    #: table width (``ceil(max_seq_len / block_size)`` entries per slot)
    max_seq_len: int = 512
    num_blocks: int | None = None
    prefill_chunk: int = 32
    eos_token_id: int | None = None
    do_sample: bool = False
    temperature: float = 1.0
    seed: int = 0
    #: default budget for add_request(max_new_tokens=None)
    max_new_tokens: int = 64
    #: decode steps per dispatch of the (single) compiled decode program —
    #: a ``lax.scan`` of this many ``[num_slots, 1]`` steps. Amortises the
    #: per-dispatch host round trip (the same move generation.py's
    #: ``_EOS_CHUNK`` makes) at the cost of scheduling granularity:
    #: admission/prefill interleave every ``decode_burst`` tokens, and a
    #: request finishing mid-burst wastes at most ``decode_burst - 1``
    #: lane-steps. 1 = schedule every token.
    decode_burst: int = 8
    #: double-buffered dispatch (ROADMAP item 5, the async engine core):
    #: ``step()`` hands round *i* to the device and returns WITHOUT
    #: waiting; round *i*'s tokens are harvested at iteration *i+1*'s
    #: harvest point, AFTER the host has already run iteration *i+1*'s
    #: scheduling work (admission, block growth/CoW, radix lookups,
    #: deadline sweeps, sampling-lane edits) under the in-flight round.
    #: Output stays token-identical to the synchronous loop — dispatch
    #: *i+1* still happens strictly after harvest *i*, so every decode
    #: input (fed token, position, lanes, DFA rows) is byte-identical;
    #: only the host's position relative to the device moves. ``False``
    #: restores the fully synchronous loop (``serve --sync-engine`` /
    #: ``ACCELERATE_SYNC_ENGINE=1``) — the escape hatch and the baseline
    #: ``benchmarks/async_smoke.py`` compares against.
    async_dispatch: bool = True
    #: emit a telemetry "serving" row every N iterations (0 disables)
    stats_interval: int = 32
    #: per-iteration flight recorder ring size (0 disables): every
    #: iteration's wall time decomposed into exclusive phases (schedule /
    #: prefill / dispatch / device_wait / harvest) whose durations are
    #: asserted to sum to the measured wall time — the host-vs-device
    #: attribution ``stats()['host_fraction']``, ``trace tail
    #: --iterations``, ``/profile`` windows, and HANG_REPORT forensics
    #: all read. Stamps are five perf_counter reads per iteration; the
    #: disabled path is one ``is None`` check.
    flight_history: int = 256
    #: finished :class:`Request` objects retained for ``stats()``
    #: percentiles — a *ring*, not a list: a long-lived serve process must
    #: not leak every completed request (nor rescan an unbounded history
    #: O(n) per stats() call). Cumulative counts stay exact through
    #: ``completed_total``; the percentile window is the newest this-many
    #: completions.
    completed_history: int = 4096
    #: per-device HBM budget in GiB; when set, the engine runs the
    #: shard-check pre-flight BEFORE allocating anything and refuses to
    #: start (ValueError naming SP004) if params + the paged pools exceed
    #: it — the capacity-planning contract: fail at bring-up, not OOM
    #: mid-request
    hbm_budget_gb: float | None = None
    #: radix prefix sharing (:mod:`.radix`): admission maps a request's
    #: longest cached prompt prefix into its block table at refcount+1 and
    #: chunk-prefills only the tail; finished prompts' full blocks stay
    #: cached (LRU-evicted under pool pressure). Sharing edits only block
    #: tables and refcounts — the one-compiled-executable contract holds.
    prefix_cache: bool = True
    #: host-DRAM swap tier in GiB (0 disables): under pool exhaustion the
    #: lowest-priority victim's unshared blocks are device_get-swapped to
    #: a :class:`~.radix.SwapPool` and the request re-queues at the front
    #: of its class; ``finish_reason="out_of_blocks"`` truncation becomes
    #: the last resort for when even swap capacity is gone.
    swap_gb: float = 0.0
    #: KV pool storage policy — decode is memory-bandwidth-bound, so the
    #: pool's dtype is the direct lever on both bytes-per-decode-step and
    #: how many blocks (slots) an HBM budget holds. ``"auto"`` stores in
    #: the params' compute dtype (the PR 4 behaviour); ``"bf16"``/``"f32"``
    #: force a float width; ``"int8"``/``"fp8"`` quantize on scatter with
    #: per-row amax scales riding beside the pool (``ops/fp8.py``) and
    #: dequantize in-register inside the fused paged-attention kernel.
    #: Scale arrays follow every pool edit — copy-on-write, swap-out/in,
    #: radix adoption — and the one-compiled-decode-executable contract
    #: holds at every setting (scales are just two more donated pool
    #: operands of the same single executable).
    kv_dtype: str = "auto"
    #: speculative decoding (0 = off, the plain burst decode). ``spec_k > 0``
    #: replaces the decode step with ONE compiled spec round per dispatch:
    #: every active slot drafts ``spec_k`` tokens from the cheap draft, a
    #: single ``[num_slots, spec_k+1]`` verify forward scores all drafts
    #: through the fused paged-attention kernel, and the longest agreeing
    #: prefix + the target's correction are emitted (greedy acceptance is
    #: exact — output stays token-identical to the non-spec engine).
    #: Rejected drafts are rolled back purely by position bookkeeping: the
    #: next round re-writes those pool rows and attention never reads past
    #: each slot's valid prefix, so no pool edit beyond the normal scatter
    #: happens at any kv_dtype. ``decode_burst`` is ignored while armed —
    #: one spec round already amortises the host round trip over up to
    #: ``spec_k + 1`` tokens. Sampled slots verify by rejection sampling
    #: (accept draft token with prob min(1, p_target/p_draft), resample
    #: the clamped residual otherwise) while greedy slots keep the exact
    #: longest-agreeing-prefix path — so speculation composes with
    #: ``do_sample`` when ``per_slot_sampling=True``.
    spec_k: int = 0
    #: draft policy when ``spec_k > 0`` (see :mod:`.spec`):
    #: ``"early_exit:N"`` runs the target's own first N layers (+ its final
    #: norm/head) as the draft, reading/writing the FIRST N LAYERS of the
    #: target's paged pool — identical weights make the draft's K/V a
    #: strict subset of the target's, so prefix sharing, copy-on-write and
    #: swap preemption maintain the draft state with zero extra machinery.
    draft: str = "early_exit:2"
    #: per-request sampling + constrained decoding (:mod:`.sampling`,
    #: :mod:`.grammar`): temperature / top-k / top-p / repetition penalty /
    #: seed / stop / min_tokens and a grammar DFA ride as fixed-shape
    #: *traced lane inputs* of the ONE compiled decode executable, so
    #: per-request variation never recompiles. ``False`` rebuilds the
    #: pre-lane executables byte-for-byte (the ``bench.py sampling``
    #: overhead baseline) and refuses per-request params at add_request.
    per_slot_sampling: bool = True
    #: top-N per-step logprobs harvested through the existing device_get
    #: (0 disables — the harvest shape is static, so this is engine
    #: geometry; requests opt in *up to* this cap). Unsupported with
    #: ``spec_k > 0``.
    logprobs_topn: int = 0
    #: concurrent distinct grammars resident in the device mask/transition
    #: tables (+1 internal row for the unconstrained sentinel). Rows are
    #: refcounted per live request and LRU-cached when idle; admission
    #: with every row held by a live request raises.
    grammar_slots: int = 4
    #: DFA state budget per grammar — sizes the device tables; a grammar
    #: compiling to more states refuses at add_request
    grammar_states: int = 64
    #: repetition-penalty window: the last this-many generated tokens ride
    #: the ``[num_slots, rep_window]`` ring lane
    rep_window: int = 32
    #: per-request resource attribution (:mod:`.usage`): every request
    #: accrues measured decode/prefill device-seconds, KV block-seconds,
    #: swap bytes, spec and grammar counts, rolled up by tenant and
    #: priority class with conservation asserted against the engine's own
    #: ``device_wait`` and pool-occupancy totals. ``False`` removes the
    #: ledger entirely — the disabled path is one truthiness check per
    #: iteration (the telemetry/flight discipline).
    usage_accounting: bool = True

    @property
    def blocks_per_slot(self) -> int:
        return blocks_needed(self.max_seq_len, self.block_size)


@dataclass
class _InFlightRound:
    """One dispatched-but-unharvested decode round (double-buffered
    dispatch). Holds the device *futures* the dispatch returned — nothing
    here has been device_get: the harvest's single blocking transfer is
    deferred until the next iteration's harvest point (or a fence). The
    ``live`` list is the dispatch-order request batch; slots cannot be
    reassigned while a round is in flight (eviction only touches FINISHED
    requests, and members only finish at harvest), so ``req.slot`` still
    indexes the result arrays when the harvest lands."""

    kind: str  # "burst" | "spec"
    live: list
    toks: object  # [burst, slots] next-token future, or [slots, k+1] spec
    accept: object = None  # [slots] accepted-prefix lengths (spec only)
    logps: object = None
    tvals: object = None
    tids: object = None
    harvest_lp: bool = False


class InferenceEngine:
    """Slot-scheduled continuous-batching engine over a paged-KV model.

    ``add_request()`` enqueues; ``step()`` runs one scheduler iteration
    (evict → admit → one prefill chunk → one decode step) and returns the
    requests that finished; ``run_until_idle()`` drains; ``stream()`` is a
    per-request generator. The model must declare ``supports_paged_kv``
    (the block-table decode path in its apply fn).

    ``mesh=`` shards the ONE decode executable over the named mesh with
    GSPMD ``NamedSharding`` rules (the same planner training uses): params
    by the model's partition rules + FSDP policy, the paged block pool by
    kv-head over ``tp``, scheduler state replicated. Host-side scheduling
    is untouched — sharding is a placement decision, never a different
    program, so greedy output stays token-identical to the single-device
    engine and the one-executable contract keeps holding."""

    def __init__(self, model, config: EngineConfig | None = None, mesh=None):
        self.config = cfg = config or EngineConfig()
        inner = getattr(model, "_model", None) or model
        if not getattr(inner, "supports_paged_kv", False):
            raise ValueError(
                f"model {getattr(inner, 'name', type(inner).__name__)!r} does not "
                "declare supports_paged_kv: the engine needs the block-table "
                "KV decode path (models/llama.py _llama_paged_step)"
            )
        self._apply_fn = inner.apply_fn
        self._params = model.params
        mcfg = inner.config
        if cfg.max_seq_len > mcfg.max_position_embeddings:
            raise ValueError(
                f"max_seq_len {cfg.max_seq_len} exceeds the model's "
                f"max_position_embeddings {mcfg.max_position_embeddings}"
            )
        if min(cfg.prefill_chunk, cfg.block_size, cfg.num_slots, cfg.decode_burst) < 1:
            raise ValueError(
                "prefill_chunk, block_size, num_slots, decode_burst must be >= 1"
            )

        # speculative decoding (spec_k > 0): parse the draft policy and
        # bind the early-exit draft apply BEFORE anything allocates — a bad
        # spec must refuse at bring-up, like every other geometry error
        self._spec = None
        self._draft_apply = None
        if cfg.spec_k:
            if cfg.spec_k < 1:
                raise ValueError("spec_k must be >= 1 (0 disables speculation)")
            if cfg.do_sample and not cfg.per_slot_sampling:
                raise ValueError(
                    "spec_k with do_sample=True needs per_slot_sampling=True "
                    "(the rejection-sampling verify path); the legacy "
                    "per_slot_sampling=False executables are greedy-only"
                )
            if cfg.logprobs_topn:
                raise ValueError(
                    "logprobs_topn with spec_k > 0 is not supported: the "
                    "verify round emits a variable accepted prefix, so there "
                    "is no per-step harvest to ride — set spec_k=0 for "
                    "logprobs"
                )
            from .spec import parse_draft_spec

            self._spec = parse_draft_spec(cfg.draft, mcfg.num_hidden_layers)
            factory = getattr(inner, "early_exit_apply", None)
            if factory is None:
                raise ValueError(
                    f"model {getattr(inner, 'name', type(inner).__name__)!r} "
                    "declares no early_exit_apply factory: the spec_k engine "
                    "needs the early-exit draft path (models/llama.py "
                    "llama_early_exit_apply)"
                )
            self._draft_apply = factory(self._spec.layers)
        #: cache positions one decode dispatch may write past context_len —
        #: the block-growth lookahead (a spec round writes k+1 positions;
        #: a plain dispatch writes decode_burst)
        self._decode_lookahead = (cfg.spec_k + 1) if self._spec else cfg.decode_burst

        # per-slot sampling + grammar state (the tentpole lanes). The
        # engine-wide do_sample/temperature survive as the DEFAULT
        # SamplingParams a request inherits when it supplies none.
        self._psampling = bool(cfg.per_slot_sampling)
        if min(cfg.logprobs_topn, cfg.grammar_slots, cfg.rep_window - 1,
               cfg.grammar_states - 1) < 0:
            raise ValueError(
                "logprobs_topn/grammar_slots must be >= 0; "
                "rep_window/grammar_states must be >= 1"
            )
        self._default_sampling = SamplingParams(
            do_sample=cfg.do_sample, temperature=cfg.temperature,
            seed=cfg.seed,
        ).validate()
        if cfg.do_sample and self._psampling:
            warnings.warn(
                "EngineConfig(do_sample=True) + temperature are superseded by "
                "per-request sampling params: they now only set the default "
                "SamplingParams a request inherits when it supplies none "
                "(sampled draws use the per-slot derived keys, not the "
                "legacy threaded key)",
                stacklevel=2,
            )
        self._vocab_size = int(mcfg.vocab_size)
        self._sampled_greedy = 0
        self._sampled_sample = 0
        self._grammar_masked_steps = 0
        self._rej_drafted = 0
        self._rej_accepted = 0
        # grammar row table: row 0 is the permanently-pinned unconstrained
        # sentinel (mask all-True, transitions all-0); rows 1..G-1 are
        # refcounted per live request, cached under their grammar hash
        # when idle, LRU-evicted when a new grammar needs a row
        self._grammar_rows: dict[str, int] = {}
        self._row_refs = [0] * (cfg.grammar_slots + 1)
        self._row_grammar: dict[int, object] = {}
        self._row_lru: OrderedDict[str, int] = OrderedDict()

        self._mb = cfg.blocks_per_slot  # block-table width
        # explicit is-None test: an explicit num_blocks=0 must reach the
        # allocator's >= 2 guard, not be silently rewritten to full residency
        num_blocks = (
            cfg.num_blocks if cfg.num_blocks is not None
            else cfg.num_slots * self._mb + 1
        )

        # device state: per-layer page pools in the kv_dtype policy's
        # storage dtype ("auto" = the params' compute dtype, the PR 4
        # behaviour; int8/fp8 add per-row amax scale arrays beside them)
        n_kv = getattr(mcfg, "num_key_value_heads", None) or mcfg.num_attention_heads
        embed = jax.tree.leaves(self._params)[0]
        dtype = embed.dtype if jnp.issubdtype(embed.dtype, jnp.floating) else jnp.float32
        if cfg.kv_dtype in (None, "auto"):
            store_dtype, quantized = dtype, False
        else:
            from ..ops.fp8 import kv_storage_dtype

            store_dtype, quantized = kv_storage_dtype(cfg.kv_dtype)
        self._quantized = quantized
        self.kv_dtype = str(np.dtype(store_dtype))
        shape = (mcfg.num_hidden_layers, num_blocks, cfg.block_size, n_kv, mcfg.head_dim)
        scale_shape = (mcfg.num_hidden_layers, num_blocks, cfg.block_size, n_kv)
        #: bytes one cached token costs across all layers (K + V payload
        #: plus the f32 scales when quantized) — the decode-bandwidth and
        #: slot-capacity headline number
        self.kv_bytes_per_token = (
            2
            * mcfg.num_hidden_layers
            * n_kv
            * (mcfg.head_dim * np.dtype(store_dtype).itemsize + (4 if quantized else 0))
        )
        #: max-length requests the pool holds concurrently (num_blocks is
        #: fixed for the engine's lifetime — computed once, reported by
        #: stats() and every telemetry step row)
        self.kv_slot_capacity = (num_blocks - 1) // cfg.blocks_per_slot
        self.hbm_preflight: dict | None = None
        if cfg.hbm_budget_gb is not None:
            self._hbm_preflight(inner, shape, store_dtype, mesh)

        self.allocator = BlockAllocator(num_blocks)
        self.radix = (
            RadixCache(self.allocator, cfg.block_size) if cfg.prefix_cache else None
        )
        self._swap = (
            SwapPool(
                num_layers=shape[0], block_size=cfg.block_size,
                num_kv_heads=n_kv, head_dim=mcfg.head_dim,
                dtype=store_dtype, capacity_gb=cfg.swap_gb,
                quantized=quantized,
            )
            if cfg.swap_gb and cfg.swap_gb > 0
            else None
        )
        #: per-request usage ledger (None = disabled: every hot-path hook
        #: site pays one truthiness check and nothing else)
        self.usage = UsageLedger() if cfg.usage_accounting else None
        self.scheduler = SlotScheduler(
            cfg.num_slots, self.allocator, cfg.block_size, cfg.max_seq_len,
            radix=self.radix, usage=self.usage,
        )
        self._kp = jnp.zeros(shape, store_dtype)
        self._vp = jnp.zeros(shape, store_dtype)
        # all-ones init: a never-written row dequantizes to exactly 0
        self._ks = jnp.ones(scale_shape, jnp.float32) if quantized else None
        self._vs = jnp.ones(scale_shape, jnp.float32) if quantized else None
        self._key = jax.random.PRNGKey(cfg.seed)
        self._temp = jnp.float32(cfg.temperature)
        #: per-slot draw root: never split/threaded — every draw derives
        #: from it by fold_in(tag, request seed, output position), which is
        #: what makes (seed, prompt) reproducible across admission orders
        #: and preempt/swap/resume (sampling.slot_keys)
        self._base_key = jax.random.PRNGKey(cfg.seed)
        if self._psampling:
            g = cfg.grammar_slots + 1
            self._gmask = jnp.ones((g, cfg.grammar_states, self._vocab_size), bool)
            self._gtrans = jnp.zeros(
                (g, cfg.grammar_states, self._vocab_size), jnp.int32
            )
        else:
            self._gmask = self._gtrans = None
        #: device-committed all-inert lane dict, built lazily: the
        #: all-greedy dispatch fast path reuses these buffers verbatim, so
        #: plain traffic never pays the per-iteration lane rebuild/upload
        #: (the in-trace lax.cond already argmaxes without reading them)
        self._lanes_idle = None
        self.mesh = mesh
        if mesh is not None:
            self._place_on_mesh(inner)

        # host mirrors the compiled step reads every iteration
        self._block_tables = np.zeros((cfg.num_slots, self._mb), np.int32)
        self._pending_tok = np.zeros((cfg.num_slots,), np.int32)

        # counters (the *_traces counters increment inside the traced
        # bodies, i.e. only on a jit cache miss — the "exactly one decode
        # executable" acceptance bar reads decode_compiles)
        self._decode_traces = 0
        self._prefill_traces = 0
        # one-executable watchdog state: the abstract signature of every
        # decode dispatch, so a second trace can NAME the argument whose
        # shape/dtype drifted (analysis/compiled.py fingerprint diff) —
        # with the sanitizer armed the re-trace raises immediately
        self._decode_sig: tuple | None = None
        self._decode_traces_seen = 0
        self.retrace_report: str | None = None
        self._iterations = 0
        self._tokens_emitted = 0
        self._occupancy_sum = 0.0
        self._start_time: float | None = None
        # bounded completion history (percentile window) + exact totals:
        # the ring caps memory and stats() cost on a long-lived server
        # while completed_total keeps counting past the cap
        self._completed: deque[Request] = deque(
            maxlen=max(1, int(cfg.completed_history))
        )
        self._completed_total = 0
        #: per-iteration request tracer (None when tracing is disabled —
        #: refreshed by ONE get_tracer() read at the top of step())
        self._tr = None
        self._last_stats_t: float | None = None
        self._last_stats_tokens = 0
        # sharing / preemption counters (reset_stats zeroes them with the
        # rest of the measurement state; the radix cache itself stays warm)
        self._preemptions = 0
        self._swapped_out_blocks = 0
        self._swapped_in_blocks = 0
        self._out_of_blocks_total = 0
        self._deadline_expired = 0
        # speculative accounting (accept rate = accepted / drafted):
        # drafted counts spec_k per live lane per round, accepted the
        # verify-agreed prefix length (the correction token is free and
        # counted in neither)
        self._spec_drafted = 0
        self._spec_accepted = 0
        # per-iteration flight recorder (None = disabled: step() pays one
        # `is None` check and nothing else). Registered process-globally
        # so the watchdog's HANG_REPORT and the /profile dump can reach
        # the ring without holding an engine reference.
        self._flight = (
            FlightRecorder(cfg.flight_history) if cfg.flight_history else None
        )
        if self._flight is not None:
            set_active_flight_recorder(self._flight)
        # double-buffered dispatch state: the round handed to the device
        # last iteration and not yet harvested (None = nothing in flight),
        # plus the parking list a mid-schedule fence (swap-out) harvests
        # into — drained into the SAME step's finished list at its harvest
        # point, so a fenced finish is still returned exactly once
        self._inflight: _InFlightRound | None = None
        self._harvest_backlog: list[Request] = []
        # flight phase accumulator (replaces fixed telescoping stamps —
        # the async loop re-enters phases, e.g. "harvest" both at the
        # harvest point and for end-of-step bookkeeping): _fl_switch
        # closes the open interval into its phase bucket; an interval
        # additionally accrues into overlap_hidden when it OPENED with a
        # round in flight — the device was busy under the whole interval,
        # so that host time is off the critical path. The open-time rule
        # makes sync-mode overlap exactly 0.0 (dispatch opens with
        # nothing in flight) and keeps device_wait pure residual sync.
        self._fl_t0 = 0.0
        self._fl_last = 0.0
        self._fl_cur = "idle"
        self._fl_phases: dict | None = None
        self._fl_overlap = 0.0
        self._fl_hidden = False
        # static HBM model for the hbm watermark fallback: params + the
        # paged pools (+ scales), the same inventory the PR 8 preflight
        # prices — used verbatim when the backend has no memory_stats()
        self._static_hbm_bytes = int(
            sum(
                np.size(x) * np.dtype(getattr(x, "dtype", np.float32)).itemsize
                for x in jax.tree_util.tree_leaves(self._params)
            )
            + sum(
                p.size * np.dtype(p.dtype).itemsize
                for p in (self._kp, self._vp, self._ks, self._vs)
                if p is not None
            )
        )

        self._decode_fn = (
            self._build_spec_decode_fn() if self._spec else self._build_decode_fn()
        )
        self._prefill_fn = self._build_prefill_fn()
        # block-granular pool edits for CoW copies and swap restores:
        # donated so XLA aliases the pool buffer instead of copying the
        # whole pool per block. These are *separate* tiny executables —
        # the one-compiled-DECODE-executable contract is about
        # ``_decode_fn``, whose trace counter they never touch. Block ids
        # ride as traced int32 scalars so every block reuses one compile.
        self._copy_block_fn = jax.jit(
            lambda pool, src, dst: pool.at[:, dst].set(pool[:, src]),
            donate_argnums=(0,),
        )
        # batched restore: the id vector's length is padded to a power of
        # two (pad entries scatter zeros into the null block, which is
        # never attended), so the executable count stays O(log blocks),
        # not one per distinct swap size
        self._write_blocks_fn = jax.jit(
            lambda pool, ids, rows: pool.at[:, ids].set(rows),
            donate_argnums=(0,),
        )
        # grammar-row install: one donated row-set per table, the row id a
        # traced scalar so every grammar reuses one compile — same tiny-
        # executable discipline as the block edits above (never touches
        # the decode trace counter)
        self._write_grammar_row_fn = jax.jit(
            lambda tab, row, data: tab.at[row].set(data),
            donate_argnums=(0,),
        )
        # first-token pick for the per-slot path: the prefill executable
        # already returns the prompt-final logits, so the lane transform
        # runs on them as a [1, vocab] slice of the SAME pick_tokens the
        # decode scan uses — one tiny extra executable, zero extra model
        # forwards, and exact key parity with decode (position 0)
        if self._psampling:
            eos_id = cfg.eos_token_id
            topn = cfg.logprobs_topn

            def first_pick(logits, lanes, gmask, base_key):
                return pick_tokens(
                    logits, lanes, lanes["dfa_state"], jnp.int32(0), gmask,
                    base_key, eos_id=eos_id, logprobs_topn=topn,
                )

            self._first_pick_fn = jax.jit(first_pick)

    def _place_on_mesh(self, inner) -> None:
        """GSPMD placement over ``self.mesh``: every device-side input to
        the compiled step gets an explicit ``NamedSharding`` so the first
        dispatch compiles the sharded program and every later dispatch
        reuses it (donated pool buffers keep their sharding, so the
        signature — avals + shardings — never drifts). Host mirrors
        (block tables, positions, tokens) stay plain numpy: they are
        uncommitted inputs GSPMD replicates for free."""
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.sharding import (
            infer_param_sharding,
            paged_kv_sharding,
            shard_params,
        )
        from ..utils.dataclasses import FullyShardedDataParallelPlugin

        mesh = self.mesh
        rules = getattr(inner, "partition_rules", None)
        shardings = infer_param_sharding(
            self._params, mesh, FullyShardedDataParallelPlugin(), rules
        )
        self._params = shard_params(self._params, shardings)
        pool_sharding = paged_kv_sharding(mesh, self._kp.shape[3])
        self._kp = jax.device_put(self._kp, pool_sharding)
        self._vp = jax.device_put(self._vp, pool_sharding)
        if self._ks is not None:
            from ..parallel.sharding import paged_kv_scale_sharding

            scale_sharding = paged_kv_scale_sharding(mesh, self._ks.shape[3])
            self._ks = jax.device_put(self._ks, scale_sharding)
            self._vs = jax.device_put(self._vs, scale_sharding)
        # scheduler-adjacent scalars must live on the SAME device set as the
        # sharded params — a single-device-committed leaf among mesh-committed
        # ones is an incompatible-devices error at dispatch
        rep = NamedSharding(mesh, PartitionSpec())
        self._key = jax.device_put(self._key, rep)
        self._temp = jax.device_put(self._temp, rep)
        self._base_key = jax.device_put(self._base_key, rep)
        if self._gmask is not None:
            # grammar tables are read-gathered per slot — tiny, replicated
            self._gmask = jax.device_put(self._gmask, rep)
            self._gtrans = jax.device_put(self._gtrans, rep)

    def _idle_lanes(self) -> dict:
        """The cached device-committed blank lane dict for all-inert
        dispatches. Every value is already a (replicated, on-mesh) jax
        array, so handing it to the compiled step costs zero host work —
        no per-iteration rebuild, no numpy→device transfer. Correct for
        any all-inert batch because the traced ``lax.cond`` in
        ``pick_tokens`` takes the bare-argmax branch without reading a
        single lane value."""
        if self._lanes_idle is None:
            lanes = blank_lanes(self.config.num_slots, self.config.rep_window)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                rep = NamedSharding(self.mesh, PartitionSpec())
                lanes = {k: jax.device_put(v, rep) for k, v in lanes.items()}
            else:
                lanes = {k: jnp.asarray(v) for k, v in lanes.items()}
            self._lanes_idle = lanes
        return self._lanes_idle

    def _hbm_preflight(self, inner, pool_shape, pool_dtype, mesh) -> None:
        """shard-check's SP004 at the serving seam: predicted per-device
        bytes of params (under the placement ``_place_on_mesh`` would pick)
        plus both paged pools — plus, with speculation armed, the
        ``draft_params`` tier (the transient in-trace slice of the target's
        first layers the spec executable materialises) — refused against
        ``hbm_budget_gb`` BEFORE a single buffer allocates."""
        from ..analysis.shardplan import engine_preflight

        report = engine_preflight(
            self._params,
            getattr(inner, "partition_rules", None),
            mesh,
            pool_shape,
            pool_dtype,
            self.config.hbm_budget_gb,
            swap_gb=self.config.swap_gb or None,
            draft_layers=self._spec.layers if self._spec else None,
            stacked_prefix=getattr(inner, "stacked_params_prefix", "layers"),
        )
        self.hbm_preflight = report
        if report["over"]:
            gib = 1 << 30
            draft = (
                f" + draft {report['draft_bytes'] / gib:.3f}"
                if report.get("draft_bytes") else ""
            )
            raise ValueError(
                f"SP004: engine refuses to start — predicted "
                f"{report['total_bytes'] / gib:.3f} GiB/device "
                f"(params {report['params_bytes'] / gib:.3f}{draft} + "
                f"kv pools {report['pool_bytes'] / gib:.3f}) exceeds the "
                f"{self.config.hbm_budget_gb:.3f} GiB budget. Lower "
                f"num_blocks/max_seq_len (or use serve --auto-blocks), shard "
                f"over a larger mesh, shrink the draft (or spec_k=0), or "
                f"raise the budget"
            )

    # -- compiled programs ---------------------------------------------------

    def _paged_kv_dict(self, kp, vp, ks, vs) -> dict:
        pages = {"k": kp, "v": vp}
        if self._quantized:
            pages["k_scale"], pages["v_scale"] = ks, vs
        return pages

    def _build_decode_fn(self):
        if self._psampling:
            return self._build_lane_decode_fn()
        apply_fn, cfg = self._apply_fn, self.config
        quantized = self._quantized

        def decode(params, kp, vp, ks, vs, block_tables, pos0, toks, active, key, temp):
            self._decode_traces += 1  # traced-body side effect: cache misses only

            def one_step(carry, _):
                kp, vp, ks, vs, toks, pos, key = carry
                out = apply_fn(
                    params,
                    input_ids=toks,
                    paged_kv=self._paged_kv_dict(kp, vp, ks, vs),
                    block_tables=block_tables,
                    cache_positions=pos,
                    paged_write_mask=active,  # PREFILL/free lanes must not scribble
                )
                logits = out["logits"][:, -1, :]
                tok, key, _ = _pick_traced(
                    logits, key, jnp.zeros(logits.shape[:1], bool), jnp.int32(0),
                    temp, cfg.do_sample, has_eos=False,  # eos is host-side state
                )
                pages = out["paged_kv"]
                ks2 = pages.get("k_scale", ks)
                vs2 = pages.get("v_scale", vs)
                return (
                    pages["k"], pages["v"], ks2, vs2, tok[:, None], pos + 1, key
                ), tok

            (kp, vp, ks, vs, _, _, key), toks_out = jax.lax.scan(
                one_step, (kp, vp, ks, vs, toks, pos0, key), None,
                length=cfg.decode_burst,
            )
            return kp, vp, ks, vs, toks_out, key  # toks_out: [burst, num_slots]

        # scale arrays are donated pool operands exactly like the pools —
        # at kv_dtype="auto"/"bf16"/"f32" they are None-free placeholders
        # that never reach the jit (see _dispatch_decode)
        donate = (1, 2, 3, 4) if quantized else (1, 2)
        if quantized:
            return jax.jit(decode, donate_argnums=donate)

        def decode_plain(params, kp, vp, block_tables, pos0, toks, active, key, temp):
            kp, vp, _, _, toks_out, key = decode(
                params, kp, vp, None, None, block_tables, pos0, toks, active,
                key, temp,
            )
            return kp, vp, toks_out, key

        return jax.jit(decode_plain, donate_argnums=donate)

    def _build_lane_decode_fn(self):
        """Per-slot twin of the legacy burst decode: the sampling lanes
        (:func:`sampling.blank_lanes` schema), the grammar tables, and the
        derived-key root ride as extra traced inputs of the SAME single
        executable — their shapes/dtypes are engine geometry, so
        per-request variation is data, never a retrace. Each burst step
        runs :func:`sampling.pick_tokens` (which drops to a bare argmax
        under ``lax.cond`` when every lane is inert — greedy parity with
        the legacy executable is exact) and advances the per-slot DFA
        state in-trace for mid-burst masking; the host re-derives the
        authoritative state per emitted token, so discarded burst tails
        never corrupt it.  The per-step top-N logprob harvest rides the
        scan outputs through the one existing device_get."""
        apply_fn, cfg = self._apply_fn, self.config
        quantized = self._quantized
        eos_id = cfg.eos_token_id
        topn = cfg.logprobs_topn

        def decode(params, kp, vp, ks, vs, block_tables, pos0, toks, active,
                   lanes, gmask, gtrans, base_key):
            self._decode_traces += 1  # traced-body side effect: cache misses only

            def one_step(carry, t):
                kp, vp, ks, vs, toks, pos, dfa = carry
                out = apply_fn(
                    params,
                    input_ids=toks,
                    paged_kv=self._paged_kv_dict(kp, vp, ks, vs),
                    block_tables=block_tables,
                    cache_positions=pos,
                    paged_write_mask=active,  # PREFILL/free lanes must not scribble
                )
                logits = out["logits"][:, -1, :]
                tok, logp_tok, top_vals, top_ids = pick_tokens(
                    logits, lanes, dfa, t, gmask, base_key,
                    eos_id=eos_id, logprobs_topn=topn,
                )
                dfa = gtrans[lanes["grammar_row"], dfa, tok]
                pages = out["paged_kv"]
                ks2 = pages.get("k_scale", ks)
                vs2 = pages.get("v_scale", vs)
                return (
                    pages["k"], pages["v"], ks2, vs2, tok[:, None], pos + 1, dfa
                ), (tok, logp_tok, top_vals, top_ids)

            (kp, vp, ks, vs, _, _, _), (toks_out, logps, tvals, tids) = jax.lax.scan(
                one_step,
                (kp, vp, ks, vs, toks, pos0, lanes["dfa_state"]),
                jnp.arange(cfg.decode_burst),
            )
            # toks_out: [burst, num_slots]; logprob outputs [burst, slots(, N)]
            return kp, vp, ks, vs, toks_out, logps, tvals, tids

        donate = (1, 2, 3, 4) if quantized else (1, 2)
        if quantized:
            return jax.jit(decode, donate_argnums=donate)

        def decode_plain(params, kp, vp, block_tables, pos0, toks, active,
                         lanes, gmask, gtrans, base_key):
            kp, vp, _, _, toks_out, logps, tvals, tids = decode(
                params, kp, vp, None, None, block_tables, pos0, toks, active,
                lanes, gmask, gtrans, base_key,
            )
            return kp, vp, toks_out, logps, tvals, tids

        return jax.jit(decode_plain, donate_argnums=donate)

    def _build_spec_decode_fn(self):
        """Speculative twin of ``_build_decode_fn`` — when ``spec_k`` is
        armed this IS the engine's one decode executable. One dispatch runs
        the whole round:

        1. **draft scan**: ``k`` greedy steps of the early-exit draft (the
           target's first ``draft_layers`` layers), autoregressing through
           a sliced view of the target pool's first layers — identical
           weights make its K/V a strict subset of the target's, so the
           draft needs no cache of its own;
        2. **one verify forward** of static shape ``[num_slots, k+1]`` over
           ``[pending, d_1 .. d_k]`` through the fused paged-attention
           kernel (quantize-on-scatter + in-register dequant ride along at
           every ``kv_dtype``). The verify re-scatters ALL layers at the
           round's positions — including the draft layers, which makes the
           draft scan's own pool writes disposable (they are discarded, not
           written back);
        3. **greedy acceptance** via the shared
           :func:`~accelerate_tpu.generation.spec_accept_tokens` helper —
           the single source of acceptance semantics with ``generate()``.

        Rollback of rejected drafts is pure position bookkeeping: the host
        advances each slot by ``accept+1``, the next round re-writes the
        stale rows before any query can attend them, and no pool edit
        beyond the normal scatter ever happens. Donation discipline and the
        traced-body compile counter are identical to the plain decode fn,
        so ``decode_compiles == 1`` remains the asserted contract."""
        if self._psampling:
            return self._build_lane_spec_decode_fn()
        from ..generation import spec_accept_tokens

        apply_fn, cfg = self._apply_fn, self.config
        draft_apply = self._draft_apply
        dl = self._spec.layers
        k = cfg.spec_k
        quantized = self._quantized

        def spec_decode(params, kp, vp, ks, vs, block_tables, pos0, toks, active):
            self._decode_traces += 1  # traced-body side effect: cache misses only

            def dstep(carry, _):
                dkp, dvp, dks, dvs, tok, pos = carry
                pages_in = {"k": dkp, "v": dvp}
                if quantized:
                    pages_in["k_scale"], pages_in["v_scale"] = dks, dvs
                out = draft_apply(
                    params,
                    input_ids=tok,
                    paged_kv=pages_in,
                    block_tables=block_tables,
                    cache_positions=pos,
                    paged_write_mask=active,  # PREFILL/free lanes must not scribble
                )
                pages = out["paged_kv"]
                nxt = jnp.argmax(out["logits"][:, -1, :], axis=-1).astype(jnp.int32)
                return (
                    pages["k"], pages["v"],
                    pages.get("k_scale", dks), pages.get("v_scale", dvs),
                    nxt[:, None], pos + 1,
                ), nxt

            # the draft autoregresses through a sliced copy of the target
            # pool's first dl layers; its writes only feed its OWN next
            # steps — the verify below regenerates those rows from the same
            # tokens/weights, so the scan carry is dropped, not merged back
            d0 = (
                kp[:dl], vp[:dl],
                ks[:dl] if quantized else None,
                vs[:dl] if quantized else None,
                toks, pos0,
            )
            _, d = jax.lax.scan(dstep, d0, None, length=k)
            d = d.T  # [num_slots, k] draft proposals

            # ONE verify forward over [pending, d_1 .. d_k]: scatters k+1
            # positions per active slot, reads the pool through the fused
            # block-table kernel (query j attends positions <= pos0+j)
            chunk = jnp.concatenate([toks, d], axis=1)  # [num_slots, k+1]
            vmask = jnp.broadcast_to(active, (cfg.num_slots, k + 1))
            out = apply_fn(
                params,
                input_ids=chunk,
                paged_kv=self._paged_kv_dict(kp, vp, ks, vs),
                block_tables=block_tables,
                cache_positions=pos0,
                paged_write_mask=vmask,
            )
            pages = out["paged_kv"]
            preds = jnp.argmax(out["logits"], axis=-1).astype(jnp.int32)  # [slots, k+1]
            accept, tok_seq = spec_accept_tokens(d, preds)
            return (
                pages["k"], pages["v"],
                pages.get("k_scale", ks), pages.get("v_scale", vs),
                tok_seq, accept,
            )

        donate = (1, 2, 3, 4) if quantized else (1, 2)
        if quantized:
            return jax.jit(spec_decode, donate_argnums=donate)

        def spec_plain(params, kp, vp, block_tables, pos0, toks, active):
            kp, vp, _, _, tok_seq, accept = spec_decode(
                params, kp, vp, None, None, block_tables, pos0, toks, active
            )
            return kp, vp, tok_seq, accept

        return jax.jit(spec_plain, donate_argnums=donate)

    def _build_lane_spec_decode_fn(self):
        """Per-slot spec round: the draft proposes through the SAME lane
        transform the plain decode uses (grammar mask, filters, per-slot
        derived keys — ``TAG_DRAFT``), the verify scores every position
        through it again, and acceptance splits per slot:

        * greedy slots keep the exact longest-agreeing-prefix path
          (:func:`~accelerate_tpu.generation.spec_accept_tokens` over the
          *filtered* target argmax — token-identical to the non-spec
          engine, and the filter re-check is what keeps accepted drafts
          inside a constrained slot's language);
        * sampled slots run standard speculative rejection sampling
          (:func:`sampling.rejection_accept`): accept draft ``d_j`` with
          prob ``min(1, p_j(d_j)/q_j(d_j))``, resample the first rejection
          from the clamped residual ``max(p - q, 0)``, bonus-sample from
          ``p_k`` on full acceptance.  ``p`` and ``q`` come out of the one
          shared :func:`sampling.dist_logprobs`, so both sides of the
          ratio see identical temperature/top-k/top-p/grammar filtering —
          an out-of-language draft has ``p = 0`` and is rejected with
          certainty, and the residual stays in-language.

        The repetition-penalty ring is held constant across the round (a
        documented approximation — consistent between ``p`` and ``q``, so
        the acceptance identity is unaffected).  Donation discipline and
        the traced-body compile counter are identical to the plain lane
        decode: ``decode_compiles == 1`` stays the asserted contract."""
        from ..generation import spec_accept_tokens

        apply_fn, cfg = self._apply_fn, self.config
        draft_apply = self._draft_apply
        dl = self._spec.layers
        k = cfg.spec_k
        quantized = self._quantized
        eos_id = cfg.eos_token_id

        def spec_decode(params, kp, vp, ks, vs, block_tables, pos0, toks, active,
                        lanes, gmask, gtrans, base_key):
            self._decode_traces += 1  # traced-body side effect: cache misses only
            row = lanes["grammar_row"]

            def dstep(carry, t):
                dkp, dvp, dks, dvs, tok, pos, dfa = carry
                pages_in = {"k": dkp, "v": dvp}
                if quantized:
                    pages_in["k_scale"], pages_in["v_scale"] = dks, dvs
                out = draft_apply(
                    params,
                    input_ids=tok,
                    paged_kv=pages_in,
                    block_tables=block_tables,
                    cache_positions=pos,
                    paged_write_mask=active,  # PREFILL/free lanes must not scribble
                )
                pages = out["paged_kv"]
                filt = apply_filters(
                    out["logits"][:, -1, :], lanes, dfa, lanes["pos"] + t,
                    gmask, eos_id,
                )
                greedy = jnp.argmax(filt, axis=-1).astype(jnp.int32)
                logq = dist_logprobs(filt, lanes)
                keys = slot_keys(base_key, lanes["seed"], lanes["pos"] + t, TAG_DRAFT)
                nxt = jnp.where(
                    lanes["sample"], categorical_per_slot(keys, logq), greedy
                ).astype(jnp.int32)
                return (
                    pages["k"], pages["v"],
                    pages.get("k_scale", dks), pages.get("v_scale", dvs),
                    nxt[:, None], pos + 1, gtrans[row, dfa, nxt],
                ), (nxt, jnp.exp(logq))

            d0 = (
                kp[:dl], vp[:dl],
                ks[:dl] if quantized else None,
                vs[:dl] if quantized else None,
                toks, pos0, lanes["dfa_state"],
            )
            _, (d, q) = jax.lax.scan(dstep, d0, jnp.arange(k))
            d = d.T  # [num_slots, k] draft proposals; q: [k, slots, vocab]

            chunk = jnp.concatenate([toks, d], axis=1)  # [num_slots, k+1]
            vmask = jnp.broadcast_to(active, (cfg.num_slots, k + 1))
            out = apply_fn(
                params,
                input_ids=chunk,
                paged_kv=self._paged_kv_dict(kp, vp, ks, vs),
                block_tables=block_tables,
                cache_positions=pos0,
                paged_write_mask=vmask,
            )
            pages = out["paged_kv"]
            tlogits = out["logits"]  # [num_slots, k+1, vocab]

            # DFA states along the draft path (k is small and static): the
            # verify filters each position with the state its PREFIX put
            # the automaton in — this is the mask re-check
            states = [lanes["dfa_state"]]
            for j in range(k):
                states.append(gtrans[row, states[j], d[:, j]])
            filts = [
                apply_filters(
                    tlogits[:, j, :], lanes, states[j], lanes["pos"] + j,
                    gmask, eos_id,
                )
                for j in range(k + 1)
            ]
            preds = jnp.stack(
                [jnp.argmax(f, axis=-1) for f in filts], axis=1
            ).astype(jnp.int32)
            accept_g, seq_g = spec_accept_tokens(d, preds)

            p = jnp.stack([jnp.exp(dist_logprobs(f, lanes)) for f in filts], axis=0)
            u = jnp.stack(
                [
                    uniform_per_slot(
                        slot_keys(base_key, lanes["seed"], lanes["pos"] + j, TAG_ACCEPT)
                    )
                    for j in range(k)
                ],
                axis=1,
            )  # [num_slots, k]
            accept_s, seq_s = rejection_accept(
                d, p, q, u, base_key, lanes["seed"], lanes["pos"]
            )

            sample = lanes["sample"]
            accept = jnp.where(sample, accept_s, accept_g).astype(jnp.int32)
            tok_seq = jnp.where(sample[:, None], seq_s, seq_g).astype(jnp.int32)
            return (
                pages["k"], pages["v"],
                pages.get("k_scale", ks), pages.get("v_scale", vs),
                tok_seq, accept,
            )

        donate = (1, 2, 3, 4) if quantized else (1, 2)
        if quantized:
            return jax.jit(spec_decode, donate_argnums=donate)

        def spec_plain(params, kp, vp, block_tables, pos0, toks, active,
                       lanes, gmask, gtrans, base_key):
            kp, vp, _, _, tok_seq, accept = spec_decode(
                params, kp, vp, None, None, block_tables, pos0, toks, active,
                lanes, gmask, gtrans, base_key,
            )
            return kp, vp, tok_seq, accept

        return jax.jit(spec_plain, donate_argnums=donate)

    def _build_prefill_fn(self):
        apply_fn, cfg = self._apply_fn, self.config
        quantized = self._quantized

        def prefill(params, kp, vp, ks, vs, block_table, start, chunk, valid,
                    last_idx, key, temp):
            self._prefill_traces += 1
            out = apply_fn(
                params,
                input_ids=chunk,  # [1, prefill_chunk]
                paged_kv=self._paged_kv_dict(kp, vp, ks, vs),
                block_tables=block_table,  # [1, mb]
                cache_positions=start,  # [1]
                paged_write_mask=valid,  # drops the padded tail
            )
            # first-token pick from the prompt's last real position — only
            # meaningful on the final chunk; the host ignores it otherwise
            logits = jnp.take(out["logits"][0], last_idx, axis=0)[None]
            tok, key, _ = _pick_traced(
                logits, key, jnp.zeros((1,), bool), jnp.int32(0),
                temp, cfg.do_sample, has_eos=False,
            )
            pages = out["paged_kv"]
            ks2 = pages.get("k_scale", ks)
            vs2 = pages.get("v_scale", vs)
            return pages["k"], pages["v"], ks2, vs2, tok[0], logits[0], key

        if quantized:
            return jax.jit(prefill, donate_argnums=(1, 2, 3, 4))

        def prefill_plain(params, kp, vp, block_table, start, chunk, valid,
                          last_idx, key, temp):
            out = prefill(params, kp, vp, None, None, block_table, start, chunk,
                          valid, last_idx, key, temp)
            return out[0], out[1], out[4], out[5], out[6]

        return jax.jit(prefill_plain, donate_argnums=(1, 2))

    # -- public API ----------------------------------------------------------

    def add_request(
        self,
        prompt,
        max_new_tokens: int | None = None,
        arrival_time: float | None = None,
        priority: str = "interactive",
        deadline_ms: float | None = None,
        trace_id: str | None = None,
        upstream_hop: bool = False,
        sampling=None,
        grammar: dict | None = None,
        tenant: str | None = None,
    ) -> Request:
        """Enqueue one request. ``deadline_ms`` is a *relative* budget from
        now: once it elapses the scheduler finishes the request with
        ``finish_reason="deadline_exceeded"`` (partial output kept, blocks
        freed the same iteration). A malformed value raises ValueError —
        the serve front end answers that as an error row, mirroring the
        unknown-``priority`` handling.

        ``trace_id`` is the request's distributed-trace identity: a
        well-formed supplied id (the router's, or a client's) survives
        verbatim; otherwise one is generated here. It rides every answer
        row, request-scoped trace event, and latency exemplar.
        ``upstream_hop=True`` declares that a routing tier dispatched this
        request (and emitted the flow arrow's tail) — the engine then
        lands the arrow's head at arrival. A standalone engine must leave
        it False even for client-supplied ids, or every request counts as
        an orphaned flow in the merged timeline.

        ``sampling`` is a :class:`SamplingParams` (or a dict of its
        fields) scoped to THIS request; ``None`` inherits the engine-wide
        defaults. ``grammar`` is a constrained-decoding spec
        (``{"type": "regex", ...}`` or ``{"type": "json_schema", ...}``)
        compiled here — admission fails loudly on an unsupported grammar
        or when every grammar row is held by a live request, never
        mid-decode.

        ``tenant`` is the usage ledger's accounting dimension, riding the
        same machinery as ``priority``/``trace_id``: any non-empty string
        is taken verbatim (stripped, bounded), everything else normalizes
        to ``"default"`` — unknown-safe, never an admission gate. It is
        echoed on the answer row beside the accrued costs."""
        if not self._psampling and (sampling is not None or grammar is not None):
            raise ValueError(
                "per-request sampling/grammar need per_slot_sampling=True "
                "(this engine was built with the lanes disabled)"
            )
        upstream = upstream_hop and valid_trace_id(trace_id)
        req = Request(
            prompt=[int(t) for t in np.asarray(prompt).reshape(-1)],
            max_new_tokens=int(
                self.config.max_new_tokens if max_new_tokens is None else max_new_tokens
            ),
            priority=priority,
            trace_id=ensure_trace_id(trace_id),
            tenant=normalize_tenant(tenant),
        )
        if arrival_time is not None:
            req.arrival_time = arrival_time
        if deadline_ms is not None:
            try:
                budget_ms = float(deadline_ms)
            except (TypeError, ValueError):
                budget_ms = float("nan")
            if not budget_ms > 0:  # also rejects NaN
                raise ValueError(
                    f"malformed deadline_ms {deadline_ms!r}: want a positive "
                    "number of milliseconds"
                )
            req.deadline = time.perf_counter() + budget_ms / 1000.0
        if self._psampling:
            params = resolve_sampling(sampling, self._default_sampling)
            if params.logprobs > self.config.logprobs_topn:
                raise ValueError(
                    f"request wants logprobs={params.logprobs} but the engine "
                    f"compiled logprobs_topn={self.config.logprobs_topn}; raise "
                    "EngineConfig.logprobs_topn (a traced-shape choice, so it "
                    "is per-engine, not per-request)"
                )
            req.sampling = params
            if grammar is not None:
                g = compile_grammar(
                    grammar, self._vocab_size,
                    eos_id=self.config.eos_token_id,
                    max_states=self.config.grammar_states,
                )
                req.grammar_row = self._acquire_grammar_row(g)
                req.dfa_state = g.start
        try:
            self.scheduler.submit(req)
        except BaseException:
            if req.grammar_row:
                self._release_grammar_row(req)
            raise
        if self.usage is not None:
            self.usage.begin(req)
        tr = get_tracer()
        if tr:
            # the engine-side async span opens at ARRIVAL (stamped with the
            # request's own arrival_time, so span math reproduces the
            # engine-reported TTFT exactly); a request that arrived with an
            # upstream trace_id also lands the flow-arrow head the
            # router's dispatch tail points at
            tr.request_begin(
                req.trace_id, "req/arrive", ts=req.arrival_time,
                request_id=req.request_id, prompt_tokens=req.prompt_len,
                max_new_tokens=req.max_new_tokens, priority=req.priority,
            )
            if upstream:
                tr.flow(req.trace_id, "f")
        return req

    def step(self) -> list[Request]:
        """One engine iteration: evict finished → admit queued → one
        prefill chunk → harvest the in-flight round → one decode dispatch
        over every slot. Returns requests that finished this iteration.

        With ``async_dispatch`` (the default) the decode dispatch is
        double-buffered: the round handed off at the end of iteration *i*
        is harvested at iteration *i+1*'s harvest point, so the schedule
        and prefill work above it runs WHILE the device computes. Every
        dispatch still happens strictly after the previous round's
        harvest, so the decode inputs — and therefore the emitted tokens —
        are identical to the synchronous loop; tokens simply surface one
        ``step()`` call later, and ``run_until_idle()``/``stream()`` keep
        stepping until the drain flush lands them."""
        if self._start_time is None:
            self._start_time = self._last_stats_t = time.perf_counter()
        # ONE global read per iteration when tracing is disabled — every
        # request-event site below keys off this cached (falsy) handle
        self._tr = get_tracer() or None
        sched = self.scheduler
        finished: list[Request] = []

        fl = self._flight
        self._fl_begin()

        deferred_deadline: list[Request] = []
        with trace_span("serve/schedule"):
            if sched.deadline_live:  # guarded: deadline-free = one int check
                now = time.perf_counter()
                inflight_slots = None
                if self._inflight is not None:
                    # an expired member of the in-flight round still has a
                    # token landing at this step's harvest — the token the
                    # synchronous engine emitted LAST step. Defer its
                    # expiry to just after the harvest point so the two
                    # loops stay token-identical.
                    inflight_slots = {r.slot for r in self._inflight.live}
                for req in sched.expire_deadlines(now, skip_slots=inflight_slots):
                    if req.slot is None:
                        self._release_expired_queued(req)
                    self._deadline_expired += 1
                    finished.append(req)
                if inflight_slots:
                    deferred_deadline = [
                        r for r in self._inflight.live
                        if r.deadline is not None and now > r.deadline
                    ]
            sched.evict_finished()
            self._admit_and_place()

        self._fl_switch("prefill")
        with trace_span("serve/prefill"):
            # one chunk per PREFILLING SLOT per iteration: slot turnover is
            # never throttled to one admission per decode burst, while any
            # single prompt still advances at most one chunk between decode
            # steps — the TTFT/stall bound chunked prefill exists for
            u = self.usage
            for req in sched.active(RequestState.PREFILL):
                if u is not None:
                    t0_pf = time.perf_counter()
                    self._prefill_one_chunk(req, finished)
                    u.accrue_prefill(req, time.perf_counter() - t0_pf)
                else:
                    self._prefill_one_chunk(req, finished)

        # harvest point: the previous iteration's round lands here,
        # exactly one iteration late. Backlog entries were force-harvested
        # by a mid-schedule fence (swap-out) and drain into THIS step's
        # finished list — a fenced finish is still returned exactly once.
        if self._harvest_backlog:
            finished.extend(self._harvest_backlog)
            self._harvest_backlog.clear()
        self._harvest_inflight(finished)
        for req in deferred_deadline:
            # the member's in-flight token has now been emitted (exactly
            # the output the synchronous engine had at its sweep) — expire
            # it before the next dispatch; blocks free at the next evict
            if req.state is RequestState.DECODE:
                req.finish_reason = "deadline_exceeded"
                req.finish_time = time.perf_counter()
                req.state = RequestState.FINISHED
                self._deadline_expired += 1
                finished.append(req)

        self._fl_switch("dispatch")
        decoding = sched.active(RequestState.DECODE)
        if decoding:
            with trace_span("serve/decode", slots=len(decoding)):
                self._dispatch_decode(decoding, finished)
        if not self.config.async_dispatch:
            # synchronous escape hatch: harvest the round we just
            # dispatched before leaving the iteration (the pre-item-5 loop)
            self._harvest_inflight(finished)

        self._fl_switch("harvest")
        self._iterations += 1
        self._occupancy_sum += sched.occupancy
        for req in finished:
            if req.grammar_row:
                self._release_grammar_row(req)
        self._completed.extend(finished)
        self._completed_total += len(finished)
        if self._tr is not None:
            for req in finished:
                # exactly one end event per request, whatever path finished
                # it (eos/length/out_of_blocks/deadline, queued or running)
                self._tr.request_end(
                    req.trace_id, "req/finish", ts=req.finish_time,
                    finish_reason=req.finish_reason,
                    new_tokens=len(req.output_tokens),
                    ttft_s=req.ttft_s, tpot_s=req.tpot_s,
                )
        if self.usage is not None:
            # close each finished request's account NOW (before the answer
            # rows emit) — held blocks drop to 0 on BOTH sides of the
            # block-second integral, so the extra iteration the scheduler
            # holds them before the next evict sweep is excluded
            # consistently, and the summary rides the telemetry row
            for req in finished:
                summary = self.usage.finish(req)
                if summary is not None:  # exactly-once across re-lists
                    req.usage = summary
        self._emit_telemetry(finished)
        rec = self._fl_finish()
        if rec is not None:
            t0, wall, phases, overlap = rec
            entry = fl.record(
                self._iterations, t0, wall,
                overlap_hidden_s=overlap, **phases,
            )
            fl.current_phase = "idle"
            reg = get_active_registry()
            if reg:
                observe_flight(reg, entry)
            if self._tr is not None:
                # host share over time as a Perfetto counter track, plus
                # one instant per iteration carrying the phase breakdown
                # (the wall-corrected reader behind `trace tail
                # --iterations` consumes these)
                self._tr.counter("serve/iteration", fl.host_fraction())
                self._tr.instant(
                    "serve/flight",
                    **{k: v for k, v in entry.items() if k != "t_start"},
                )
        return finished

    def run_until_idle(self, max_iterations: int | None = None) -> list[Request]:
        """Drain queue + slots + the in-flight round; returns every
        request finished during the drain (scheduling-bug guard:
        ``max_iterations`` bounds the loop). The final drain flush — the
        step that only harvests the last in-flight round — counts as an
        iteration like any other; the cap is checked BEFORE stepping, so
        a cap that lands exactly on the drain boundary still returns
        every finished request (and raising never swallows them)."""
        done: list[Request] = []
        it = 0
        while self.scheduler.has_work() or self._inflight is not None:
            if max_iterations is not None and it >= max_iterations:
                raise RuntimeError(f"engine not idle after {it} iterations")
            done.extend(self.step())
            it += 1
        return done

    def stream(self, prompt, max_new_tokens: int | None = None):
        """Generator yielding this request's tokens as the engine emits
        them (other in-flight requests keep decoding underneath)."""
        req = self.add_request(prompt, max_new_tokens)
        served = 0
        while req.state is not RequestState.FINISHED:
            self.step()
            while served < len(req.output_tokens):
                yield req.output_tokens[served]
                served += 1
        while served < len(req.output_tokens):
            yield req.output_tokens[served]
            served += 1

    def reset_stats(self) -> None:
        """Zero the measurement state (iterations, tokens, occupancy,
        completed-request percentiles, wall clock) while keeping the
        compiled programs, pages, and compile counters — so a bench can
        warm up and then measure without the warmup's idle-engine TTFT and
        low-occupancy drain iterations biasing the reported percentiles."""
        self._iterations = 0
        self._tokens_emitted = 0
        self._occupancy_sum = 0.0
        self._start_time = None
        self._completed.clear()
        self._completed_total = 0
        self._last_stats_t = None
        self._last_stats_tokens = 0
        self._preemptions = 0
        self._swapped_out_blocks = 0
        self._swapped_in_blocks = 0
        self._out_of_blocks_total = 0
        self._deadline_expired = 0
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._sampled_greedy = 0
        self._sampled_sample = 0
        self._grammar_masked_steps = 0
        self._rej_drafted = 0
        self._rej_accepted = 0
        # hit accounting restarts with the measurement window; the trie and
        # its cached blocks deliberately stay warm (steady-state behaviour
        # is what a warmed bench leg measures)
        self.scheduler.prompt_tokens_admitted = 0
        self.scheduler.prefix_hit_tokens = 0
        if self.radix is not None:
            self.radix.evicted_blocks = 0
            self.radix.inserted_blocks = 0
        # the flight ring is measurement state like everything above: a
        # warmup→reset→measure cycle must report post-reset iterations
        # only, for stats()['host_fraction'] and the ring both
        if self._flight is not None:
            self._flight.reset()
        # like the flight ring: the ledger is measurement state — rollups
        # zero, live requests re-base their block integrals at now
        if self.usage is not None:
            self.usage.reset()

    def _hbm_watermarks(self) -> dict:
        """Live device-memory watermarks where the backend exposes them
        (``Device.memory_stats()`` — TPU/GPU runtimes), else the static
        params+pools model the PR 8 preflight prices, labelled
        ``"estimate"`` so a CPU reading is never mistaken for a real
        high-water mark. Headroom appears when a limit is known (backend
        ``bytes_limit`` or the configured ``hbm_budget_gb``)."""
        used = peak = limit = None
        source = "estimate"
        try:
            mem = jax.local_devices()[0].memory_stats()
            if mem and "bytes_in_use" in mem:
                used = int(mem["bytes_in_use"])
                peak = int(mem.get("peak_bytes_in_use", used))
                limit = int(mem["bytes_limit"]) if "bytes_limit" in mem else None
                source = "memory_stats"
        except Exception:
            pass
        if used is None:
            used = peak = self._static_hbm_bytes
        if limit is None and self.config.hbm_budget_gb is not None:
            limit = int(self.config.hbm_budget_gb * (1 << 30))
        out = {
            "hbm_used_bytes": used,
            "hbm_peak_bytes": peak,
            "hbm_bytes_source": source,
        }
        if limit is not None:
            out["hbm_limit_bytes"] = limit
            out["hbm_headroom_bytes"] = limit - used
        return out

    def _spec_stats(self) -> dict:
        """Speculative health fields (accept rate is the TPOT lever — each
        round costs one dispatch and emits accept+1 tokens). The SINGLE
        source for both export surfaces, ``stats()`` and the telemetry
        step rows; empty when speculation is off (monitor keys off
        ``spec_k``)."""
        if self._spec is None:
            return {}
        return {
            "spec_k": self.config.spec_k,
            "spec_draft": str(self._spec),
            "spec_drafted_tokens": self._spec_drafted,
            "spec_accepted_tokens": self._spec_accepted,
            "spec_accept_rate": (
                self._spec_accepted / self._spec_drafted
                if self._spec_drafted else 0.0
            ),
        }

    def _sampling_stats(self) -> dict:
        """Per-slot sampling/grammar health fields. Like ``_spec_stats``,
        the SINGLE source for both ``stats()`` and the telemetry step
        rows; empty when the lanes are disabled. The rejection counters
        only appear with speculation armed — they are the sampled-slot
        analogue of the greedy accept rate."""
        if not self._psampling:
            return {}
        out = {
            "sampled_tokens_greedy": self._sampled_greedy,
            "sampled_tokens_sample": self._sampled_sample,
            "grammar_masked_steps": self._grammar_masked_steps,
            "grammar_rows_live": sum(1 for r in self._row_refs if r > 0),
        }
        if self._spec is not None:
            out["rejection_drafted_tokens"] = self._rej_drafted
            out["rejection_accepted_tokens"] = self._rej_accepted
            out["rejection_accept_rate"] = (
                self._rej_accepted / self._rej_drafted
                if self._rej_drafted else 0.0
            )
        return out

    def stats(self) -> dict:
        """Aggregate serving health: goodput, TTFT/TPOT percentiles over
        completed requests, mean slot occupancy, and the compile counters
        the one-executable contract is asserted against."""
        sched = self.scheduler
        cached = self.radix.cached_block_count if self.radix is not None else 0
        cached_exclusive = (
            self.radix.exclusive_block_count() if self.radix is not None else 0
        )
        out = {
            "iterations": self._iterations,
            # exact cumulative count — NOT the percentile window's length
            # (the ring caps history; the counter keeps counting past it)
            "completed": self._completed_total,
            "completed_window": len(self._completed),
            "queue_depth": sched.queue_depth,
            "active_slots": len(sched.active()),
            "num_slots": self.config.num_slots,
            "tokens_emitted": self._tokens_emitted,
            "decode_compiles": self._decode_traces,
            "prefill_compiles": self._prefill_traces,
            # kv_dtype policy: bytes one cached token moves/holds (K+V
            # payload + scales across layers) and how many max-length
            # requests the pool can hold concurrently — the capacity rows
            # `serve --auto-blocks` and `bench.py kv` report ratios of
            "kv_dtype": self.kv_dtype,
            "kv_bytes_per_token": self.kv_bytes_per_token,
            "kv_bytes_per_block": self.kv_bytes_per_token * self.config.block_size,
            "kv_slot_capacity": self.kv_slot_capacity,
            "free_blocks": self.allocator.free_count,
            # blocks live requests hold (shared prefix blocks included);
            # blocks held ONLY by the radix cache are reported separately —
            # at idle, allocated_blocks is 0 and free + cached == usable
            "allocated_blocks": self.allocator.allocated_count - cached_exclusive,
            "cached_blocks": cached,
            "slot_occupancy_mean": (
                self._occupancy_sum / self._iterations if self._iterations else 0.0
            ),
            "prefix_hit_tokens": sched.prefix_hit_tokens,
            "prefix_hit_ratio": (
                sched.prefix_hit_tokens / sched.prompt_tokens_admitted
                if sched.prompt_tokens_admitted
                else 0.0
            ),
            "preemptions": self._preemptions,
            "swapped_out_blocks": self._swapped_out_blocks,
            "swapped_in_blocks": self._swapped_in_blocks,
            "out_of_blocks_total": self._out_of_blocks_total,
            "deadline_expired_total": self._deadline_expired,
        }
        out.update(self._spec_stats())
        out.update(self._sampling_stats())
        out.update(self._hbm_watermarks())
        if self.usage is not None:
            # totals + capped by_tenant + heavy hitters + the conservation
            # partner totals (device_wait_seconds / pool_block_seconds)
            out["usage"] = self.usage.snapshot()
        if self._flight is not None:
            # host_fraction + iteration p50/p99 + per-phase breakdowns
            # over the ring window (empty until an iteration records)
            out.update(self._flight.summary())
        if self.radix is not None:
            out["radix_inserted_blocks"] = self.radix.inserted_blocks
            out["radix_evicted_blocks"] = self.radix.evicted_blocks
        if self._swap is not None:
            out["swap_used_blocks"] = self._swap.used_blocks
            out["swap_capacity_blocks"] = self._swap.capacity_blocks
            out["swap_pool_host_bytes"] = (
                self._swap.capacity_blocks * self._swap.bytes_per_block
            )
        if self.mesh is not None:
            from ..mesh import mesh_axis_sizes

            out["mesh"] = mesh_axis_sizes(self.mesh)
        if self.retrace_report is not None:
            out["retrace_report"] = self.retrace_report
        if self.hbm_preflight is not None:
            out["hbm_preflight"] = self.hbm_preflight
        if self._start_time is not None:
            elapsed = time.perf_counter() - self._start_time
            out["elapsed_s"] = elapsed
            out["tokens_per_sec"] = self._tokens_emitted / elapsed if elapsed > 0 else 0.0
        # latency percentiles over the completion window, overall and per
        # priority class — the per-tenant-SLO groundwork: "p99 TTFT" alone
        # hides an interactive regression behind a batch flood
        window = list(self._completed)
        for attr, key in (("ttft_s", "ttft_s"), ("tpot_s", "tpot_s")):
            values = [getattr(r, attr) for r in window if getattr(r, attr) is not None]
            if not values:
                continue
            entry = {
                "p50": float(np.percentile(values, 50)),
                "p99": float(np.percentile(values, 99)),
            }
            by_class = {}
            for cls in {r.priority for r in window}:
                cls_values = [
                    getattr(r, attr) for r in window
                    if r.priority == cls and getattr(r, attr) is not None
                ]
                if cls_values:
                    by_class[cls] = {
                        "p50": float(np.percentile(cls_values, 50)),
                        "p99": float(np.percentile(cls_values, 99)),
                    }
            if by_class:
                entry["by_class"] = by_class
            out[key] = entry
        return out

    # -- iteration internals -------------------------------------------------

    def _fl_begin(self) -> None:
        """Open the iteration's flight accounting in the "schedule" phase
        (no-op when the recorder is disabled)."""
        if self._flight is None:
            self._fl_phases = None
            return
        t = time.perf_counter()
        self._fl_t0 = self._fl_last = t
        self._fl_phases = dict.fromkeys(ITERATION_PHASES, 0.0)
        self._fl_overlap = 0.0
        self._fl_cur = "schedule"
        # hidden-overlap rule: an interval counts as hidden iff a round
        # was in flight when it OPENED (and it is not device_wait) — the
        # schedule work at the top of an async steady-state iteration runs
        # entirely under the previous round
        self._fl_hidden = self._inflight is not None
        self._flight.current_phase = "schedule"

    def _fl_switch(self, phase: str) -> float | None:
        """Close the open interval into its phase bucket and open
        ``phase``. Phases may be re-entered (the async loop visits
        "harvest" both at the harvest point and for bookkeeping) — the
        buckets accumulate, and their sum telescopes to the iteration
        wall exactly, which ``FlightRecorder.record`` asserts. Returns
        the closed interval's duration (None when the recorder is off) —
        the usage ledger accrues the EXACT ``device_wait`` float the
        flight recorder does, which is what makes Σ per-request decode
        shares == flight ``device_wait`` an identity, not an estimate."""
        if self._fl_phases is None:
            return None
        t = time.perf_counter()
        dt = t - self._fl_last
        self._fl_phases[self._fl_cur] += dt
        if self._fl_hidden:
            self._fl_overlap += dt
        self._fl_last = t
        self._fl_cur = phase
        # decided at OPEN time: device_wait is by definition the residual
        # the host could NOT hide, so it never accrues overlap
        self._fl_hidden = self._inflight is not None and phase != "device_wait"
        self._flight.current_phase = phase
        return dt

    def _fl_finish(self):
        """Close the last interval; returns ``(t0, wall_s, phases,
        overlap_hidden_s)`` for ``FlightRecorder.record`` (None when the
        recorder is disabled)."""
        if self._fl_phases is None:
            return None
        t = time.perf_counter()
        dt = t - self._fl_last
        self._fl_phases[self._fl_cur] += dt
        if self._fl_hidden:
            self._fl_overlap += dt
        phases, self._fl_phases = self._fl_phases, None
        self._fl_cur = "idle"
        return self._fl_t0, t - self._fl_t0, phases, self._fl_overlap

    def _harvest_inflight(self, finished: list[Request]) -> None:
        """Blocking harvest of the in-flight round: ONE device_get of
        everything the round surfaces, then token emission through the
        same ``_emit_token`` path both engine modes share (eos / length /
        grammar-final / stop-trim are host state, so finish semantics are
        inherited, not re-implemented). A member that finished while the
        round was in flight emits nothing — its lane result is discarded
        exactly like a mid-burst eos tail."""
        rd = self._inflight
        if rd is None:
            return
        u = self.usage
        pre = None if u is None else [len(r.output_tokens) for r in rd.live]
        self._fl_switch("device_wait")
        # flight disabled: the ledger stamps its own device_wait interval
        # around the blocking device_get (flight enabled: it reuses the
        # EXACT float _fl_switch closed, so the two totals are identical)
        t_dw = (
            time.perf_counter()
            if u is not None and self._fl_phases is None
            else 0.0
        )
        if rd.kind == "spec":
            tok_seq, accept = (
                np.asarray(x) for x in jax.device_get((rd.toks, rd.accept))
            )
        elif rd.harvest_lp:
            # the logprob surfaces ride the SAME device_get — no second
            # dispatch, no extra sync point
            next_toks, logps, tvals, tids = (
                np.asarray(x)
                for x in jax.device_get((rd.toks, rd.logps, rd.tvals, rd.tids))
            )
        else:
            next_toks = np.asarray(jax.device_get(rd.toks))  # [burst, slots]
        self._inflight = None
        dw = self._fl_switch("harvest")
        if u is not None and dw is None:
            dw = time.perf_counter() - t_dw
        if rd.kind == "spec":
            k = self.config.spec_k
            if self._tr is not None:
                self._tr.instant(
                    "serve/spec_round", slots=len(rd.live), k=k,
                    trace_ids=[r.trace_id for r in rd.live],
                    accepted=[int(accept[r.slot]) for r in rd.live],
                )
            for req in rd.live:
                a = int(accept[req.slot])
                self._spec_drafted += k
                self._spec_accepted += a
                if u is not None:
                    u.accrue_spec(req, k, a)
                if req.sampling is not None and req.sampling.do_sample:
                    # rejection-sampling health, counted over sampled slots
                    # only (greedy slots use exact-prefix acceptance)
                    self._rej_drafted += k
                    self._rej_accepted += a
                for t in range(a + 1):
                    if req.state is RequestState.FINISHED:
                        break  # mid-round eos/length: the run's tail is waste
                    self._emit_token(req, int(tok_seq[req.slot, t]), finished)
        else:
            for req in rd.live:
                want_lp = (
                    rd.harvest_lp
                    and req.sampling is not None
                    and req.sampling.logprobs
                )
                for t in range(self.config.decode_burst):
                    if req.state is RequestState.FINISHED:
                        break  # mid-burst eos/length: tail lane-steps are waste
                    entry = None
                    if want_lp:
                        entry = self._logprob_entry(
                            req.sampling, float(logps[t, req.slot]),
                            tvals[t, req.slot], tids[t, req.slot],
                        )
                    self._emit_token(
                        req, int(next_toks[t, req.slot]), finished, entry
                    )
        if u is not None:
            # apportion the round's device_wait across its batch, weighted
            # by the tokens each request actually emitted from this
            # harvest (stop-trim can shrink output_tokens — clamp at 0);
            # an all-discarded round splits equally so no interval is lost
            emitted = [
                max(0, len(r.output_tokens) - p) for r, p in zip(rd.live, pre)
            ]
            shares = (
                [(r.request_id, e) for r, e in zip(rd.live, emitted) if e]
                if any(emitted)
                else [(r.request_id, 1) for r in rd.live]
            )
            u.accrue_decode(dw, shares)

    def _fence_inflight(self) -> bool:
        """Synchronize with the in-flight round before host code touches
        pool rows it may still be writing (swap-out's device_get). The
        round is harvested into the backlog — its tokens land on their
        requests NOW (an in-flight member already owns that token in the
        synchronous engine's timeline), any finishes park until the step's
        harvest point drains them into the finished list — and the evict
        sweep runs so the caller's capacity math sees the freed slots.
        Returns True when a round was actually fenced."""
        if self._inflight is None:
            return False
        prev = self._fl_cur if self._fl_phases is not None else None
        self._harvest_inflight(self._harvest_backlog)
        self.scheduler.evict_finished()
        if prev is not None:
            # resume the interrupted phase: the fence's device_wait +
            # harvest intervals were attributed; the remainder of the
            # interrupted phase keeps telescoping
            self._fl_switch(prev)
        return True

    def _admit_and_place(self) -> None:
        """Admission plus its device obligations (CoW copies, swap-in
        restores), looped with priority preemption: when the head of the
        waiting queue outranks a running request and cannot be admitted
        (no slot, or no blocks even after cache eviction), the
        lowest-priority victim is swapped to host DRAM and admission
        retries. Strictly-higher rank only — equal classes never thrash
        each other at admission."""
        sched = self.scheduler
        while True:
            for req in sched.admit():
                if self._tr is not None:
                    now = time.perf_counter()
                    self._tr.request_instant(
                        req.trace_id, "req/admit", ts=now, slot=req.slot,
                        queued_s=now - req.arrival_time,
                        radix_hit_tokens=req.matched_tokens,
                        restored=req.preempted,
                    )
                self._place_admitted(req)
            head = sched.peek_head()
            if head is None or self._swap is None:
                return
            victim = sched.pick_victim()
            if victim is None or priority_rank(victim.priority) <= priority_rank(
                head.priority
            ):
                return
            if not self._swap_out(victim):
                return  # swap full: the head waits its turn

    def _place_admitted(self, req: Request) -> None:
        """The device half of admission: restore a preempted request's
        swapped rows into its freshly allocated blocks, or run the pending
        copy-on-write block copy for a partial-prefix hit."""
        if req.swap_plan:
            swap_t0 = time.perf_counter() if self._tr is not None else 0.0
            # one gathered scatter per pool (mirrors _swap_out's batched
            # device_get), padded with null-block zero rows
            n = len(req.swap_plan)
            m = 1 << max(0, (n - 1).bit_length())
            layers, _, bs, kv, hd = self._kp.shape
            dtype = np.dtype(self._kp.dtype)
            ids = np.full((m,), NULL_BLOCK, np.int32)
            k_rows = np.zeros((layers, m, bs, kv, hd), dtype)
            v_rows = np.zeros_like(k_rows)
            ks_rows = vs_rows = None
            if self._quantized:
                ks_rows = np.ones((layers, m, bs, kv), np.float32)
                vs_rows = np.ones_like(ks_rows)
            for j, (idx, handle) in enumerate(req.swap_plan):
                ids[j] = req.blocks[idx]
                k, v, ksc, vsc = self._swap.load(handle)
                k_rows[:, j] = k
                v_rows[:, j] = v
                if self._quantized:
                    ks_rows[:, j] = ksc
                    vs_rows[:, j] = vsc
            self._kp = self._write_blocks_fn(self._kp, ids, k_rows)
            self._vp = self._write_blocks_fn(self._vp, ids, v_rows)
            if self._quantized:
                # scale rows ride the same batched restore — a quantized
                # block without its scales is garbage, so they move as one
                self._ks = self._write_blocks_fn(self._ks, ids, ks_rows)
                self._vs = self._write_blocks_fn(self._vs, ids, vs_rows)
            for _, handle in req.swap_plan:
                self._swap.release(handle)
            self._swapped_in_blocks += n
            req.swap_plan = []
            req.preempted = False
            if self.usage is not None:
                # restored blocks re-enter the held count (admit() stamped
                # the pre-restore count with swap_plan still pending)
                self.usage.accrue_swap(
                    req, bytes_in=n * self._swap.bytes_per_block
                )
                self.usage.update_blocks(req)
            if self._tr is not None:
                # seconds ride the event: swap-in stalls are exactly the
                # tail-latency share `trace tail` attributes to this phase
                self._tr.request_instant(
                    req.trace_id, "req/swap_in", blocks=n,
                    seconds=time.perf_counter() - swap_t0,
                )
            if req.state is RequestState.DECODE:
                # resume feeding the last emitted token at context_len
                self._pending_tok[req.slot] = req.output_tokens[-1]
        elif req.cow is not None:
            src, dst = req.cow
            self._kp = self._copy_block_fn(self._kp, np.int32(src), np.int32(dst))
            self._vp = self._copy_block_fn(self._vp, np.int32(src), np.int32(dst))
            if self._quantized:
                # the CoW copy is byte-exact for payload AND scales: the
                # private copy dequantizes identically to the cached block
                self._ks = self._copy_block_fn(self._ks, np.int32(src), np.int32(dst))
                self._vs = self._copy_block_fn(self._vs, np.int32(src), np.int32(dst))
            self.allocator.decref([src])  # drop the eviction pin
            req.cow = None

    def _swap_out(self, victim: Request) -> bool:
        """Preempt ``victim``: device_get its unshared blocks into the host
        swap pool, release them, free the slot, and re-queue the request at
        the front of its priority class. "Unshared" means no *other live
        request* reads the block: a block shared only with the radix cache
        is swapped too (the victim's reference drops; the cache's copy
        stays resident at refcount 1, LRU-evictable — retaining it under
        the victim's ref would pin capacity the preemption exists to
        free). Blocks another live request maps keep the victim's
        reference and stay resident — their HBM is shared anyway. Returns
        False when the swap pool cannot hold the victim (caller falls back
        to truncation or waiting)."""
        # fence FIRST: an in-flight round may still be writing the
        # victim's rows — and holds a token the synchronous engine would
        # already have emitted, which must land on the victim before it
        # re-queues (pending_tok on resume is output_tokens[-1]). The
        # fence may finish the victim (eos/length on the harvested token)
        # or free its slot entirely; capacity is then already available
        # and there is nothing left to swap — report success so the
        # caller retries admission/growth instead of picking a new victim.
        if self._fence_inflight() and (
            victim.state is RequestState.FINISHED or victim.slot is None
        ):
            return True
        swappable = []
        for i, b in enumerate(victim.blocks):
            rc = self.allocator.refcount(b)
            if rc == 1 or (
                rc == 2 and self.radix is not None and self.radix.is_cached(b)
            ):
                swappable.append(i)
        if self._swap is None or not self._swap.can_hold(len(swappable)):
            return False
        swap_t0 = time.perf_counter() if self._tr is not None else 0.0
        plan: list[tuple[int, int]] = []
        released = [victim.blocks[i] for i in swappable]
        if released:
            # ONE device round trip for the whole victim: the 2–4 pool
            # gathers (k/v rows plus scale mirrors when quantized) ride a
            # single device_get of a tuple, not one blocking transfer
            # each; ids padded to a power of two (null-block reads, rows
            # discarded host-side) so the gather compiles O(log blocks)
            # executables, symmetric with _place_admitted's restore
            n = len(released)
            m = 1 << max(0, (n - 1).bit_length())
            idx = np.full((m,), NULL_BLOCK, np.int32)
            idx[:n] = released
            gathers = [self._kp[:, idx], self._vp[:, idx]]
            if self._quantized:
                gathers += [self._ks[:, idx], self._vs[:, idx]]
            rows = jax.device_get(tuple(gathers))
            k_rows, v_rows = rows[0], rows[1]  # [layers, m, bs, kv, hd]
            ks_rows = vs_rows = None
            if self._quantized:
                ks_rows, vs_rows = rows[2], rows[3]  # [layers, m, bs, kv]
            for j, i in enumerate(swappable):
                plan.append((
                    i,
                    self._swap.store(
                        k_rows[:, j], v_rows[:, j],
                        None if ks_rows is None else ks_rows[:, j],
                        None if vs_rows is None else vs_rows[:, j],
                    ),
                ))
        # refcount-1 blocks return to the freelist; cache-shared ones stay
        # allocated under the cache's own (now sole, evictable) reference
        self.allocator.decref(released)
        victim.swap_plan = plan
        self.scheduler.requeue_preempted(victim)
        self._preemptions += 1
        self._swapped_out_blocks += len(plan)
        if self.usage is not None:
            # swapped blocks leave the victim's held count (host DRAM is
            # not pool occupancy); retained shared blocks keep accruing
            self.usage.accrue_swap(
                victim, bytes_out=len(plan) * self._swap.bytes_per_block
            )
            self.usage.update_blocks(victim)
        if self._tr is not None:
            self._tr.request_instant(
                victim.trace_id, "req/preempt", blocks=len(plan),
                swap_out_s=time.perf_counter() - swap_t0,
            )
        return True

    def _release_expired_queued(self, req: Request) -> None:
        """A request that expired while *queued* holds no slot, but a
        preempted one still owns swap handles (host DRAM) and references on
        blocks it shares with live requests — release both. Pure block-table
        and refcount edits; the compiled executables never run for it."""
        if req.swap_plan:
            for _, handle in req.swap_plan:
                self._swap.release(handle)
            swapped = {idx for idx, _ in req.swap_plan}
            retained = [b for i, b in enumerate(req.blocks) if i not in swapped]
            if retained:
                self.allocator.decref(retained)
            req.swap_plan = []
        req.blocks = []
        if self.usage is not None:
            self.usage.update_blocks(req)

    def _force_finish_out_of_blocks(
        self, req: Request, finished: list[Request]
    ) -> None:
        req.finish_reason = "out_of_blocks"
        req.finish_time = time.perf_counter()
        req.state = RequestState.FINISHED
        self._out_of_blocks_total += 1
        finished.append(req)
        # free the blocks NOW (not at next step's evict sweep) so the
        # requests this truncation is making room for can grow this
        # iteration
        self.scheduler.evict_finished()

    def _sync_block_table(self, req: Request) -> None:
        row = self._block_tables[req.slot]
        row[:] = 0
        row[: len(req.blocks)] = req.blocks

    def _prefill_one_chunk(self, req: Request, finished: list[Request]) -> None:
        cfg = self.config
        c = cfg.prefill_chunk
        start = req.prefill_pos
        end = min(start + c, req.prompt_len)
        chunk = np.zeros((1, c), np.int32)
        chunk[0, : end - start] = req.prompt[start:end]
        valid = np.zeros((1, c), bool)
        valid[0, : end - start] = True
        self._sync_block_table(req)
        is_final = end == req.prompt_len
        last_idx = np.int32((req.prompt_len - 1) - start if is_final else 0)

        if self._quantized:
            (self._kp, self._vp, self._ks, self._vs, tok, _logits,
             self._key) = self._prefill_fn(
                self._params, self._kp, self._vp, self._ks, self._vs,
                self._block_tables[req.slot : req.slot + 1],
                np.asarray([start], np.int32), chunk, valid, last_idx,
                self._key, self._temp,
            )
        else:
            self._kp, self._vp, tok, _logits, self._key = self._prefill_fn(
                self._params, self._kp, self._vp,
                self._block_tables[req.slot : req.slot + 1],
                np.asarray([start], np.int32), chunk, valid, last_idx,
                self._key, self._temp,
            )
        req.prefill_pos = end
        if self._tr is not None:
            # one event per CHUNK (bounded by prompt_len / prefill_chunk),
            # never per token
            self._tr.request_instant(
                req.trace_id, "req/prefill_chunk", start=start, end=end,
                final=is_final,
            )
        if is_final:
            if self.radix is not None:
                # the prompt's full blocks now hold valid K/V: adopt them
                # into the prefix trie (refcount+1 = the cache's reference)
                # so later admissions with the same leading tokens map them
                self.radix.insert(req.prompt, req.blocks)
            lp_entry = None
            if self._psampling:
                # re-pick from the returned prompt-final logits through the
                # SAME lane transform decode uses (position 0 of the
                # request's derived key stream); on an inert request this
                # is the same argmax the executable's own pick took
                tok, lp_entry = self._first_token_pick(req, _logits)
            self._emit_token(req, int(tok), finished, lp_entry)
            if req.state is not RequestState.FINISHED:
                req.state = RequestState.DECODE

    def _ensure_decode_capacity(self, req: Request, finished: list[Request]) -> None:
        """Growth for one decode lane, with swap preemption under pool
        exhaustion. Eviction of refcount-1 cached blocks happens inside
        ``grow_for_decode``; when even that fails, the lowest-priority
        victim (possibly ``req`` itself — a request never preempts a
        *higher*-priority one) is swapped to host DRAM and growth retries.
        Truncation (``out_of_blocks``) is the last resort: swap disabled or
        full, or ``req`` alone in the pool with nothing left to reclaim."""
        sched = self.scheduler
        while not sched.grow_for_decode(req, tokens_ahead=self._decode_lookahead):
            if self._swap is None:
                # no swap tier: keep PR 4's FCFS contract — the request
                # that failed to grow is the one truncated, never an
                # innocent neighbor that fit its reservation
                self._force_finish_out_of_blocks(req, finished)
                return
            victim = sched.pick_victim() or req
            if priority_rank(victim.priority) < priority_rank(req.priority):
                victim = req  # never evict someone more important than req
            if victim is req and len(sched.active()) <= 1:
                # req is the sole tenant. Swapping itself out only helps if
                # something else would run first — a strictly higher-priority
                # waiting head admits before req's front-of-class re-queue.
                # Otherwise req re-admits immediately and ping-pongs through
                # the swap pool forever: the pool is simply too small for it,
                # and truncation is the honest answer.
                head = sched.peek_head()
                if head is None or priority_rank(head.priority) >= priority_rank(
                    req.priority
                ):
                    self._force_finish_out_of_blocks(req, finished)
                    return
            if not self._swap_out(victim):
                # swap full: truncation may only roll downhill — a
                # strictly lower-priority victim pays, equal priority
                # keeps the requester-pays rule (no innocent neighbor
                # truncated for a peer)
                if priority_rank(victim.priority) > priority_rank(req.priority):
                    self._force_finish_out_of_blocks(victim, finished)
                    continue
                self._force_finish_out_of_blocks(req, finished)
                return
            if victim is req:
                return  # req is queued for re-admission; lane goes idle

    def _dispatch_decode(
        self, decoding: list[Request], finished: list[Request]
    ) -> None:
        """Build this round's operands and hand the ONE compiled decode
        executable to the runtime — non-blocking: the results stay device
        futures in ``self._inflight`` until ``_harvest_inflight`` lands
        them (next iteration's harvest point in async mode, immediately
        after this returns in sync mode)."""
        cfg = self.config
        # pass 1 — capacity: grow every lane (evicting cached blocks,
        # preempting victims, truncating last-resort). A later lane's
        # preemption may take an *earlier* lane out of its slot, so lane
        # state is only materialised in pass 2, over the survivors.
        for req in decoding:
            if req.slot is None or req.state is not RequestState.DECODE:
                continue  # preempted or force-finished by an earlier lane
            self._ensure_decode_capacity(req, finished)
        pos0 = np.zeros((cfg.num_slots,), np.int32)
        active = np.zeros((cfg.num_slots, 1), bool)
        toks = np.zeros((cfg.num_slots, 1), np.int32)
        live: list[Request] = []
        for req in decoding:
            # a dispatch writes up to `_decode_lookahead` positions ahead
            # (capped at the request's own budget); lane-steps past the
            # budget scatter into the null block and are dropped host-side
            if req.slot is None or req.state is not RequestState.DECODE:
                continue
            self._sync_block_table(req)
            pos0[req.slot] = req.context_len
            toks[req.slot, 0] = self._pending_tok[req.slot]
            active[req.slot, 0] = True
            live.append(req)
        if not live:
            return

        # per-slot lanes: rebuilt from the live requests on EVERY dispatch
        # (pos/ring/DFA state re-derived from the request, so preemption,
        # swap, and slot reassignment can never desynchronise them); the
        # shapes/dtypes are engine geometry — one abstract signature forever.
        # When every live request is inert the cached device-resident blank
        # dict stands in — the traced lax.cond argmaxes without reading a
        # single lane value, so stale contents cannot matter
        lanes = None
        if self._psampling:
            if all(
                (req.sampling or self._default_sampling).inert
                and not req.grammar_row
                for req in live
            ):
                lanes = self._idle_lanes()
            else:
                lanes = blank_lanes(cfg.num_slots, cfg.rep_window)
                for req in live:
                    params = req.sampling or self._default_sampling
                    set_slot_lane(
                        lanes, req.slot, params,
                        pos=len(req.output_tokens),
                        grammar_row=req.grammar_row, dfa_state=req.dfa_state,
                        recent=(
                            req.prompt + req.output_tokens
                            if params.repetition_penalty != 1.0
                            else ()
                        ),
                    )

        # signature capture costs ~8 shape/dtype formats per dispatch, so it
        # rides the same armed-instrumentation gate as every other hot-path
        # site (one global read each when disabled); the retrace *counter*
        # check below stays unconditional — it is just two int compares
        decode_sig = None
        if _get_sanitizer() or get_active_recorder():
            args = [
                ("kp", self._kp), ("vp", self._vp),
                ("block_tables", self._block_tables), ("pos0", pos0),
                ("toks", toks), ("active", active),
            ]
            if self._psampling:
                args += sorted(lanes.items())
                args += [
                    ("gmask", self._gmask), ("gtrans", self._gtrans),
                    ("base_key", self._base_key),
                ]
            elif self._spec is None:  # legacy spec round is greedy: no key/temp
                args += [("key", self._key), ("temp", self._temp)]
            if self._quantized:
                args[2:2] = [("ks", self._ks), ("vs", self._vs)]
            decode_sig = tuple(
                (name, tuple(np.shape(v)), str(getattr(v, "dtype", type(v).__name__)))
                for name, v in args
            )

        if self._spec is not None:
            self._spec_decode_dispatch(pos0, toks, active, lanes, live, decode_sig)
            return
        logps = tvals = tids = None
        if self._psampling:
            lane_args = (lanes, self._gmask, self._gtrans, self._base_key)
            if self._quantized:
                (self._kp, self._vp, self._ks, self._vs, next_toks,
                 logps, tvals, tids) = self._decode_fn(
                    self._params, self._kp, self._vp, self._ks, self._vs,
                    self._block_tables, pos0, toks, active, *lane_args,
                )
            else:
                (self._kp, self._vp, next_toks, logps, tvals,
                 tids) = self._decode_fn(
                    self._params, self._kp, self._vp, self._block_tables,
                    pos0, toks, active, *lane_args,
                )
        elif self._quantized:
            (self._kp, self._vp, self._ks, self._vs, next_toks,
             self._key) = self._decode_fn(
                self._params, self._kp, self._vp, self._ks, self._vs,
                self._block_tables, pos0, toks, active, self._key, self._temp,
            )
        else:
            self._kp, self._vp, next_toks, self._key = self._decode_fn(
                self._params, self._kp, self._vp, self._block_tables, pos0, toks,
                active, self._key, self._temp,
            )
        self._check_one_executable(decode_sig)
        if self._tr is not None:
            # request identity on the decode timeline WITHOUT per-token
            # spans: one instant per dispatch carries the whole slot batch
            self._tr.instant(
                "serve/decode_batch", slots=len(live),
                burst=cfg.decode_burst,
                trace_ids=[r.trace_id for r in live],
            )
        harvest_lp = cfg.logprobs_topn > 0 and any(
            r.sampling is not None and r.sampling.logprobs for r in live
        )
        self._inflight = _InFlightRound(
            kind="burst", live=live, toks=next_toks, logps=logps,
            tvals=tvals, tids=tids, harvest_lp=harvest_lp,
        )

    def _spec_decode_dispatch(
        self, pos0, toks, active, lanes, live: list[Request],
        decode_sig: tuple | None,
    ) -> None:
        """One speculative round: dispatch the single compiled
        draft+verify executable; ``_harvest_inflight`` later emits each
        live slot's accepted prefix + correction through the SAME
        host-side ``_emit_token`` path the plain engine uses (eos and
        length budgets are host state, so greedy parity with the non-spec
        engine is inherited, not re-implemented). Rollback is implicit: a
        slot advances by ``accept+1`` positions; the rejected rows beyond
        that are re-scattered by the next round before anything can
        attend them."""
        lane_args = (
            (lanes, self._gmask, self._gtrans, self._base_key)
            if self._psampling
            else ()
        )
        if self._quantized:
            (self._kp, self._vp, self._ks, self._vs, tok_seq,
             accept) = self._decode_fn(
                self._params, self._kp, self._vp, self._ks, self._vs,
                self._block_tables, pos0, toks, active, *lane_args,
            )
        else:
            self._kp, self._vp, tok_seq, accept = self._decode_fn(
                self._params, self._kp, self._vp, self._block_tables,
                pos0, toks, active, *lane_args,
            )
        self._check_one_executable(decode_sig)
        # the round's [num_slots, k+1] token matrix and [num_slots]
        # accepted-prefix vector stay device futures; the serve/spec_round
        # instant needs the accept values, so it moves to the harvest
        self._inflight = _InFlightRound(
            kind="spec", live=live, toks=tok_seq, accept=accept
        )

    def _check_one_executable(self, decode_sig: tuple | None) -> None:
        """ONE compiled decode executable is the engine's core contract.
        When the trace counter moves past 1, diff the dispatch's abstract
        signature against the first trace's and put the named argument in
        the failure message — "decode re-traced" alone sends the operator
        bisecting; "block_tables went (8, 32):int32 -> (8, 64):int32" names
        the bug. ``decode_sig`` is None when no instrumentation is armed
        (the counter still catches the retrace, just without arg naming).
        Armed sanitizer ⇒ raise; otherwise record + surface via
        ``stats()['retrace_report']`` and telemetry."""
        traced_now = self._decode_traces != self._decode_traces_seen
        self._decode_traces_seen = self._decode_traces
        if not traced_now or self._decode_traces <= 1:
            self._decode_sig = decode_sig
            return
        if self._decode_sig is not None and decode_sig is not None:
            from ..analysis.compiled import diff_signatures, format_signature_diff

            diff = diff_signatures(self._decode_sig, decode_sig)
            detail = (
                format_signature_diff(diff)
                if diff is not None
                else "abstract signature unchanged (params/pages identity drift?)"
            )
        else:
            detail = (
                "fingerprint not captured — enable sanitizer or telemetry "
                "for argument naming"
            )
        self._decode_sig = decode_sig
        message = (
            f"serving engine decode re-traced (compile #{self._decode_traces}; "
            f"the one-compiled-executable contract is broken) — fingerprint "
            f"diff vs previous dispatch: {detail}"
        )
        self.retrace_report = message
        tel = get_active_recorder()
        if tel:
            tel.record_event("serving_retrace", message=message)
        if _get_sanitizer():
            raise RuntimeError(message)

    def _first_token_pick(self, req: Request, logits):
        """Per-slot first-token pick from the prompt-final logits the
        prefill executable already returns: one ``[1, vocab]`` run of the
        shared :func:`sampling.pick_tokens` at output position 0 — exact
        key parity with the decode lanes, so a preempted-and-restarted
        request reproduces its first token too."""
        params = req.sampling or self._default_sampling
        lanes = blank_lanes(1, self.config.rep_window)
        set_slot_lane(
            lanes, 0, params, pos=0, grammar_row=req.grammar_row,
            dfa_state=req.dfa_state,
            recent=req.prompt if params.repetition_penalty != 1.0 else (),
        )
        tok, logp, tvals, tids = self._first_pick_fn(
            logits[None], lanes, self._gmask, self._base_key
        )
        entry = None
        if params.logprobs:
            entry = self._logprob_entry(
                params, float(logp[0]), np.asarray(tvals[0]), np.asarray(tids[0])
            )
        return int(tok[0]), entry

    @staticmethod
    def _logprob_entry(params, logp: float, top_vals, top_ids) -> dict:
        n = int(params.logprobs)
        return {
            "logprob": logp,
            "top": [
                [int(i), float(v)]
                for i, v in zip(top_ids[:n], top_vals[:n])
            ],
        }

    # -- grammar row lifecycle ------------------------------------------------

    def _acquire_grammar_row(self, g) -> int:
        """Pin one row of the device-resident grammar tables for a live
        request. Rows are refcounted by grammar hash — concurrent requests
        with the same schema share one row (and one upload). A fully-idle
        row keeps its compiled tables cached LRU-style, so the common
        serve pattern (many requests, few schemas) uploads each grammar
        once; eviction only happens when a NEW grammar needs a row and
        every free row is someone's cache entry. Runs at admission, so
        exhaustion (every row pinned by a live request) fails the
        add_request loudly instead of wedging a slot mid-decode."""
        row = self._grammar_rows.get(g.hash)
        if row is not None:
            self._row_lru.pop(g.hash, None)
            self._row_refs[row] += 1
            return row
        row = next(
            (
                r
                for r in range(1, self.config.grammar_slots + 1)
                if self._row_refs[r] == 0 and r not in self._row_grammar
            ),
            None,
        )
        if row is None:
            if not self._row_lru:
                raise ValueError(
                    f"all {self.config.grammar_slots} grammar rows are held by "
                    "live requests; raise EngineConfig.grammar_slots or retry "
                    "after a constrained request finishes"
                )
            old_hash, row = self._row_lru.popitem(last=False)
            del self._grammar_rows[old_hash]
        allow, trans = g.padded_tables(self.config.grammar_states)
        self._gmask = self._write_grammar_row_fn(
            self._gmask, jnp.int32(row), jnp.asarray(allow)
        )
        self._gtrans = self._write_grammar_row_fn(
            self._gtrans, jnp.int32(row), jnp.asarray(trans)
        )
        self._grammar_rows[g.hash] = row
        self._row_grammar[row] = g
        self._row_refs[row] += 1
        return row

    def _release_grammar_row(self, req: Request) -> None:
        row = req.grammar_row
        if not row:
            return
        req.grammar_row = 0
        self._row_refs[row] -= 1
        if self._row_refs[row] == 0:
            # idle: keep the uploaded tables as an LRU cache entry so the
            # next request with this schema skips the host→device write
            self._row_lru[self._row_grammar[row].hash] = row

    def _emit_token(
        self, req: Request, tok: int, finished: list[Request], lp_entry=None
    ) -> None:
        now = time.perf_counter()
        req.output_tokens.append(tok)
        self._pending_tok[req.slot] = tok
        self._tokens_emitted += 1
        params = req.sampling
        if self._psampling:
            if params is not None and params.do_sample:
                self._sampled_sample += 1
            else:
                self._sampled_greedy += 1
        if lp_entry is not None:
            lp_entry["token"] = tok
            if req.logprobs is None:
                req.logprobs = []
            req.logprobs.append(lp_entry)
        if req.first_token_time is None:
            req.first_token_time = now
            if self._tr is not None:
                self._tr.request_instant(
                    req.trace_id, "req/first_token", ts=now,
                    ttft_s=now - req.arrival_time,
                )
        eos = self.config.eos_token_id
        if eos is not None and tok == eos:
            req.finish_reason = "eos"
        elif len(req.output_tokens) >= req.max_new_tokens:
            req.finish_reason = "length"
        if req.grammar_row:
            # advance the AUTHORITATIVE automaton state host-side (the
            # in-trace advance only fed mid-burst masking); entering a
            # state with no live continuation means the match is complete
            self._grammar_masked_steps += 1
            if self.usage is not None:
                self.usage.accrue_grammar(req)
            g = self._row_grammar[req.grammar_row]
            req.dfa_state = g.advance(req.dfa_state, tok)
            if req.finish_reason is None and g.final[req.dfa_state]:
                req.finish_reason = "stop"
        if (
            req.finish_reason is None
            and params is not None
            and params.stop
        ):
            n = match_stop(req.output_tokens, params.stop)
            if n:
                # the matched stop sequence is not part of the answer
                del req.output_tokens[-n:]
                req.finish_reason = "stop"
        if req.finish_reason is not None:
            req.finish_time = now
            req.state = RequestState.FINISHED
            finished.append(req)

    # -- observability -------------------------------------------------------

    def _emit_telemetry(self, finished: list[Request]) -> None:
        tel = get_active_recorder()
        if not tel:
            return
        for req in finished:
            tel.record_serving(
                kind="request",
                request_id=req.request_id,
                trace_id=req.trace_id,
                priority=req.priority,
                tenant=req.tenant,
                prompt_tokens=req.prompt_len,
                new_tokens=len(req.output_tokens),
                ttft_s=req.ttft_s,
                tpot_s=req.tpot_s,
                finish_reason=req.finish_reason,
                # device_time_s / kv_block_seconds / swap_bytes — the
                # closed per-request account (absent on a no-ledger engine)
                **(req.usage or {}),
            )
        interval = self.config.stats_interval
        if interval and self._iterations % interval == 0:
            now = time.perf_counter()
            window_s = now - (self._last_stats_t or now)
            window_tokens = self._tokens_emitted - self._last_stats_tokens
            self._last_stats_t, self._last_stats_tokens = now, self._tokens_emitted
            sched = self.scheduler
            tel.record_serving(
                kind="step",
                iteration=self._iterations,
                tokens_per_sec=(window_tokens / window_s) if window_s > 0 else None,
                queue_depth=sched.queue_depth,
                active_slots=len(sched.active()),
                slot_occupancy=sched.occupancy,
                free_blocks=self.allocator.free_count,
                kv_dtype=self.kv_dtype,
                kv_bytes_per_token=self.kv_bytes_per_token,
                kv_slot_capacity=self.kv_slot_capacity,
                decode_compiles=self._decode_traces,
                # cumulative totals: the monitor reads a bounded JSONL tail,
                # so run-total counts must ride every row, not be re-counted
                completed_total=self._completed_total,
                tokens_total=self._tokens_emitted,
                prefix_hit_tokens=sched.prefix_hit_tokens,
                prefix_hit_ratio=(
                    sched.prefix_hit_tokens / sched.prompt_tokens_admitted
                    if sched.prompt_tokens_admitted
                    else 0.0
                ),
                preemptions=self._preemptions,
                swapped_out_blocks=self._swapped_out_blocks,
                swapped_in_blocks=self._swapped_in_blocks,
                out_of_blocks_total=self._out_of_blocks_total,
                deadline_expired_total=self._deadline_expired,
                **self._spec_stats(),
                **self._sampling_stats(),
                **self._hbm_watermarks(),
                **(
                    self._flight.telemetry_fields()
                    if self._flight is not None
                    else {}
                ),
                **(
                    {"usage": self.usage.snapshot()}
                    if self.usage is not None
                    else {}
                ),
            )
