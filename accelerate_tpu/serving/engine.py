"""Continuous-batching inference engine: one compiled decode step, forever.

The static-batch ``generate()`` path compiles a prefill + decode program per
call and every request in the batch waits for the slowest one. This engine
inverts the design for serving (Orca-style iteration scheduling over a
vLLM-style block-paged cache):

* the decode step is **one** pjit-compiled program of static shape
  ``[num_slots, 1]`` against a block-paged KV pool — admitting, evicting,
  or resizing requests never recompiles (asserted by ``stats()``'s
  ``decode_compiles`` counter, which increments only when JAX re-traces);
* prompts are **chunk-prefilled**: ``prefill_chunk`` tokens of one prompt
  per engine iteration, interleaved with the decode step, so a long prompt
  bounds every in-flight request's inter-token latency by one chunk's
  forward instead of a whole prefill;
* KV memory is allocated in ``block_size``-token blocks from a freelist
  (:mod:`.blocks`) — padding waste is bounded by block granularity, and a
  finished short completion's blocks are serving a new request on the next
  iteration.

Sampling/eos semantics reuse ``generation.py``'s traced pick helper
(:func:`accelerate_tpu.generation._pick_traced`), so greedy engine output
is token-for-token identical to ``generate(use_cache=True)``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.sanitizer import get_active_sanitizer as _get_sanitizer
from ..diagnostics.tracing import trace_span
from ..generation import _pick_traced
from ..telemetry import get_active_recorder
from .blocks import BlockAllocator, blocks_needed
from .scheduler import Request, RequestState, SlotScheduler


@dataclass
class EngineConfig:
    """Engine geometry. ``num_blocks`` defaults to full residency
    (``num_slots`` × the per-slot maximum + the null block) — set it lower
    to exercise freelist contention."""

    num_slots: int = 8
    block_size: int = 16
    #: per-request cap on prompt + generated tokens; also sizes the block
    #: table width (``ceil(max_seq_len / block_size)`` entries per slot)
    max_seq_len: int = 512
    num_blocks: int | None = None
    prefill_chunk: int = 32
    eos_token_id: int | None = None
    do_sample: bool = False
    temperature: float = 1.0
    seed: int = 0
    #: default budget for add_request(max_new_tokens=None)
    max_new_tokens: int = 64
    #: decode steps per dispatch of the (single) compiled decode program —
    #: a ``lax.scan`` of this many ``[num_slots, 1]`` steps. Amortises the
    #: per-dispatch host round trip (the same move generation.py's
    #: ``_EOS_CHUNK`` makes) at the cost of scheduling granularity:
    #: admission/prefill interleave every ``decode_burst`` tokens, and a
    #: request finishing mid-burst wastes at most ``decode_burst - 1``
    #: lane-steps. 1 = schedule every token.
    decode_burst: int = 8
    #: emit a telemetry "serving" row every N iterations (0 disables)
    stats_interval: int = 32
    #: per-device HBM budget in GiB; when set, the engine runs the
    #: shard-check pre-flight BEFORE allocating anything and refuses to
    #: start (ValueError naming SP004) if params + the paged pools exceed
    #: it — the capacity-planning contract: fail at bring-up, not OOM
    #: mid-request
    hbm_budget_gb: float | None = None

    @property
    def blocks_per_slot(self) -> int:
        return blocks_needed(self.max_seq_len, self.block_size)


class InferenceEngine:
    """Slot-scheduled continuous-batching engine over a paged-KV model.

    ``add_request()`` enqueues; ``step()`` runs one scheduler iteration
    (evict → admit → one prefill chunk → one decode step) and returns the
    requests that finished; ``run_until_idle()`` drains; ``stream()`` is a
    per-request generator. The model must declare ``supports_paged_kv``
    (the block-table decode path in its apply fn).

    ``mesh=`` shards the ONE decode executable over the named mesh with
    GSPMD ``NamedSharding`` rules (the same planner training uses): params
    by the model's partition rules + FSDP policy, the paged block pool by
    kv-head over ``tp``, scheduler state replicated. Host-side scheduling
    is untouched — sharding is a placement decision, never a different
    program, so greedy output stays token-identical to the single-device
    engine and the one-executable contract keeps holding."""

    def __init__(self, model, config: EngineConfig | None = None, mesh=None):
        self.config = cfg = config or EngineConfig()
        inner = getattr(model, "_model", None) or model
        if not getattr(inner, "supports_paged_kv", False):
            raise ValueError(
                f"model {getattr(inner, 'name', type(inner).__name__)!r} does not "
                "declare supports_paged_kv: the engine needs the block-table "
                "KV decode path (models/llama.py _llama_paged_step)"
            )
        self._apply_fn = inner.apply_fn
        self._params = model.params
        mcfg = inner.config
        if cfg.max_seq_len > mcfg.max_position_embeddings:
            raise ValueError(
                f"max_seq_len {cfg.max_seq_len} exceeds the model's "
                f"max_position_embeddings {mcfg.max_position_embeddings}"
            )
        if min(cfg.prefill_chunk, cfg.block_size, cfg.num_slots, cfg.decode_burst) < 1:
            raise ValueError(
                "prefill_chunk, block_size, num_slots, decode_burst must be >= 1"
            )

        self._mb = cfg.blocks_per_slot  # block-table width
        # explicit is-None test: an explicit num_blocks=0 must reach the
        # allocator's >= 2 guard, not be silently rewritten to full residency
        num_blocks = (
            cfg.num_blocks if cfg.num_blocks is not None
            else cfg.num_slots * self._mb + 1
        )

        # device state: per-layer page pools in the params' compute dtype
        n_kv = getattr(mcfg, "num_key_value_heads", None) or mcfg.num_attention_heads
        embed = jax.tree.leaves(self._params)[0]
        dtype = embed.dtype if jnp.issubdtype(embed.dtype, jnp.floating) else jnp.float32
        shape = (mcfg.num_hidden_layers, num_blocks, cfg.block_size, n_kv, mcfg.head_dim)
        self.hbm_preflight: dict | None = None
        if cfg.hbm_budget_gb is not None:
            self._hbm_preflight(inner, shape, dtype, mesh)

        self.allocator = BlockAllocator(num_blocks)
        self.scheduler = SlotScheduler(
            cfg.num_slots, self.allocator, cfg.block_size, cfg.max_seq_len
        )
        self._kp = jnp.zeros(shape, dtype)
        self._vp = jnp.zeros(shape, dtype)
        self._key = jax.random.PRNGKey(cfg.seed)
        self._temp = jnp.float32(cfg.temperature)
        self.mesh = mesh
        if mesh is not None:
            self._place_on_mesh(inner)

        # host mirrors the compiled step reads every iteration
        self._block_tables = np.zeros((cfg.num_slots, self._mb), np.int32)
        self._pending_tok = np.zeros((cfg.num_slots,), np.int32)

        # counters (the *_traces counters increment inside the traced
        # bodies, i.e. only on a jit cache miss — the "exactly one decode
        # executable" acceptance bar reads decode_compiles)
        self._decode_traces = 0
        self._prefill_traces = 0
        # one-executable watchdog state: the abstract signature of every
        # decode dispatch, so a second trace can NAME the argument whose
        # shape/dtype drifted (analysis/compiled.py fingerprint diff) —
        # with the sanitizer armed the re-trace raises immediately
        self._decode_sig: tuple | None = None
        self._decode_traces_seen = 0
        self.retrace_report: str | None = None
        self._iterations = 0
        self._tokens_emitted = 0
        self._occupancy_sum = 0.0
        self._start_time: float | None = None
        self._completed: list[Request] = []
        self._last_stats_t: float | None = None
        self._last_stats_tokens = 0

        self._decode_fn = self._build_decode_fn()
        self._prefill_fn = self._build_prefill_fn()

    def _place_on_mesh(self, inner) -> None:
        """GSPMD placement over ``self.mesh``: every device-side input to
        the compiled step gets an explicit ``NamedSharding`` so the first
        dispatch compiles the sharded program and every later dispatch
        reuses it (donated pool buffers keep their sharding, so the
        signature — avals + shardings — never drifts). Host mirrors
        (block tables, positions, tokens) stay plain numpy: they are
        uncommitted inputs GSPMD replicates for free."""
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.sharding import (
            infer_param_sharding,
            paged_kv_sharding,
            shard_params,
        )
        from ..utils.dataclasses import FullyShardedDataParallelPlugin

        mesh = self.mesh
        rules = getattr(inner, "partition_rules", None)
        shardings = infer_param_sharding(
            self._params, mesh, FullyShardedDataParallelPlugin(), rules
        )
        self._params = shard_params(self._params, shardings)
        pool_sharding = paged_kv_sharding(mesh, self._kp.shape[3])
        self._kp = jax.device_put(self._kp, pool_sharding)
        self._vp = jax.device_put(self._vp, pool_sharding)
        # scheduler-adjacent scalars must live on the SAME device set as the
        # sharded params — a single-device-committed leaf among mesh-committed
        # ones is an incompatible-devices error at dispatch
        rep = NamedSharding(mesh, PartitionSpec())
        self._key = jax.device_put(self._key, rep)
        self._temp = jax.device_put(self._temp, rep)

    def _hbm_preflight(self, inner, pool_shape, pool_dtype, mesh) -> None:
        """shard-check's SP004 at the serving seam: predicted per-device
        bytes of params (under the placement ``_place_on_mesh`` would pick)
        plus both paged pools, refused against ``hbm_budget_gb`` BEFORE a
        single buffer allocates."""
        from ..analysis.shardplan import engine_preflight

        report = engine_preflight(
            self._params,
            getattr(inner, "partition_rules", None),
            mesh,
            pool_shape,
            pool_dtype,
            self.config.hbm_budget_gb,
        )
        self.hbm_preflight = report
        if report["over"]:
            gib = 1 << 30
            raise ValueError(
                f"SP004: engine refuses to start — predicted "
                f"{report['total_bytes'] / gib:.3f} GiB/device "
                f"(params {report['params_bytes'] / gib:.3f} + "
                f"kv pools {report['pool_bytes'] / gib:.3f}) exceeds the "
                f"{self.config.hbm_budget_gb:.3f} GiB budget. Lower "
                f"num_blocks/max_seq_len (or use serve --auto-blocks), shard "
                f"over a larger mesh, or raise the budget"
            )

    # -- compiled programs ---------------------------------------------------

    def _build_decode_fn(self):
        apply_fn, cfg = self._apply_fn, self.config

        def decode(params, kp, vp, block_tables, pos0, toks, active, key, temp):
            self._decode_traces += 1  # traced-body side effect: cache misses only

            def one_step(carry, _):
                kp, vp, toks, pos, key = carry
                out = apply_fn(
                    params,
                    input_ids=toks,
                    paged_kv={"k": kp, "v": vp},
                    block_tables=block_tables,
                    cache_positions=pos,
                    paged_write_mask=active,  # PREFILL/free lanes must not scribble
                )
                logits = out["logits"][:, -1, :]
                tok, key, _ = _pick_traced(
                    logits, key, jnp.zeros(logits.shape[:1], bool), jnp.int32(0),
                    temp, cfg.do_sample, has_eos=False,  # eos is host-side state
                )
                pages = out["paged_kv"]
                return (pages["k"], pages["v"], tok[:, None], pos + 1, key), tok

            (kp, vp, _, _, key), toks_out = jax.lax.scan(
                one_step, (kp, vp, toks, pos0, key), None, length=cfg.decode_burst
            )
            return kp, vp, toks_out, key  # toks_out: [decode_burst, num_slots]

        return jax.jit(decode, donate_argnums=(1, 2))

    def _build_prefill_fn(self):
        apply_fn, cfg = self._apply_fn, self.config

        def prefill(params, kp, vp, block_table, start, chunk, valid, last_idx, key, temp):
            self._prefill_traces += 1
            out = apply_fn(
                params,
                input_ids=chunk,  # [1, prefill_chunk]
                paged_kv={"k": kp, "v": vp},
                block_tables=block_table,  # [1, mb]
                cache_positions=start,  # [1]
                paged_write_mask=valid,  # drops the padded tail
            )
            # first-token pick from the prompt's last real position — only
            # meaningful on the final chunk; the host ignores it otherwise
            logits = jnp.take(out["logits"][0], last_idx, axis=0)[None]
            tok, key, _ = _pick_traced(
                logits, key, jnp.zeros((1,), bool), jnp.int32(0),
                temp, cfg.do_sample, has_eos=False,
            )
            pages = out["paged_kv"]
            return pages["k"], pages["v"], tok[0], logits[0], key

        return jax.jit(prefill, donate_argnums=(1, 2))

    # -- public API ----------------------------------------------------------

    def add_request(
        self,
        prompt,
        max_new_tokens: int | None = None,
        arrival_time: float | None = None,
    ) -> Request:
        req = Request(
            prompt=[int(t) for t in np.asarray(prompt).reshape(-1)],
            max_new_tokens=int(
                self.config.max_new_tokens if max_new_tokens is None else max_new_tokens
            ),
        )
        if arrival_time is not None:
            req.arrival_time = arrival_time
        return self.scheduler.submit(req)

    def step(self) -> list[Request]:
        """One engine iteration: evict finished → admit queued → one
        prefill chunk → one decode step over every slot. Returns requests
        that finished during this iteration."""
        if self._start_time is None:
            self._start_time = self._last_stats_t = time.perf_counter()
        sched = self.scheduler
        finished: list[Request] = []

        with trace_span("serve/schedule"):
            sched.evict_finished()
            sched.admit()

        with trace_span("serve/prefill"):
            # one chunk per PREFILLING SLOT per iteration: slot turnover is
            # never throttled to one admission per decode burst, while any
            # single prompt still advances at most one chunk between decode
            # steps — the TTFT/stall bound chunked prefill exists for
            for req in sched.active(RequestState.PREFILL):
                self._prefill_one_chunk(req, finished)

        decoding = sched.active(RequestState.DECODE)
        if decoding:
            with trace_span("serve/decode", slots=len(decoding)):
                self._decode_once(decoding, finished)

        self._iterations += 1
        self._occupancy_sum += sched.occupancy
        self._completed.extend(finished)
        self._emit_telemetry(finished)
        return finished

    def run_until_idle(self, max_iterations: int | None = None) -> list[Request]:
        """Drain queue + slots; returns every request finished during the
        drain (scheduling-bug guard: ``max_iterations`` bounds the loop)."""
        done: list[Request] = []
        it = 0
        while self.scheduler.has_work():
            done.extend(self.step())
            it += 1
            if max_iterations is not None and it >= max_iterations:
                raise RuntimeError(f"engine not idle after {it} iterations")
        return done

    def stream(self, prompt, max_new_tokens: int | None = None):
        """Generator yielding this request's tokens as the engine emits
        them (other in-flight requests keep decoding underneath)."""
        req = self.add_request(prompt, max_new_tokens)
        served = 0
        while req.state is not RequestState.FINISHED:
            self.step()
            while served < len(req.output_tokens):
                yield req.output_tokens[served]
                served += 1
        while served < len(req.output_tokens):
            yield req.output_tokens[served]
            served += 1

    def reset_stats(self) -> None:
        """Zero the measurement state (iterations, tokens, occupancy,
        completed-request percentiles, wall clock) while keeping the
        compiled programs, pages, and compile counters — so a bench can
        warm up and then measure without the warmup's idle-engine TTFT and
        low-occupancy drain iterations biasing the reported percentiles."""
        self._iterations = 0
        self._tokens_emitted = 0
        self._occupancy_sum = 0.0
        self._start_time = None
        self._completed = []
        self._last_stats_t = None
        self._last_stats_tokens = 0

    def stats(self) -> dict:
        """Aggregate serving health: goodput, TTFT/TPOT percentiles over
        completed requests, mean slot occupancy, and the compile counters
        the one-executable contract is asserted against."""
        out = {
            "iterations": self._iterations,
            "completed": len(self._completed),
            "queue_depth": self.scheduler.queue_depth,
            "active_slots": len(self.scheduler.active()),
            "num_slots": self.config.num_slots,
            "tokens_emitted": self._tokens_emitted,
            "decode_compiles": self._decode_traces,
            "prefill_compiles": self._prefill_traces,
            "free_blocks": self.allocator.free_count,
            "allocated_blocks": self.allocator.allocated_count,
            "slot_occupancy_mean": (
                self._occupancy_sum / self._iterations if self._iterations else 0.0
            ),
        }
        if self.mesh is not None:
            from ..mesh import mesh_axis_sizes

            out["mesh"] = mesh_axis_sizes(self.mesh)
        if self.retrace_report is not None:
            out["retrace_report"] = self.retrace_report
        if self.hbm_preflight is not None:
            out["hbm_preflight"] = self.hbm_preflight
        if self._start_time is not None:
            elapsed = time.perf_counter() - self._start_time
            out["elapsed_s"] = elapsed
            out["tokens_per_sec"] = self._tokens_emitted / elapsed if elapsed > 0 else 0.0
        ttfts = [r.ttft_s for r in self._completed if r.ttft_s is not None]
        tpots = [r.tpot_s for r in self._completed if r.tpot_s is not None]
        if ttfts:
            out["ttft_s"] = {
                "p50": float(np.percentile(ttfts, 50)),
                "p99": float(np.percentile(ttfts, 99)),
            }
        if tpots:
            out["tpot_s"] = {
                "p50": float(np.percentile(tpots, 50)),
                "p99": float(np.percentile(tpots, 99)),
            }
        return out

    # -- iteration internals -------------------------------------------------

    def _sync_block_table(self, req: Request) -> None:
        row = self._block_tables[req.slot]
        row[:] = 0
        row[: len(req.blocks)] = req.blocks

    def _prefill_one_chunk(self, req: Request, finished: list[Request]) -> None:
        cfg = self.config
        c = cfg.prefill_chunk
        start = req.prefill_pos
        end = min(start + c, req.prompt_len)
        chunk = np.zeros((1, c), np.int32)
        chunk[0, : end - start] = req.prompt[start:end]
        valid = np.zeros((1, c), bool)
        valid[0, : end - start] = True
        self._sync_block_table(req)
        is_final = end == req.prompt_len
        last_idx = np.int32((req.prompt_len - 1) - start if is_final else 0)

        self._kp, self._vp, tok, _logits, self._key = self._prefill_fn(
            self._params, self._kp, self._vp,
            self._block_tables[req.slot : req.slot + 1],
            np.asarray([start], np.int32), chunk, valid, last_idx,
            self._key, self._temp,
        )
        req.prefill_pos = end
        if is_final:
            self._emit_token(req, int(tok), finished)
            if req.state is not RequestState.FINISHED:
                req.state = RequestState.DECODE

    def _decode_once(self, decoding: list[Request], finished: list[Request]) -> None:
        cfg = self.config
        burst = cfg.decode_burst
        pos0 = np.zeros((cfg.num_slots,), np.int32)
        active = np.zeros((cfg.num_slots, 1), bool)
        toks = np.zeros((cfg.num_slots, 1), np.int32)
        live: list[Request] = []
        for req in decoding:
            # the burst writes up to `burst` positions ahead (capped at the
            # request's own budget); lane-steps past the budget scatter into
            # the null block and are dropped host-side
            if not self.scheduler.grow_for_decode(req, tokens_ahead=burst):
                req.finish_reason = "out_of_blocks"
                req.finish_time = time.perf_counter()
                req.state = RequestState.FINISHED
                finished.append(req)
                continue
            self._sync_block_table(req)
            pos0[req.slot] = req.context_len
            toks[req.slot, 0] = self._pending_tok[req.slot]
            active[req.slot, 0] = True
            live.append(req)
        if not live:
            return

        # signature capture costs ~8 shape/dtype formats per dispatch, so it
        # rides the same armed-instrumentation gate as every other hot-path
        # site (one global read each when disabled); the retrace *counter*
        # check below stays unconditional — it is just two int compares
        decode_sig = None
        if _get_sanitizer() or get_active_recorder():
            decode_sig = tuple(
                (name, tuple(np.shape(v)), str(getattr(v, "dtype", type(v).__name__)))
                for name, v in (
                    ("kp", self._kp), ("vp", self._vp),
                    ("block_tables", self._block_tables), ("pos0", pos0),
                    ("toks", toks), ("active", active), ("key", self._key),
                    ("temp", self._temp),
                )
            )
        self._kp, self._vp, next_toks, self._key = self._decode_fn(
            self._params, self._kp, self._vp, self._block_tables, pos0, toks,
            active, self._key, self._temp,
        )
        self._check_one_executable(decode_sig)
        next_toks = np.asarray(jax.device_get(next_toks))  # [burst, num_slots]
        for req in live:
            for t in range(burst):
                if req.state is RequestState.FINISHED:
                    break  # mid-burst eos/length: the tail lane-steps are waste
                self._emit_token(req, int(next_toks[t, req.slot]), finished)

    def _check_one_executable(self, decode_sig: tuple | None) -> None:
        """ONE compiled decode executable is the engine's core contract.
        When the trace counter moves past 1, diff the dispatch's abstract
        signature against the first trace's and put the named argument in
        the failure message — "decode re-traced" alone sends the operator
        bisecting; "block_tables went (8, 32):int32 -> (8, 64):int32" names
        the bug. ``decode_sig`` is None when no instrumentation is armed
        (the counter still catches the retrace, just without arg naming).
        Armed sanitizer ⇒ raise; otherwise record + surface via
        ``stats()['retrace_report']`` and telemetry."""
        traced_now = self._decode_traces != self._decode_traces_seen
        self._decode_traces_seen = self._decode_traces
        if not traced_now or self._decode_traces <= 1:
            self._decode_sig = decode_sig
            return
        if self._decode_sig is not None and decode_sig is not None:
            from ..analysis.compiled import diff_signatures, format_signature_diff

            diff = diff_signatures(self._decode_sig, decode_sig)
            detail = (
                format_signature_diff(diff)
                if diff is not None
                else "abstract signature unchanged (params/pages identity drift?)"
            )
        else:
            detail = (
                "fingerprint not captured — enable sanitizer or telemetry "
                "for argument naming"
            )
        self._decode_sig = decode_sig
        message = (
            f"serving engine decode re-traced (compile #{self._decode_traces}; "
            f"the one-compiled-executable contract is broken) — fingerprint "
            f"diff vs previous dispatch: {detail}"
        )
        self.retrace_report = message
        tel = get_active_recorder()
        if tel:
            tel.record_event("serving_retrace", message=message)
        if _get_sanitizer():
            raise RuntimeError(message)

    def _emit_token(self, req: Request, tok: int, finished: list[Request]) -> None:
        now = time.perf_counter()
        req.output_tokens.append(tok)
        self._pending_tok[req.slot] = tok
        self._tokens_emitted += 1
        if req.first_token_time is None:
            req.first_token_time = now
        eos = self.config.eos_token_id
        if eos is not None and tok == eos:
            req.finish_reason = "eos"
        elif len(req.output_tokens) >= req.max_new_tokens:
            req.finish_reason = "length"
        if req.finish_reason is not None:
            req.finish_time = now
            req.state = RequestState.FINISHED
            finished.append(req)

    # -- observability -------------------------------------------------------

    def _emit_telemetry(self, finished: list[Request]) -> None:
        tel = get_active_recorder()
        if not tel:
            return
        for req in finished:
            tel.record_serving(
                kind="request",
                request_id=req.request_id,
                prompt_tokens=req.prompt_len,
                new_tokens=len(req.output_tokens),
                ttft_s=req.ttft_s,
                tpot_s=req.tpot_s,
                finish_reason=req.finish_reason,
            )
        interval = self.config.stats_interval
        if interval and self._iterations % interval == 0:
            now = time.perf_counter()
            window_s = now - (self._last_stats_t or now)
            window_tokens = self._tokens_emitted - self._last_stats_tokens
            self._last_stats_t, self._last_stats_tokens = now, self._tokens_emitted
            tel.record_serving(
                kind="step",
                iteration=self._iterations,
                tokens_per_sec=(window_tokens / window_s) if window_s > 0 else None,
                queue_depth=self.scheduler.queue_depth,
                active_slots=len(self.scheduler.active()),
                slot_occupancy=self.scheduler.occupancy,
                free_blocks=self.allocator.free_count,
                decode_compiles=self._decode_traces,
                # cumulative totals: the monitor reads a bounded JSONL tail,
                # so run-total counts must ride every row, not be re-counted
                completed_total=len(self._completed),
                tokens_total=self._tokens_emitted,
            )
