"""OpenAI-compatible front door: ``/v1/completions`` + ``/v1/chat/completions``.

A pure translation layer between the OpenAI request/response shapes and the
engine's JSONL dialect — it owns NO sockets and NO engine: the host
(``serve --http`` or ``route --http``) hands it a ``submit(payload, cb)``
function and mounts :meth:`OpenAIFrontend.handle` on its POST paths. That
keeps the translation testable without a server and identical across the
single-engine and routed front ends.

Request mapping:

* ``temperature`` / ``top_p`` / ``seed`` / ``stop`` / ``logprobs`` /
  ``max_tokens`` → per-request :class:`~.sampling.SamplingParams` lanes
  (``temperature=0`` is greedy, like the OpenAI convention; the default
  ``temperature=1`` samples);
* ``response_format={"type": "json_schema", ...}`` → a :mod:`.grammar`
  constrained-decoding spec — every completion parses and validates;
* ``priority`` / ``deadline_ms`` / ``trace_id`` / ``tenant`` ride the
  vendor-prefixed extension fields ``x_accelerate_priority`` /
  ``x_accelerate_deadline_ms`` / ``x_accelerate_trace_id`` /
  ``x_accelerate_tenant``, so scheduling + tracing + usage-attribution
  machinery works through the standard surface (and the response carries
  an ``x_accelerate`` block with trace_id/ttft/tpot plus the request's
  measured costs — ``device_time_s``/``kv_block_seconds``/``swap_bytes``
  from the usage ledger);
* errors are OpenAI-shaped ``{"error": {message, type, param, code}}``
  objects with the right HTTP status.

Tokenization: the model zoo is byte-vocab (token id *i* is byte *i*), so
``prompt`` strings and chat messages encode as UTF-8 bytes and completions
decode the same way — token-id lists also pass straight through for
clients that pre-tokenize. The chat template is deliberately minimal
(``"role: content"`` lines + a trailing ``assistant:`` cue); this box
ships no tokenizer/template assets, and the golden tests pin the shape.

Streaming: ``stream=true`` answers Server-Sent Events. Behind ``serve``
the host wires a per-request delta callback (``streaming="delta"``) so
chunks flow as the engine emits tokens; behind ``route`` the replica
answers whole completions, so the front end replays the completion as one
chunk burst (``streaming="at_completion"``) — same framing, one
``data: [DONE]`` terminator, exactly-once either way.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid

__all__ = ["OpenAIError", "OpenAIFrontend", "OPENAI_PATHS"]

#: POST paths the front end answers — hosts route these to handle()
OPENAI_PATHS = ("/v1/completions", "/v1/chat/completions")

#: request fields we accept but deliberately do not implement; a value
#: other than the OpenAI default is an explicit 400, never silence
_UNSUPPORTED_NON_DEFAULT = (
    ("n", 1), ("best_of", 1), ("echo", False), ("suffix", None),
    ("presence_penalty", 0), ("frequency_penalty", 0), ("logit_bias", None),
    ("tools", None), ("tool_choice", None), ("parallel_tool_calls", None),
)

#: engine finish_reasons with an exact OpenAI equivalent; anything else
#: (deadline_exceeded, out_of_blocks, ...) maps to "length" and the raw
#: reason rides the vendor block
_FINISH_MAP = {"eos": "stop", "stop": "stop", "length": "length"}


class OpenAIError(Exception):
    """A request refusal carrying its OpenAI error object + HTTP status."""

    def __init__(self, message: str, status: int = 400,
                 type_: str = "invalid_request_error",
                 param: str | None = None, code: str | None = None):
        super().__init__(message)
        self.status = status
        self.body = {
            "error": {
                "message": message,
                "type": type_,
                "param": param,
                "code": code,
            }
        }


def encode_text(text: str) -> list[int]:
    """Byte-vocab tokenize: token id i is byte i (UTF-8)."""
    return list(text.encode("utf-8"))


def decode_tokens(tokens) -> str:
    return bytes(int(t) & 0xFF for t in tokens).decode("utf-8", "replace")


def chat_prompt(messages) -> str:
    """The minimal chat template (no template assets on this box): one
    ``role: content`` line per message + the assistant cue."""
    if not isinstance(messages, list) or not messages:
        raise OpenAIError("messages must be a non-empty array", param="messages")
    lines = []
    for i, m in enumerate(messages):
        if not isinstance(m, dict) or not isinstance(m.get("role"), str):
            raise OpenAIError(
                f"messages[{i}] must be an object with a string 'role'",
                param="messages",
            )
        content = m.get("content")
        if not isinstance(content, str):
            raise OpenAIError(
                f"messages[{i}].content must be a string", param="messages"
            )
        lines.append(f"{m['role']}: {content}")
    lines.append("assistant:")
    return "\n".join(lines)


def _num(body, key, lo, hi, default):
    v = body.get(key, default)
    if v is None:
        v = default
    try:
        v = float(v)
    except (TypeError, ValueError):
        raise OpenAIError(f"{key} must be a number", param=key) from None
    if not lo <= v <= hi:
        raise OpenAIError(f"{key} must be in [{lo}, {hi}]", param=key)
    return v


def _stop_sequences(stop) -> tuple:
    if stop is None:
        return ()
    if isinstance(stop, str):
        stop = [stop]
    if not isinstance(stop, list) or len(stop) > 4:
        raise OpenAIError("stop must be a string or up to 4 strings", param="stop")
    out = []
    for s in stop:
        if not isinstance(s, str) or not s:
            raise OpenAIError("stop entries must be non-empty strings", param="stop")
        out.append(tuple(encode_text(s)))
    return tuple(out)


def _response_format_grammar(body) -> dict | None:
    rf = body.get("response_format")
    if rf is None:
        return None
    if not isinstance(rf, dict) or "type" not in rf:
        raise OpenAIError(
            "response_format must be an object with a 'type'",
            param="response_format",
        )
    kind = rf["type"]
    if kind == "text":
        return None
    if kind == "json_object":
        raise OpenAIError(
            "response_format type 'json_object' is not supported — use "
            "'json_schema' with an explicit schema (constrained decoding "
            "compiles the schema to a token DFA, and free-form JSON has no "
            "schema to compile)",
            param="response_format",
        )
    if kind != "json_schema":
        raise OpenAIError(
            f"unknown response_format type {kind!r}", param="response_format"
        )
    # OpenAI nests the schema under json_schema.schema; accept the flat
    # shorthand too so curl examples stay short
    spec = rf.get("json_schema", rf)
    schema = spec.get("schema") if isinstance(spec, dict) else None
    if not isinstance(schema, dict):
        raise OpenAIError(
            "response_format json_schema needs a 'schema' object "
            '({"type": "json_schema", "json_schema": {"schema": {...}}})',
            param="response_format",
        )
    return {"type": "json_schema", "schema": schema}


class OpenAIFrontend:
    """Translate OpenAI requests into engine payloads and back.

    ``submit(payload, cb)`` enqueues one engine-dialect request; ``cb``
    fires exactly once with the result row (the serve/route answer shape:
    ``tokens``/``finish_reason``/``prompt_tokens``/``ttft_s``/... or
    ``error``). With ``streaming="delta"`` the host's engine loop honours
    a ``_stream`` callable in the payload by calling it with each new
    token chunk as decode emits them."""

    def __init__(self, submit, model: str = "accelerate-tpu",
                 streaming: str = "delta"):
        if streaming not in ("delta", "at_completion"):
            raise ValueError(f"unknown streaming mode {streaming!r}")
        self._submit = submit
        self.model = model
        self.streaming = streaming

    # -- request parsing -----------------------------------------------------

    def _payload_from(self, body: dict, prompt_tokens: list[int]) -> dict:
        """The shared field mapping (everything but prompt extraction)."""
        for key, default in _UNSUPPORTED_NON_DEFAULT:
            if key in body and body[key] not in (None, default):
                raise OpenAIError(
                    f"{key}={body[key]!r} is not supported (only the default "
                    f"{default!r})", param=key,
                )
        temperature = _num(body, "temperature", 0.0, 2.0, 1.0)
        top_p = _num(body, "top_p", 0.0, 1.0, 1.0)
        sampling: dict = {}
        if temperature == 0.0:
            sampling["do_sample"] = False  # the OpenAI greedy convention
        else:
            sampling["do_sample"] = True
            sampling["temperature"] = temperature
            if top_p < 1.0:
                sampling["top_p"] = top_p
        if body.get("seed") is not None:
            try:
                sampling["seed"] = int(body["seed"])
            except (TypeError, ValueError):
                raise OpenAIError("seed must be an integer", param="seed") from None
        stop = _stop_sequences(body.get("stop"))
        if stop:
            sampling["stop"] = [list(s) for s in stop]
        payload = {"prompt": prompt_tokens, "sampling": sampling}
        grammar = _response_format_grammar(body)
        if grammar is not None:
            payload["grammar"] = grammar
        if body.get("max_tokens") is not None:
            try:
                mnt = int(body["max_tokens"])
            except (TypeError, ValueError):
                raise OpenAIError(
                    "max_tokens must be an integer", param="max_tokens"
                ) from None
            if mnt < 1:
                raise OpenAIError("max_tokens must be >= 1", param="max_tokens")
            payload["max_new_tokens"] = mnt
        # vendor extension fields: the PR 11/15 scheduling + tracing knobs
        if body.get("x_accelerate_priority") is not None:
            payload["priority"] = body["x_accelerate_priority"]
        if body.get("x_accelerate_deadline_ms") is not None:
            payload["deadline_ms"] = body["x_accelerate_deadline_ms"]
        if body.get("x_accelerate_trace_id") is not None:
            payload["trace_id"] = body["x_accelerate_trace_id"]
        if body.get("x_accelerate_tenant") is not None:
            payload["tenant"] = body["x_accelerate_tenant"]
        return payload

    def _parse(self, path: str, body) -> tuple[dict, dict]:
        if not isinstance(body, dict):
            raise OpenAIError("request body must be a JSON object")
        chat = path.rstrip("/") == "/v1/chat/completions"
        if chat:
            prompt_tokens = encode_text(chat_prompt(body.get("messages")))
            logprobs = 0
            if body.get("logprobs"):
                logprobs = int(body.get("top_logprobs") or 1)
        else:
            prompt = body.get("prompt")
            if isinstance(prompt, str):
                prompt_tokens = encode_text(prompt)
            elif (
                isinstance(prompt, list)
                and prompt
                and all(isinstance(t, int) for t in prompt)
            ):
                prompt_tokens = prompt
            else:
                raise OpenAIError(
                    "prompt must be a string or a list of token ids",
                    param="prompt",
                )
            logprobs = body.get("logprobs") or 0
            try:
                logprobs = int(logprobs)
            except (TypeError, ValueError):
                raise OpenAIError(
                    "logprobs must be an integer", param="logprobs"
                ) from None
        payload = self._payload_from(body, prompt_tokens)
        if logprobs:
            payload["sampling"]["logprobs"] = logprobs
        meta = {
            "chat": chat,
            "stream": bool(body.get("stream")),
            "model": body.get("model") or self.model,
            "prompt_tokens": len(prompt_tokens),
            "logprobs": logprobs,
        }
        return payload, meta

    # -- response building ---------------------------------------------------

    @staticmethod
    def _finish(result: dict) -> tuple[str, str | None]:
        raw = result.get("finish_reason")
        mapped = _FINISH_MAP.get(raw)
        if mapped is not None:
            return mapped, None
        return "length", raw  # over-budget/expired: raw reason rides vendor

    @staticmethod
    def _vendor(result: dict, raw_finish: str | None) -> dict:
        out = {}
        for key in (
            "trace_id", "ttft_s", "tpot_s",
            # usage-ledger costs: what THIS request spent (measured, not
            # estimated — absent on usage_accounting=False engines)
            "tenant", "device_time_s", "kv_block_seconds", "swap_bytes",
        ):
            if result.get(key) is not None:
                out[key] = result[key]
        if raw_finish is not None:
            out["finish_reason"] = raw_finish
        return out

    @staticmethod
    def _logprobs_block(result: dict, meta: dict) -> dict | None:
        rows = result.get("logprobs")
        if not meta["logprobs"] or rows is None:
            return None
        if meta["chat"]:
            return {
                "content": [
                    {
                        "token": decode_tokens([e["token"]]),
                        "logprob": e["logprob"],
                        "top_logprobs": [
                            {"token": decode_tokens([t]), "logprob": lp}
                            for t, lp in e["top"]
                        ],
                    }
                    for e in rows
                ]
            }
        offsets, pos = [], 0
        texts = [decode_tokens([e["token"]]) for e in rows]
        for t in texts:
            offsets.append(pos)
            pos += len(t)
        return {
            "tokens": texts,
            "token_logprobs": [e["logprob"] for e in rows],
            "top_logprobs": [
                {decode_tokens([t]): lp for t, lp in e["top"]} for e in rows
            ],
            "text_offset": offsets,
        }

    def _completion_body(self, result: dict, meta: dict, rid: str,
                         created: int) -> dict:
        finish, raw = self._finish(result)
        tokens = result.get("tokens") or []
        usage = {
            "prompt_tokens": result.get("prompt_tokens", meta["prompt_tokens"]),
            "completion_tokens": len(tokens),
        }
        usage["total_tokens"] = usage["prompt_tokens"] + usage["completion_tokens"]
        choice: dict = {"index": 0, "finish_reason": finish,
                       "logprobs": self._logprobs_block(result, meta)}
        if meta["chat"]:
            choice["message"] = {
                "role": "assistant", "content": decode_tokens(tokens),
            }
        else:
            choice["text"] = decode_tokens(tokens)
        out = {
            "id": rid,
            "object": "chat.completion" if meta["chat"] else "text_completion",
            "created": created,
            "model": meta["model"],
            "choices": [choice],
            "usage": usage,
        }
        vendor = self._vendor(result, raw)
        if vendor:
            out["x_accelerate"] = vendor
        return out

    def _chunk_body(self, meta: dict, rid: str, created: int, *,
                    text=None, role=None, finish=None, usage=None,
                    vendor=None) -> dict:
        delta: dict = {}
        if role is not None:
            delta["role"] = role
        if text is not None:
            delta["content" if meta["chat"] else "text"] = text
        choice = {"index": 0, "finish_reason": finish}
        if meta["chat"]:
            choice["delta"] = delta
        else:
            choice["text"] = text or ""
            choice["logprobs"] = None
        out = {
            "id": rid,
            "object": (
                "chat.completion.chunk" if meta["chat"] else "text_completion"
            ),
            "created": created,
            "model": meta["model"],
            "choices": [choice],
        }
        if usage is not None:
            out["usage"] = usage
        if vendor:
            out["x_accelerate"] = vendor
        return out

    # -- the entry point -----------------------------------------------------

    def handle(self, path: str, body):
        """Answer one POST. Returns ``("json", status, obj)`` or
        ``("sse", iterator)`` — the iterator yields complete
        ``data: ...\\n\\n`` SSE event strings, ending with the
        ``data: [DONE]`` terminator."""
        try:
            payload, meta = self._parse(path, body)
        except OpenAIError as e:
            return ("json", e.status, e.body)
        rid = ("chatcmpl-" if meta["chat"] else "cmpl-") + uuid.uuid4().hex[:24]
        created = int(time.time())
        if not meta["stream"]:
            done = threading.Event()
            answer: dict = {}

            def cb(result):
                answer["result"] = result
                done.set()

            self._submit(payload, cb)
            done.wait()
            result = answer["result"]
            if "error" in result:
                err = OpenAIError(str(result["error"]), status=400)
                return ("json", err.status, err.body)
            return ("json", 200, self._completion_body(result, meta, rid, created))

        # streaming: deltas (and the final row) land in one queue; the
        # returned generator drains it from the host's handler thread
        q: queue.Queue = queue.Queue()
        if self.streaming == "delta":
            payload["_stream"] = lambda toks: q.put(("delta", list(toks)))
        self._submit(payload, lambda result: q.put(("done", result)))

        def events():
            served = 0
            sent_role = False
            while True:
                kind, item = q.get()
                if kind == "delta":
                    chunk_kw = {}
                    if meta["chat"] and not sent_role:
                        chunk_kw["role"] = "assistant"
                        sent_role = True
                    yield "data: " + json.dumps(self._chunk_body(
                        meta, rid, created, text=decode_tokens(item), **chunk_kw
                    )) + "\n\n"
                    served += len(item)
                    continue
                result = item
                if "error" in result:
                    err = OpenAIError(str(result["error"]), status=400)
                    yield "data: " + json.dumps(err.body) + "\n\n"
                    yield "data: [DONE]\n\n"
                    return
                finish, raw = self._finish(result)
                tokens = result.get("tokens") or []
                tail = tokens[served:]
                usage = {
                    "prompt_tokens": result.get(
                        "prompt_tokens", meta["prompt_tokens"]
                    ),
                    "completion_tokens": len(tokens),
                }
                usage["total_tokens"] = (
                    usage["prompt_tokens"] + usage["completion_tokens"]
                )
                chunk_kw = {}
                if meta["chat"] and not sent_role:
                    chunk_kw["role"] = "assistant"
                yield "data: " + json.dumps(self._chunk_body(
                    meta, rid, created,
                    text=decode_tokens(tail) if tail else None,
                    finish=finish, usage=usage,
                    vendor=self._vendor(result, raw), **chunk_kw
                )) + "\n\n"
                yield "data: [DONE]\n\n"
                return

        return ("sse", events())
