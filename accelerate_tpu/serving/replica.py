"""Engine replica processes: spawn, attach, health-check, drain, kill.

One replica = one ``accelerate-tpu serve --http <port>`` process (its own
engine, its own mesh, its own compiled decode executable). This module owns
the *per-replica* mechanics the router composes: process lifecycle, the
``/healthz`` state machine probe (``starting``/``ready``/``draining``), the
blocking ``POST /generate`` dispatch, and drain/kill. Pure stdlib — the
router side never imports jax, so it can front replicas from a machine with
no accelerator (the same contract as ``accelerate-tpu monitor``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from collections import deque

from ..logging import get_logger

logger = get_logger(__name__)

#: replica lifecycle as the router tracks it — the first three mirror the
#: serve front end's /healthz state machine; the rest are router-observed
REPLICA_STATES = ("starting", "ready", "draining", "dead", "terminated")


class ReplicaError(Exception):
    """Transport-level dispatch failure (connection refused/reset, torn
    response): the replica may be dead and the request must be requeued —
    distinct from an application error (HTTP 400), which is a final answer."""


class ReplicaTimeout(ReplicaError):
    """The router's ``request_timeout`` expired with the socket still open.
    A dead replica resets the connection instantly, so a timeout is
    evidence the replica is *slow-but-alive* — the router requeues the
    ticket but must NOT walk the failure path that marks replicas dead."""


class ReplicaHandle:
    """One engine replica as the router sees it.

    ``process`` is the spawned ``subprocess.Popen`` (None for attached
    remote replicas). ``in_flight``/``sessions`` are router-side dispatch
    accounting; health fields (``state``, ``queue_depth``, ``active_slots``)
    mirror the replica's last ``/healthz`` answer.
    """

    def __init__(self, replica_id: int, base_url: str, process=None):
        self.replica_id = int(replica_id)
        self.base_url = base_url.rstrip("/")
        self.process = process
        self.state = "starting"
        self.in_flight = 0
        self.sessions: set = set()
        self.queue_depth = 0
        self.active_slots = 0
        self.num_slots: int | None = None
        # engine-side deadline evictions (cumulative, from /healthz): the
        # router folds the fleet's sum into its totals row for the SLO feed
        self.deadline_expired = 0
        self.last_heartbeat: float | None = None
        self.consecutive_failures = 0
        self.dispatched = 0
        self.completed = 0
        # supervisor state: how many times this identity has been respawned,
        # and whether the current incarnation is a half-open probation probe
        # (the router routes it one request at a time until it proves itself)
        self.restarts = 0
        self.probation = False
        self.probation_successes = 0
        # leading-block hashes of recently dispatched prompts: the router's
        # prefix-affinity signal (this replica's radix cache is likely warm
        # for these) — see Router._pick_replica
        self.recent_prefixes: deque = deque(maxlen=128)

    # -- health --------------------------------------------------------------

    @property
    def pid(self) -> int | None:
        return self.process.pid if self.process is not None else None

    def process_exited(self) -> bool:
        return self.process is not None and self.process.poll() is not None

    def check_health(self, timeout: float = 2.0) -> dict | None:
        """GET ``/healthz``; returns the parsed payload (and refreshes the
        mirrored fields) or None on any failure."""
        try:
            with urllib.request.urlopen(
                f"{self.base_url}/healthz", timeout=timeout
            ) as resp:
                payload = json.loads(resp.read())
        except Exception:
            return None
        if isinstance(payload, dict):
            self.last_heartbeat = time.time()
            self.consecutive_failures = 0
            # A probe that started before a kill can finish after the router
            # marked us dead — its stale "ready" payload must not resurrect
            # a spawned replica (that process is gone for good). Attached
            # replicas may genuinely come back, so they do adopt it.
            if payload.get("state") in REPLICA_STATES and not (
                self.process is not None and self.state in ("dead", "terminated")
            ):
                self.state = payload["state"]
            for field in (
                "queue_depth", "active_slots", "num_slots", "deadline_expired"
            ):
                if isinstance(payload.get(field), int):
                    setattr(self, field, payload[field])
            return payload
        return None

    @property
    def load(self) -> int:
        """Dispatch-ordering key: requests the router has in flight here
        plus what the replica itself reports queued/decoding. Router-side
        ``in_flight`` dominates — it is current even between health ticks."""
        return self.in_flight + self.queue_depth + self.active_slots

    def is_dispatchable(self) -> bool:
        return self.state == "ready" and not self.process_exited()

    # -- dispatch ------------------------------------------------------------

    def generate(self, payload: dict, timeout: float | None = None) -> dict:
        """Blocking ``POST /generate``. An HTTP 400 is a *final* answer (the
        replica rejected the request — re-sending it elsewhere would fail
        identically); transport failures raise :class:`ReplicaError` so the
        router requeues."""
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            f"{self.base_url}/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            if e.code == 503:
                # "not accepting requests" (starting/draining) — the request
                # is valid, this replica just can't take it: requeue to a
                # survivor instead of handing the client the refusal
                raise ReplicaError(
                    f"replica {self.replica_id}: not accepting requests (503)"
                ) from e
            # an application-level rejection is a completed request
            try:
                return json.loads(e.read())
            except Exception:
                raise ReplicaError(f"replica {self.replica_id}: torn HTTP error body") from e
        except TimeoutError as e:
            raise ReplicaTimeout(
                f"replica {self.replica_id}: request_timeout after {timeout}s "
                "(replica slow but alive)"
            ) from e
        except Exception as e:
            # urllib wraps socket timeouts in URLError("timed out") — the
            # distinction matters: a timeout means slow-but-alive, never a
            # death verdict (see ReplicaTimeout)
            reason = getattr(e, "reason", None)
            if isinstance(e, TimeoutError) or isinstance(reason, TimeoutError):
                raise ReplicaTimeout(
                    f"replica {self.replica_id}: request_timeout after {timeout}s "
                    "(replica slow but alive)"
                ) from e
            raise ReplicaError(f"replica {self.replica_id}: {e}") from e

    # -- lifecycle -----------------------------------------------------------

    def drain(self) -> None:
        """SIGTERM — the serve front end's PreemptionHandler flag: stop
        admission, finish in-flight, exit 0."""
        if self.process is not None and self.process.poll() is None:
            try:
                self.process.send_signal(signal.SIGTERM)
            except OSError:
                pass

    def kill(self) -> None:
        if self.process is not None and self.process.poll() is None:
            try:
                self.process.kill()
            except OSError:
                pass

    def wait(self, timeout: float | None = None) -> int | None:
        if self.process is None:
            return None
        try:
            return self.process.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None


def free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_replica(
    replica_id: int,
    serve_args: list[str],
    port: int | None = None,
    env: dict | None = None,
    stderr=None,
) -> ReplicaHandle:
    """Launch one ``accelerate-tpu serve --http`` process and return its
    handle (state ``starting`` until ``/healthz`` says otherwise).

    ``serve_args`` is the engine-shape tail (``--preset``, ``--num-slots``,
    ...) forwarded verbatim, so every replica serves the identical model —
    the router's dispatch assumes replicas are interchangeable."""
    port = port or free_port()
    cmd = [
        sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
        "serve", "--http", str(port), "--replica-id", str(replica_id),
        *serve_args,
    ]
    process = subprocess.Popen(
        cmd,
        env=dict(os.environ if env is None else env),
        stdin=subprocess.DEVNULL,
        stdout=subprocess.DEVNULL,
        stderr=stderr if stderr is not None else subprocess.DEVNULL,
    )
    handle = ReplicaHandle(replica_id, f"http://127.0.0.1:{port}", process=process)
    logger.info("spawned replica %d on port %d (pid %d)", replica_id, port, process.pid)
    return handle


def wait_until_ready(
    replicas: list[ReplicaHandle], timeout: float = 120.0, poll: float = 0.25
) -> None:
    """Block until every replica's ``/healthz`` reports ``ready``. A replica
    process dying during bring-up raises immediately — a half-ready fleet
    that silently dispatches to fewer replicas than requested would skew
    every capacity assumption downstream."""
    deadline = time.monotonic() + timeout
    pending = list(replicas)
    while pending:
        for r in list(pending):
            if r.process_exited():
                raise RuntimeError(
                    f"replica {r.replica_id} (pid {r.pid}) exited with "
                    f"{r.process.returncode} during bring-up"
                )
            r.check_health(timeout=2.0)
            if r.state == "ready":
                pending.remove(r)
        if not pending:
            return
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"replicas {[r.replica_id for r in pending]} not ready after {timeout}s"
            )
        time.sleep(poll)
