"""Continuous-batching serving engine (Orca-style slot scheduling over a
vLLM-style block-paged KV cache) — see :mod:`.engine` for the design —
plus the pod-scale layer: mesh-sharded decode (``InferenceEngine(...,
mesh=)``), the multi-replica router (:mod:`.router` / :mod:`.replica`),
and the self-healing layer — the replica supervisor with crash-loop
backoff and min/max autoscale (:mod:`.supervisor`) and the seeded
fault-injection harness (:mod:`.chaos`).

The router side (router/replica/supervisor/chaos) is jax-free on purpose:
importing ``Router`` or ``ReplicaSupervisor`` must work on a machine with
no accelerator, so those names are NOT imported here eagerly — use
``from accelerate_tpu.serving.router import Router`` etc.
"""

from .blocks import NULL_BLOCK, BlockAllocator, blocks_needed
from .engine import EngineConfig, InferenceEngine
from .grammar import Grammar, GrammarError, compile_grammar, validate_instance
from .flight import (
    ITERATION_PHASES,
    FlightRecorder,
    get_active_flight_recorder,
    set_active_flight_recorder,
)
from .radix import RadixCache, SwapPool
from .sampling import SamplingParams, resolve_sampling
from .scheduler import PRIORITY_CLASSES, Request, RequestState, SlotScheduler
from .spec import DraftSpec, parse_draft_spec

__all__ = [
    "NULL_BLOCK",
    "BlockAllocator",
    "blocks_needed",
    "DraftSpec",
    "EngineConfig",
    "FlightRecorder",
    "Grammar",
    "GrammarError",
    "ITERATION_PHASES",
    "InferenceEngine",
    "SamplingParams",
    "compile_grammar",
    "resolve_sampling",
    "validate_instance",
    "get_active_flight_recorder",
    "set_active_flight_recorder",
    "PRIORITY_CLASSES",
    "RadixCache",
    "Request",
    "RequestState",
    "SlotScheduler",
    "SwapPool",
    "parse_draft_spec",
]
