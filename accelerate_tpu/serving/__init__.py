"""Continuous-batching serving engine (Orca-style slot scheduling over a
vLLM-style block-paged KV cache) — see :mod:`.engine` for the design."""

from .blocks import NULL_BLOCK, BlockAllocator, blocks_needed
from .engine import EngineConfig, InferenceEngine
from .scheduler import Request, RequestState, SlotScheduler

__all__ = [
    "NULL_BLOCK",
    "BlockAllocator",
    "blocks_needed",
    "EngineConfig",
    "InferenceEngine",
    "Request",
    "RequestState",
    "SlotScheduler",
]
