"""Continuous-batching serving engine (Orca-style slot scheduling over a
vLLM-style block-paged KV cache) — see :mod:`.engine` for the design —
plus the pod-scale layer: mesh-sharded decode (``InferenceEngine(...,
mesh=)``) and the multi-replica router (:mod:`.router` / :mod:`.replica`).

The router side is jax-free on purpose: importing ``Router`` or
``ReplicaHandle`` must work on a machine with no accelerator, so those
names are NOT imported here eagerly — use
``from accelerate_tpu.serving.router import Router``.
"""

from .blocks import NULL_BLOCK, BlockAllocator, blocks_needed
from .engine import EngineConfig, InferenceEngine
from .radix import RadixCache, SwapPool
from .scheduler import PRIORITY_CLASSES, Request, RequestState, SlotScheduler

__all__ = [
    "NULL_BLOCK",
    "BlockAllocator",
    "blocks_needed",
    "EngineConfig",
    "InferenceEngine",
    "PRIORITY_CLASSES",
    "RadixCache",
    "Request",
    "RequestState",
    "SlotScheduler",
    "SwapPool",
]
