"""Per-request resource attribution — the conservation-checked usage ledger.

Every request accrues **measured** costs as it runs, rolled up by request,
priority class, and tenant:

* **decode device-seconds** — each harvested decode round's ``device_wait``
  interval (the exact float the flight recorder accrues, when flight is on)
  apportioned across the round's live slots, weighted by how many tokens
  each request actually emitted from that harvest;
* **prefill device-seconds** — per prefill chunk, wall time around the
  chunk dispatch, attributed to the one request the chunk belongs to;
* **KV block-seconds** — the integral of per-request *held* blocks over
  wall time, accrued at every refcount edge (admit, decode growth, CoW
  resolve, swap-out/in, deadline release, eviction). "Held" means blocks
  the request owns allocator references to (``req.blocks`` minus blocks
  parked host-side in ``req.swap_plan``); a prefix block shared by N
  requests bills each holder — the fair-division choice chargeback wants.
  Radix-cache-exclusive blocks belong to the cache, not any request, and
  are deliberately outside the ledger;
* **swap bytes** in/out, **speculative** drafted/accepted tokens, and
  **grammar-masked** steps.

The headline property is **conservation, asserted not estimated**: the
ledger independently accrues two partner totals per resource —
``device_wait_seconds`` (one add per harvest) vs the sum of per-request
decode shares, and ``pool_block_seconds`` (one pool-wide integrand) vs
the sum of per-request block-second integrals — using the *same*
timestamps at the *same* edges, so the pairs agree to float tolerance no
matter how requests are preempted, swapped, expired, or speculated.
Per-request accounting closes when the engine processes the request's
completion (the iteration its answer row is emitted); the extra
iteration the scheduler holds the blocks before eviction is excluded
from *both* sides of the integral, consistently.

Jax-free by design, like :mod:`.flight` — the ``usage report`` CLI and
the monitor consume ledger snapshots from trails alone. The disabled
path is one truthiness check per engine iteration
(``EngineConfig(usage_accounting=False)`` → ``engine.usage is None``),
the telemetry/flight discipline.

Tenant-label cardinality on any exported surface is capped to the
``top_k`` heaviest tenants plus an ``other`` fold (:func:`cap_by_key`),
so a hostile tenant-id stream can never blow up the metrics registry or
a scrape.
"""

from __future__ import annotations

import time

__all__ = [
    "DEFAULT_TOP_K",
    "OTHER_TENANT",
    "USAGE_SCHEMA",
    "UsageLedger",
    "cap_by_key",
    "normalize_tenant",
]

#: schema stamp on ledger snapshots (telemetry step rows, stats()["usage"])
USAGE_SCHEMA = 1

#: exported tenant-label cardinality cap: top-K heaviest + ``other``
DEFAULT_TOP_K = 8

#: the fold bucket every beyond-top-K tenant aggregates into; a real
#: tenant named "other" merges with the fold (documented, not detected)
OTHER_TENANT = "other"

#: tenant ids are labels on metrics and JSONL rows — bound them
_TENANT_MAX_LEN = 64

#: the tenant every request without one belongs to (unknown-safe: a
#: malformed tenant value normalizes here instead of raising)
DEFAULT_TENANT = "default"


def normalize_tenant(value) -> str:
    """The tenant key contract: any non-empty string (stripped, bounded
    to 64 chars); everything else — ``None``, numbers, empty — is the
    ``default`` tenant. Never raises: tenant is an accounting dimension,
    not an admission gate."""
    if isinstance(value, str):
        v = value.strip()
        if v:
            return v[:_TENANT_MAX_LEN]
    return DEFAULT_TENANT


def cap_by_key(entries: dict, top_k: int, weight_field: str = "device_seconds") -> dict:
    """Cap a ``{tenant: rollup}`` dict to the ``top_k`` heaviest (by
    ``weight_field``, ties broken by name for determinism) plus an
    ``other`` bucket summing every numeric field of the rest."""
    if len(entries) <= top_k:
        return {k: dict(v) for k, v in entries.items()}
    ranked = sorted(
        entries.items(), key=lambda kv: (-float(kv[1].get(weight_field) or 0.0), kv[0])
    )
    out = {k: dict(v) for k, v in ranked[:top_k]}
    other: dict = {}
    for _, row in ranked[top_k:]:
        for field, val in row.items():
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            other[field] = other.get(field, 0) + val
    # fold INTO an existing literal "other" tenant rather than clobber it
    if OTHER_TENANT in out:
        for field, val in other.items():
            out[OTHER_TENANT][field] = out[OTHER_TENANT].get(field, 0) + val
    else:
        out[OTHER_TENANT] = other
    return out


class _RequestUsage:
    """One live request's accruals. A plain slotted record — this sits on
    the engine's per-token path, so no dataclass machinery."""

    __slots__ = (
        "tenant", "priority", "trace_id", "request_id",
        "decode_device_s", "prefill_device_s", "block_seconds",
        "held_blocks", "held_since",
        "swap_bytes_in", "swap_bytes_out",
        "spec_drafted", "spec_accepted", "grammar_masked_steps",
    )

    def __init__(self, request_id, tenant, priority, trace_id, now):
        self.request_id = request_id
        self.tenant = tenant
        self.priority = priority
        self.trace_id = trace_id
        self.decode_device_s = 0.0
        self.prefill_device_s = 0.0
        self.block_seconds = 0.0
        self.held_blocks = 0
        self.held_since = now
        self.swap_bytes_in = 0
        self.swap_bytes_out = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.grammar_masked_steps = 0


def _zero_rollup() -> dict:
    return {
        "requests": 0,
        "tokens": 0,
        "device_seconds": 0.0,
        "decode_device_seconds": 0.0,
        "prefill_device_seconds": 0.0,
        "block_seconds": 0.0,
        "swap_bytes": 0,
        "spec_drafted_tokens": 0,
        "spec_accepted_tokens": 0,
        "grammar_masked_steps": 0,
    }


def _fold(table: dict, key: str, rec: _RequestUsage, tokens: int,
          block_seconds: float) -> None:
    row = table.get(key)
    if row is None:
        row = table[key] = _zero_rollup()
    row["requests"] += 1
    row["tokens"] += tokens
    row["decode_device_seconds"] += rec.decode_device_s
    row["prefill_device_seconds"] += rec.prefill_device_s
    row["device_seconds"] += rec.decode_device_s + rec.prefill_device_s
    row["block_seconds"] += block_seconds
    row["swap_bytes"] += rec.swap_bytes_in + rec.swap_bytes_out
    row["spec_drafted_tokens"] += rec.spec_drafted
    row["spec_accepted_tokens"] += rec.spec_accepted
    row["grammar_masked_steps"] += rec.grammar_masked_steps


class UsageLedger:
    """The engine-owned per-request cost accumulator.

    Hooks (all no-ops for unknown request ids, so late edges after a
    request closed are safe):

    * :meth:`begin` — on admission to the scheduler;
    * :meth:`update_blocks` — at every block-ownership edge;
    * :meth:`accrue_decode` — once per harvested round, with the exact
      ``device_wait`` seconds and per-request emission weights;
    * :meth:`accrue_prefill` / :meth:`accrue_swap` / :meth:`accrue_spec`
      / :meth:`accrue_grammar`;
    * :meth:`finish` — when the engine processes the completion; returns
      the answer-row cost summary and folds the record into the
      tenant/class rollups and heavy-hitter ranking.
    """

    def __init__(self, top_k: int = DEFAULT_TOP_K):
        now = time.perf_counter()
        self.top_k = top_k
        self._live: dict = {}  # request_id -> _RequestUsage
        self._by_tenant: dict = {}
        self._by_class: dict = {}
        self._heavy: list = []  # finished-request summaries, heaviest first
        self._requests_finished = 0
        # conservation partners: each accrued ONCE per edge, independently
        # of the per-request apportionment they must sum to
        self._device_wait_s = 0.0
        self._pool_held = 0
        self._pool_block_seconds = 0.0
        self._pool_since = now

    # -- lifecycle hooks -----------------------------------------------------

    def begin(self, req) -> None:
        now = time.perf_counter()
        self._live[req.request_id] = _RequestUsage(
            req.request_id, req.tenant, req.priority, req.trace_id, now
        )

    def update_blocks(self, req) -> None:
        """Accrue block-seconds up to now and restamp the held count.
        Held = allocator references the request owns: ``req.blocks``
        minus entries parked host-side in ``req.swap_plan``."""
        rec = self._live.get(req.request_id)
        if rec is None:
            return
        held = len(req.blocks) - len(req.swap_plan)
        self._accrue_blocks(rec, held, time.perf_counter())

    def _accrue_blocks(self, rec: _RequestUsage, held: int, now: float) -> None:
        if rec.held_blocks:
            rec.block_seconds += rec.held_blocks * (now - rec.held_since)
        # the pool-wide integrand advances at the SAME edge with the SAME
        # stamp, so Σ per-request integrals == the pool integral exactly
        # (up to float rounding), by construction
        if self._pool_held:
            self._pool_block_seconds += self._pool_held * (now - self._pool_since)
        self._pool_since = now
        self._pool_held += held - rec.held_blocks
        rec.held_blocks = held
        rec.held_since = now

    def accrue_decode(self, device_wait_s: float, shares) -> None:
        """One harvested round: ``device_wait_s`` is the round's exact
        device-wait interval (the float the flight recorder accrued, when
        flight is on); ``shares`` is ``[(request_id, weight), ...]`` with
        arbitrary non-negative weights (normalized here — the engine
        passes per-request emitted-token counts)."""
        self._device_wait_s += device_wait_s
        total = sum(w for _, w in shares)
        if not total:
            return
        live = self._live
        for rid, w in shares:
            rec = live.get(rid)
            if rec is not None:
                rec.decode_device_s += device_wait_s * (w / total)

    def accrue_prefill(self, req, dt_s: float) -> None:
        rec = self._live.get(req.request_id)
        if rec is not None:
            rec.prefill_device_s += dt_s

    def accrue_swap(self, req, *, bytes_out: int = 0, bytes_in: int = 0) -> None:
        rec = self._live.get(req.request_id)
        if rec is not None:
            rec.swap_bytes_out += bytes_out
            rec.swap_bytes_in += bytes_in

    def accrue_spec(self, req, drafted: int, accepted: int) -> None:
        rec = self._live.get(req.request_id)
        if rec is not None:
            rec.spec_drafted += drafted
            rec.spec_accepted += accepted

    def accrue_grammar(self, req) -> None:
        rec = self._live.get(req.request_id)
        if rec is not None:
            rec.grammar_masked_steps += 1

    def finish(self, req) -> dict | None:
        """Close the request's account: final block-second accrual (held
        drops to 0 on both sides of the integral), fold into rollups, and
        return the answer-row summary. Exactly-once: a second finish (or
        any later edge) no-ops."""
        rec = self._live.pop(req.request_id, None)
        if rec is None:
            return None
        self._accrue_blocks(rec, 0, time.perf_counter())
        tokens = len(req.output_tokens)
        _fold(self._by_tenant, rec.tenant, rec, tokens, rec.block_seconds)
        _fold(self._by_class, rec.priority, rec, tokens, rec.block_seconds)
        self._requests_finished += 1
        device_s = rec.decode_device_s + rec.prefill_device_s
        swap_bytes = rec.swap_bytes_in + rec.swap_bytes_out
        entry = {
            "request_id": rec.request_id,
            "trace_id": rec.trace_id,
            "tenant": rec.tenant,
            "class": rec.priority,
            "device_seconds": device_s,
            "block_seconds": rec.block_seconds,
            "swap_bytes": swap_bytes,
            "new_tokens": tokens,
            "finish_reason": req.finish_reason,
        }
        heavy = self._heavy
        heavy.append(entry)
        heavy.sort(key=lambda e: -e["device_seconds"])
        del heavy[self.top_k:]
        return {
            "device_time_s": device_s,
            "kv_block_seconds": rec.block_seconds,
            "swap_bytes": swap_bytes,
        }

    # -- read side -----------------------------------------------------------

    def snapshot(self) -> dict:
        """Cumulative ledger state (finished rollups + live accruals to
        now, without mutating any edge stamps): totals, capped
        ``by_tenant``, ``by_class``, heavy hitters, and the conservation
        partner totals."""
        now = time.perf_counter()
        tenants = {k: dict(v) for k, v in self._by_tenant.items()}
        classes = {k: dict(v) for k, v in self._by_class.items()}
        for rec in self._live.values():
            live_bs = rec.block_seconds + rec.held_blocks * (now - rec.held_since)
            for table, key in ((tenants, rec.tenant), (classes, rec.priority)):
                row = table.get(key)
                if row is None:
                    row = table[key] = _zero_rollup()
                row["decode_device_seconds"] += rec.decode_device_s
                row["prefill_device_seconds"] += rec.prefill_device_s
                row["device_seconds"] += rec.decode_device_s + rec.prefill_device_s
                row["block_seconds"] += live_bs
                row["swap_bytes"] += rec.swap_bytes_in + rec.swap_bytes_out
                row["spec_drafted_tokens"] += rec.spec_drafted
                row["spec_accepted_tokens"] += rec.spec_accepted
                row["grammar_masked_steps"] += rec.grammar_masked_steps
        totals = _zero_rollup()
        del totals["requests"], totals["tokens"]
        for row in tenants.values():
            for field in totals:
                totals[field] += row[field]
        pool_bs = self._pool_block_seconds
        if self._pool_held:
            pool_bs += self._pool_held * (now - self._pool_since)
        return {
            "schema": USAGE_SCHEMA,
            "requests_finished": self._requests_finished,
            "requests_live": len(self._live),
            "top_k": self.top_k,
            **totals,
            # conservation partners (Σ decode shares vs device_wait; Σ
            # block-seconds vs the pool integrand)
            "device_wait_seconds": self._device_wait_s,
            "pool_block_seconds": pool_bs,
            "by_tenant": cap_by_key(tenants, self.top_k),
            "by_class": classes,
            "heavy_hitters": [dict(e) for e in self._heavy],
        }

    def reset(self) -> None:
        """``engine.reset_stats()``: zero every accrual but keep live
        requests' identities and current block holdings (they re-base at
        now, like the flight recorder's reset)."""
        now = time.perf_counter()
        self._by_tenant.clear()
        self._by_class.clear()
        self._heavy = []
        self._requests_finished = 0
        self._device_wait_s = 0.0
        self._pool_block_seconds = 0.0
        self._pool_since = now
        for rec in self._live.values():
            rec.decode_device_s = 0.0
            rec.prefill_device_s = 0.0
            rec.block_seconds = 0.0
            rec.held_since = now
            rec.swap_bytes_in = 0
            rec.swap_bytes_out = 0
            rec.spec_drafted = 0
            rec.spec_accepted = 0
            rec.grammar_masked_steps = 0
