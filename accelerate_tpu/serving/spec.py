"""Speculative-decoding draft specs for the serving engine.

One tiny parser shared by ``EngineConfig(draft=...)``, ``serve/route
--draft`` and ``shard-check --draft`` so every surface agrees on what a
draft string means and rejects the same garbage with the same message.

The shipped draft family is ``"early_exit:N"`` — the target's own first
``N`` layers plus its embeddings/final norm/head (the construction the
bench ``spec`` mode measures). It is the one draft whose KV state is a
strict subset of the target's paged pool (identical weights ⇒ identical
K/V for the shared layers), which is what lets the engine run speculation
without a second cache and without teaching prefix sharing, copy-on-write,
or swap preemption anything new. A path to a companion draft checkpoint is
reserved syntax: a companion model needs its own paged pool with full
CoW/swap/radix maintenance, which this engine does not grow until a
trained companion exists to justify it — the refusal says so instead of
silently serving wrong tokens.
"""

from __future__ import annotations

from dataclasses import dataclass

#: the draft family the engine implements
EARLY_EXIT_PREFIX = "early_exit:"


@dataclass(frozen=True)
class DraftSpec:
    """Parsed ``EngineConfig.draft``. ``kind`` is ``"early_exit"``;
    ``layers`` the draft's depth (< the target's)."""

    kind: str
    layers: int

    def __str__(self) -> str:  # the normalized form stats()/telemetry report
        return f"{self.kind}:{self.layers}"


def parse_draft_spec(draft: str, num_layers: int | None = None) -> DraftSpec:
    """``"early_exit:2"`` → :class:`DraftSpec`. ``num_layers`` (the target's
    depth, when known) bounds the early-exit depth: a draft as deep as the
    target verifies nothing it didn't already compute. Raises ValueError
    with guidance on any other string — including a companion-checkpoint
    path, which is recognised and refused explicitly."""
    if not isinstance(draft, str) or not draft.strip():
        raise ValueError(
            f"malformed draft spec {draft!r}: want 'early_exit:N' "
            "(the target's first N layers as the draft)"
        )
    draft = draft.strip()
    if draft.startswith(EARLY_EXIT_PREFIX):
        raw = draft[len(EARLY_EXIT_PREFIX):]
        try:
            layers = int(raw)
        except ValueError:
            raise ValueError(
                f"malformed draft spec {draft!r}: the early-exit depth "
                f"{raw!r} is not an integer"
            ) from None
        if layers < 1:
            raise ValueError(
                f"early-exit draft depth must be >= 1, got {layers}"
            )
        if num_layers is not None and layers >= num_layers:
            raise ValueError(
                f"early-exit draft depth {layers} must be < the target's "
                f"{num_layers} layers: a full-depth draft IS the target and "
                "speculation would verify its own output"
            )
        return DraftSpec(kind="early_exit", layers=layers)
    if "/" in draft or draft.endswith((".ckpt", ".safetensors", ".msgpack")):
        raise ValueError(
            f"companion draft checkpoints ({draft!r}) are not supported yet: "
            "a separate draft model needs its own paged KV pool with "
            "CoW/swap/radix maintenance. Use draft='early_exit:N' — the "
            "target's first N layers share the target's pool for free"
        )
    raise ValueError(
        f"unknown draft spec {draft!r}: want 'early_exit:N'"
    )
