"""Process/accelerator state singletons.

TPU-native re-design of ``/root/reference/src/accelerate/state.py`` (1257
LoC). Same Borg-singleton contract — ``PartialState`` (reference
``state.py:115``), ``AcceleratorState`` (``state.py:816``), ``GradientState``
(``state.py:1134``) share state across all instances so library helpers
(``get_logger``, ``gather``…) work without passing handles — but the
execution environment is JAX:

* "process" == JAX host process (one per machine, driving all its local
  chips), not one-process-per-device. ``num_processes`` is
  ``jax.process_count()``.
* backend selection/process-group init (reference ``state.py:710-767``)
  becomes ``jax.distributed.initialize`` + named-``Mesh`` construction
  (see :mod:`accelerate_tpu.mesh`).
* ``wait_for_everyone`` (reference ``state.py:343``) lowers to
  ``multihost_utils.sync_global_devices``.
* there is no ``xm.mark_step()`` bookkeeping — dispatch is explicit under
  ``jit``, so ``GradientState`` keeps only the accumulation/remainder
  semantics.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import math
import os
from typing import Any, Callable, Iterable

import jax

from .mesh import (
    batch_axis_size,
    build_mesh,
    device_topology,
    initialize_distributed,
    single_device_mesh,
)
from .utils.dataclasses import (
    DistributedType,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    MeshPlugin,
    PrecisionType,
)
from .utils.environment import parse_choice_from_env, parse_flag_from_env

logger = logging.getLogger(__name__)


class PartialState:
    """Singleton holding the topology view + process-control primitives.

    Reference: ``PartialState`` ``state.py:115`` (``_prepare_backend``
    :710, ``set_device`` :769, ``wait_for_everyone`` :343,
    ``split_between_processes`` :389, ``main_process_first`` :477,
    ``on_*_process`` decorators :519-675).
    """

    _shared_state: dict[str, Any] = {}
    _known_attrs = [
        "debug",
        "device",
        "distributed_type",
        "local_process_index",
        "num_processes",
        "process_index",
        "mesh",
        "mesh_plugin",
    ]

    def __init__(self, cpu: bool = False, mesh_plugin: MeshPlugin | None = None, **kwargs):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        self.debug = parse_flag_from_env("ACCELERATE_DEBUG_MODE")
        # Multi-host rendezvous first (no-op unless coordinator env/flag set).
        initialize_distributed(
            coordinator_address=kwargs.pop("coordinator_address", None),
            num_processes=kwargs.pop("num_processes", None),
            process_id=kwargs.pop("process_id", None),
        )
        if cpu or parse_flag_from_env("ACCELERATE_USE_CPU"):
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
        topo = device_topology()
        self.num_processes = topo["process_count"]
        self.process_index = topo["process_index"]
        self.local_process_index = 0  # one JAX process per host
        self.mesh_plugin = mesh_plugin or MeshPlugin()
        if topo["num_devices"] == 1:
            self.distributed_type = DistributedType.NO
            self.mesh = single_device_mesh()
        else:
            if self.num_processes > 1:
                self.distributed_type = DistributedType.MULTI_HOST_TPU
            elif topo["platform"] == "cpu":
                self.distributed_type = DistributedType.CPU_MESH
            else:
                self.distributed_type = DistributedType.TPU
            self.mesh = build_mesh(self.mesh_plugin)
        self.device = jax.local_devices()[0]

    # -- lifecycle -----------------------------------------------------------

    @property
    def initialized(self) -> bool:
        return "distributed_type" in self.__dict__

    @classmethod
    def _reset_state(cls):
        cls._shared_state.clear()

    def destroy_process_group(self):  # API parity; JAX owns teardown
        self._reset_state()

    # -- identity ------------------------------------------------------------

    @property
    def use_distributed(self) -> bool:
        return self.distributed_type != DistributedType.NO

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return self.local_process_index == 0

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    @property
    def local_device_count(self) -> int:
        return jax.local_device_count()

    @property
    def num_devices(self) -> int:
        return self.mesh.size

    @property
    def data_parallel_size(self) -> int:
        """How many ways the global batch is split (dp × fsdp axes)."""
        return batch_axis_size(self.mesh)

    # -- process control -----------------------------------------------------

    def wait_for_everyone(self):
        """Cross-host barrier (reference ``state.py:343``)."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("accelerate_tpu.wait_for_everyone")

    def consensus_any(self, flag: bool) -> bool:
        """Does ANY process assert ``flag``? A tiny all-gather of one int —
        the primitive behind preemption consensus (resilience subsystem)
        and any one-host-decides breaker. COLLECTIVE when multi-process:
        every process must call it at the same point."""
        if self.num_processes <= 1:
            return bool(flag)
        import numpy as np
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(
            np.asarray([1 if flag else 0], dtype=np.int32)
        )
        return bool(np.asarray(gathered).any())

    @contextlib.contextmanager
    def main_process_first(self):
        """Main process runs the body before others (download-then-load idiom;
        reference ``state.py:477``)."""
        if not self.is_main_process:
            self.wait_for_everyone()
        yield
        if self.is_main_process:
            self.wait_for_everyone()

    @contextlib.contextmanager
    def local_main_process_first(self):
        with self.main_process_first():  # 1 process per host ⇒ same thing
            yield

    def on_main_process(self, function: Callable = None):
        def wrapper(fn):
            @functools.wraps(fn)
            def inner(*args, **kwargs):
                if self.is_main_process:
                    return fn(*args, **kwargs)

            return inner

        return wrapper(function) if function is not None else wrapper

    def on_local_main_process(self, function: Callable = None):
        return self.on_main_process(function)

    def on_last_process(self, function: Callable):
        @functools.wraps(function)
        def inner(*args, **kwargs):
            if self.is_last_process:
                return function(*args, **kwargs)

        return inner

    def on_process(self, function: Callable = None, process_index: int = None):
        if function is None:
            return functools.partial(self.on_process, process_index=process_index)

        @functools.wraps(function)
        def inner(*args, **kwargs):
            if self.process_index == process_index:
                return function(*args, **kwargs)

        return inner

    def on_local_process(self, function: Callable = None, local_process_index: int = None):
        if function is None:
            return functools.partial(self.on_local_process, local_process_index=local_process_index)

        @functools.wraps(function)
        def inner(*args, **kwargs):
            if self.local_process_index == local_process_index:
                return function(*args, **kwargs)

        return inner

    @contextlib.contextmanager
    def split_between_processes(self, inputs, apply_padding: bool = False):
        """Split a list/tuple/dict/array between processes, last process
        padded when uneven and ``apply_padding`` (reference ``state.py:389``)."""
        if self.num_processes == 1:
            yield inputs
            return
        length = len(inputs)
        num_per = math.ceil(length / self.num_processes)
        start = self.process_index * num_per
        end = min(start + num_per, length)

        def _slice(obj):
            sliced = obj[start:end]
            if apply_padding and len(sliced) < num_per and len(obj) > 0:
                pad = [obj[-1]] * (num_per - len(sliced))
                if isinstance(sliced, list):
                    sliced = sliced + pad
                else:
                    import numpy as np

                    sliced = np.concatenate([sliced, np.stack(pad)])
            return sliced

        if isinstance(inputs, dict):
            yield {k: _slice(v) for k, v in inputs.items()}
        else:
            yield _slice(inputs)

    def print(self, *args, **kwargs):
        if self.is_main_process:
            print(*args, **kwargs)

    def __repr__(self) -> str:
        return (
            f"Distributed environment: {self.distributed_type}\n"
            f"Num processes: {self.num_processes}\n"
            f"Process index: {self.process_index}\n"
            f"Local process index: {self.local_process_index}\n"
            f"Device: {self.device}\n"
            f"Mesh: {dict(self.mesh.shape)}\n"
        )


def _require_initialized(method):
    @functools.wraps(method)
    def inner(self, *args, **kwargs):
        if not self.initialized:
            raise RuntimeError(
                f"`{method.__name__}` requires AcceleratorState to be initialized — "
                "construct an `Accelerator()` first."
            )
        return method(self, *args, **kwargs)

    return inner


class AcceleratorState:
    """Adds precision + plugin decisions on top of PartialState (reference
    ``state.py:816``; plugin merge :893-941)."""

    _shared_state: dict[str, Any] = {}

    def __init__(
        self,
        mixed_precision: str | None = None,
        cpu: bool = False,
        mesh_plugin: MeshPlugin | None = None,
        fsdp_plugin: FullyShardedDataParallelPlugin | None = None,
        _from_accelerator: bool = False,
        **kwargs,
    ):
        self.__dict__ = self._shared_state
        if self.initialized:
            if mixed_precision is not None and mixed_precision != self._mixed_precision:
                raise ValueError(
                    "AcceleratorState already initialized with "
                    f"mixed_precision={self._mixed_precision!r}; call "
                    "AcceleratorState._reset_state() to change it."
                )
            return
        self._partial = PartialState(cpu=cpu, mesh_plugin=mesh_plugin, **kwargs)
        if mixed_precision is None:
            mixed_precision = parse_choice_from_env("ACCELERATE_MIXED_PRECISION", "no")
        mixed_precision = PrecisionType(mixed_precision).value
        self._mixed_precision = mixed_precision
        self.fsdp_plugin = fsdp_plugin
        self.dynamo_plugin = None  # XLA always compiles; kept for API parity
        self.deepspeed_plugins = None  # plugin | dict[str, plugin] | None
        self.initialized_trackers = []

    @property
    def initialized(self) -> bool:
        return "_partial" in self.__dict__

    @classmethod
    def _reset_state(cls, reset_partial_state: bool = False):
        cls._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()
        from .ops.attention import set_attention_context

        set_attention_context(None)

    @property
    def mixed_precision(self) -> str:
        return self._mixed_precision

    # -- multi-plugin DeepSpeed selection (reference ``state.py:1100-1116``) --

    def _named_deepspeed_plugins(self) -> dict:
        plugins = self.__dict__.get("deepspeed_plugins")
        if plugins is None:
            raise ValueError(
                "No DeepSpeedPlugin is enabled — pass `deepspeed_plugin=` "
                "(a plugin or a dict of named plugins) to Accelerator first."
            )
        if not isinstance(plugins, dict):
            raise ValueError(
                "A single (unnamed) DeepSpeedPlugin is enabled; named "
                "selection needs a dict of plugins passed to Accelerator."
            )
        return plugins

    @_require_initialized
    def get_deepspeed_plugin(self, name: str):
        """The DeepSpeedPlugin registered under ``name``."""
        return self._named_deepspeed_plugins()[name]

    @_require_initialized
    def select_deepspeed_plugin(self, name: str = None):
        """Activate the plugin registered under ``name`` and deactivate all
        others; runtime consumers (auto-fill, accumulation, dummy-object
        lowering) immediately see the newly active plugin's config."""
        plugins = self._named_deepspeed_plugins()
        if name not in plugins:
            raise KeyError(
                f"no DeepSpeedPlugin named {name!r}; registered: {sorted(plugins)}"
            )
        for key, plugin in plugins.items():
            if key != name:
                plugin._unselect()
        plugins[name].select(_from_accelerator_state=True)

    def __getattr__(self, name: str):
        # Delegate topology/process-control surface to PartialState.
        if name in ("_shared_state", "__dict__", "_partial"):
            raise AttributeError(name)
        partial = self.__dict__.get("_partial")
        if partial is not None and hasattr(partial, name):
            return getattr(partial, name)
        raise AttributeError(f"AcceleratorState has no attribute {name!r}")

    def __repr__(self):
        return self._partial.__repr__() + f"Mixed precision: {self.mixed_precision}\n"


class GradientState:
    """Gradient-accumulation bookkeeping shared between Accelerator,
    dataloaders, optimizer and scheduler wrappers (reference
    ``state.py:1134``: sync_gradients / num_steps / remainder /
    end_of_dataloader; the TPU build drops the ``xm.mark_step`` hook at
    :1228-1237 — dispatch is explicit)."""

    _shared_state: dict[str, Any] = {}

    def __init__(self, gradient_accumulation_plugin: GradientAccumulationPlugin | None = None):
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.active_dataloader = None
            self.dataloader_references = [None]
            self.plugin_kwargs = (
                gradient_accumulation_plugin.to_dict()
                if gradient_accumulation_plugin is not None
                else {}
            )
            self._is_xla_gradients_synced = True  # parity attr; always True
        if gradient_accumulation_plugin is not None and self.plugin_kwargs != gradient_accumulation_plugin.to_dict():
            self.plugin_kwargs = gradient_accumulation_plugin.to_dict()

    @property
    def initialized(self) -> bool:
        return "sync_gradients" in self.__dict__

    @classmethod
    def _reset_state(cls):
        cls._shared_state.clear()

    @property
    def num_steps(self) -> int:
        return self.plugin_kwargs.get("num_steps", 1)

    @property
    def adjust_scheduler(self) -> bool:
        return self.plugin_kwargs.get("adjust_scheduler", False)

    @property
    def sync_with_dataloader(self) -> bool:
        return self.plugin_kwargs.get("sync_with_dataloader", True)

    @property
    def end_of_dataloader(self) -> bool:
        if not self.in_dataloader:
            return False
        return self.active_dataloader.end_of_dataloader

    @property
    def remainder(self) -> int:
        if not self.in_dataloader:
            return -1
        return self.active_dataloader.remainder

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def _set_sync_gradients(self, sync_gradients: bool):
        self.sync_gradients = sync_gradients

    def _add_dataloader(self, dataloader):
        self.active_dataloader = dataloader
        self.dataloader_references.append(dataloader)

    def _remove_dataloader(self, dataloader):
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)
        self.active_dataloader = self.dataloader_references[-1]

    def __repr__(self):
        return (
            f"Sync gradients: {self.sync_gradients}\n"
            f"At end of current dataloader: {self.end_of_dataloader}\n"
            f"Extra samples added: {self.remainder}\n"
            f"Gradient accumulation plugin: {self.plugin_kwargs}\n"
        )


def is_initialized() -> bool:
    return AcceleratorState().initialized
