"""Collective operations over pytrees — eager (cross-host) and in-jit (mesh).

TPU-native re-design of ``/root/reference/src/accelerate/utils/operations.py``
(871 LoC). The reference dispatches each op per torch backend
(``_tpu_gather`` :306 / ``_gpu_gather`` :321, ``broadcast`` :543, ``reduce``
:728…). Here there are exactly two worlds:

* **eager** — host-level values (numpy / host-resident jax.Array) exchanged
  across *processes* (hosts) via ``jax.experimental.multihost_utils``. These
  are the ``gather_for_metrics`` / ``broadcast_object_list`` equivalents that
  must work outside ``jit``.
* **in-jit** — values inside a compiled step, where collectives are mesh ops
  (``lax.psum`` / ``all_gather`` / ``ppermute`` / ``all_to_all``) expressed
  against named axes. Exposed as thin wrappers (:mod:`jops`) for use under
  ``shard_map``; under plain ``jit`` + ``NamedSharding`` XLA inserts them
  automatically — which is the normal path.

Debug mode (``ACCELERATE_DEBUG_MODE=1``) verifies shape/dtype agreement
across processes before any eager collective, mirroring the reference's
``verify_operation`` (``operations.py:368-400``).
"""

from __future__ import annotations

import functools
import pickle
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .diagnostics.tracing import traced
from .utils.compat import axis_size

P = PartitionSpec


def _traced_collective(function: Callable):
    """Span-wrap an eager collective: these are the host-blocking
    rendezvous points where a multi-host hang actually *sits*, so the open
    span names the culprit op in watchdog hang reports and the merged
    timeline shows which host entered the collective late (the
    straggler)."""
    return traced(f"collective/{function.__name__}")(function)


class DistributedOperationException(Exception):
    """Raised in debug mode when ranks disagree on operand structure
    (reference ``operations.py:359``)."""


def pack_words(raw: bytes | np.ndarray) -> np.ndarray:
    """Bytes → the int32-WORD wire format every cross-host byte/raw-tensor
    broadcast in this package uses. int32 is the one dtype every backend
    moves verbatim: a real 2-process run showed this jaxlib's gloo CPU
    broadcast strides sub-4-byte elements through 4-byte slots (each u8
    lands at offset 4i), and >4-byte dtypes (int64/float64) are silently
    truncated by the jax round-trip under the default
    ``jax_enable_x64=False``. Pads to a 4-byte multiple; pair with
    :func:`unpack_words` and the original byte length."""
    if isinstance(raw, bytes):
        raw = np.frombuffer(raw, np.uint8)
    else:
        # reinterpret the array's BYTES — assigning a typed array into a
        # uint8 buffer would element-cast (truncating anything >255)
        raw = np.ascontiguousarray(raw).reshape(-1).view(np.uint8)
    padded = np.zeros((raw.size + 3) // 4 * 4, np.uint8)
    padded[: raw.size] = raw
    return padded.view(np.int32)


def word_count(nbytes: int) -> int:
    """How many int32 words :func:`pack_words` produces for ``nbytes``."""
    return (int(nbytes) + 3) // 4


def unpack_words(words, nbytes: int) -> bytes:
    """Inverse of :func:`pack_words`: the first ``nbytes`` payload bytes of
    an int32 word array (accepts jax or numpy arrays)."""
    return (
        np.ascontiguousarray(np.asarray(words, dtype=np.int32))
        .view(np.uint8)[: int(nbytes)]
        .tobytes()
    )


# ---------------------------------------------------------------------------
# pytree plumbing
# ---------------------------------------------------------------------------

def recursively_apply(
    func: Callable,
    data: Any,
    *args,
    test_type: Callable[[Any], bool] = lambda t: isinstance(t, (jax.Array, np.ndarray)),
    error_on_other_type: bool = False,
    **kwargs,
):
    """Apply ``func`` to every array leaf of a nested structure (reference
    ``operations.py:85``; here it is a jax.tree.map specialisation that keeps
    non-array leaves intact)."""

    def _apply(leaf):
        if test_type(leaf):
            return func(leaf, *args, **kwargs)
        if error_on_other_type:
            raise TypeError(f"Unsupported type {type(leaf)} passed to {func.__name__}")
        return leaf

    return jax.tree.map(_apply, data)


def is_array_like(x: Any) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


def send_to_device(tensor: Any, device=None, non_blocking: bool = True, skip_keys=None):
    """Move a pytree onto a device or (Named)Sharding (reference
    ``operations.py:136``). ``device`` may be a jax.Device, a Sharding, or
    None (default device)."""
    del non_blocking  # device_put is async by nature

    def _put(leaf):
        return jax.device_put(leaf, device)

    if skip_keys and isinstance(tensor, dict):
        return {
            k: (v if k in skip_keys else send_to_device(v, device)) for k, v in tensor.items()
        }
    return recursively_apply(_put, tensor)


def get_data_structure(data: Any):
    """Shape/dtype skeleton of a pytree (reference ``operations.py:171``)."""
    return recursively_apply(lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), data)


def listify(data: Any):
    """Convert all array leaves to plain Python lists (reference :197)."""
    return recursively_apply(lambda t: np.asarray(t).tolist(), data)


def convert_to_fp32(tensor: Any):
    """Upcast 16-bit float leaves to fp32 (reference
    ``convert_outputs_to_fp32``/``convert_to_fp32`` :787-829)."""

    def _upcast(t):
        if t.dtype in (jnp.bfloat16, jnp.float16):
            return t.astype(jnp.float32)
        return t

    return recursively_apply(_upcast, tensor)


def find_device(data: Any):
    """First device found in a pytree (reference :831)."""
    for leaf in jax.tree.leaves(data):
        if isinstance(leaf, jax.Array):
            try:
                return next(iter(leaf.devices()))
            except Exception:
                continue
    return None


def find_batch_size(data: Any) -> int | None:
    for leaf in jax.tree.leaves(data):
        if is_array_like(leaf) and leaf.ndim > 0:
            return leaf.shape[0]
    return None


def slice_tensors(data: Any, tensor_slice: slice, process_index=None, num_processes=None):
    """Slice every leaf along dim 0 (reference ``operations.py:585``)."""
    return recursively_apply(lambda t: t[tensor_slice], data)


def concatenate(data: list[Any], dim: int = 0):
    """Concatenate a list of same-structure pytrees leafwise (reference :605)."""
    if isinstance(data[0], (tuple, list)):
        return type(data[0])(concatenate([d[i] for d in data], dim=dim) for i in range(len(data[0])))
    if isinstance(data[0], dict):
        return type(data[0])({k: concatenate([d[k] for d in data], dim=dim) for k in data[0]})
    if not is_array_like(data[0]):
        raise TypeError(f"Cannot concatenate {type(data[0])}")
    return jnp.concatenate([jnp.asarray(d) for d in data], axis=dim)


# ---------------------------------------------------------------------------
# debug-mode verification
# ---------------------------------------------------------------------------

def _state():
    from .state import PartialState

    return PartialState()


def verify_operation(function: Callable):
    """Debug-mode wrapper: all processes must agree on operand metadata
    (reference ``operations.py:368-400``)."""

    @functools.wraps(function)
    def wrapper(*args, **kwargs):
        state = _state()
        if not state.debug or state.num_processes == 1:
            return function(*args, **kwargs)
        tensor = kwargs.get("tensor", args[0] if args else None)
        meta = jax.tree.map(
            lambda t: (tuple(t.shape), str(t.dtype)) if is_array_like(t) else None, tensor
        )
        from jax.experimental import multihost_utils

        all_meta = gather_object([meta])
        if not all(m == all_meta[0] for m in all_meta):
            raise DistributedOperationException(
                f"Mismatch between processes in {function.__name__}: "
                + "; ".join(f"process {i}: {m}" for i, m in enumerate(all_meta))
            )
        return function(*args, **kwargs)

    return wrapper


# ---------------------------------------------------------------------------
# eager collectives (outside jit)
# ---------------------------------------------------------------------------

def _materialize(t: jax.Array | np.ndarray) -> np.ndarray | jax.Array:
    """Bring a possibly device-sharded array to a host-global view."""
    if isinstance(t, jax.Array):
        if not t.is_fully_addressable:
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(t, tiled=True)
        return np.asarray(jax.device_get(t))
    return t


@verify_operation
@_traced_collective
def gather(tensor: Any):
    """Global view of per-shard data, concatenated on dim 0 (reference
    ``gather`` :423). A globally-sharded ``jax.Array`` *is already* the
    gathered value — we materialise it on host; multi-host host-local values
    go through ``process_allgather``."""
    state = _state()

    def _gather(t):
        if isinstance(t, jax.Array):
            return _materialize(t)
        if state.num_processes > 1:
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(np.asarray(t), tiled=True)
        return t

    return recursively_apply(_gather, tensor)


@_traced_collective
def gather_object(object: list[Any]) -> list[Any]:
    """Gather arbitrary picklable objects from all processes into one list
    (reference ``gather_object`` :449)."""
    state = _state()
    if state.num_processes == 1:
        return list(object)
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(object), dtype=np.uint8)
    sizes = multihost_utils.process_allgather(np.array([payload.size], dtype=np.int64))
    max_size = int(sizes.max())
    padded = np.zeros(max_size, dtype=np.uint8)
    padded[: payload.size] = payload
    all_payloads = multihost_utils.process_allgather(padded)  # [procs, max_size]
    out: list[Any] = []
    for i in range(all_payloads.shape[0]):
        out.extend(pickle.loads(all_payloads[i, : int(sizes[i, 0])].tobytes()))
    return out


@verify_operation
@_traced_collective
def broadcast(tensor: Any, from_process: int = 0):
    """Broadcast array leaves from one process to all (reference :543)."""
    state = _state()
    if state.num_processes == 1:
        return tensor
    from jax.experimental import multihost_utils

    def _bcast(t):
        is_source = state.process_index == from_process
        a = np.asarray(_materialize(t))
        if a.dtype.itemsize != 4:
            # non-4-byte dtypes ride the wire as int32 WORDS — see
            # pack_words for the gloo/x64 rationale; every rank knows the
            # leaf's shape/dtype (broadcast semantics: all ranks pass a
            # same-structured operand), so no metadata exchange is needed
            nbytes = a.nbytes
            words = (
                pack_words(np.ascontiguousarray(a).tobytes())
                if is_source
                else np.zeros(word_count(nbytes), np.int32)
            )
            data = multihost_utils.broadcast_one_to_all(words, is_source=is_source)
            return (
                np.frombuffer(unpack_words(data, nbytes), a.dtype)
                .reshape(a.shape)
                .copy()
            )
        return multihost_utils.broadcast_one_to_all(a, is_source=is_source)

    return recursively_apply(_bcast, tensor)


@_traced_collective
def broadcast_object_list(object_list: list[Any], from_process: int = 0) -> list[Any]:
    """In-place broadcast of picklable objects (reference :564)."""
    state = _state()
    if state.num_processes == 1:
        return object_list
    from jax.experimental import multihost_utils

    payload = pickle.dumps(list(object_list))
    is_source = state.process_index == from_process
    size = multihost_utils.broadcast_one_to_all(
        np.array([len(payload)], dtype=np.int64), is_source=is_source
    )
    nbytes = int(size[0])
    # ship the bytes as int32 WORDS, not uint8 — see pack_words for why
    words = (
        pack_words(payload)
        if is_source
        else np.zeros(word_count(nbytes), dtype=np.int32)
    )
    data = multihost_utils.broadcast_one_to_all(words, is_source=is_source)
    received = pickle.loads(unpack_words(data, nbytes))
    object_list[:] = received
    return object_list


def _dim0_shard_count_of_sharding(sharding) -> int:
    """How many ways a NamedSharding splits dim 0."""
    spec = getattr(sharding, "spec", None)
    if spec is None or len(spec) == 0 or spec[0] is None:
        return 1
    axes = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
    n = 1
    for ax in axes:
        n *= sharding.mesh.shape[ax]
    return n


def _dim0_shard_count(t: jax.Array) -> int:
    """How many ways dim 0 of a jax.Array is split by its sharding."""
    if not isinstance(t, jax.Array) or t.ndim == 0:
        return 1
    return _dim0_shard_count_of_sharding(getattr(t, "sharding", None))


@verify_operation
@_traced_collective
def reduce(tensor: Any, reduction: str = "mean", scale: float = 1.0):
    """Elementwise reduce of per-participant values (reference ``reduce``
    :728; XLA path :750-757 applied sum+scale). The participants are the
    data-parallel shards: a batch-sharded global array of shape
    ``[P·n, ...]`` reduces to ``[n, ...]`` combining its P shards —
    the analog of each torch rank holding an ``[n, ...]`` tensor. Host
    values on multi-host reduce across processes."""
    state = _state()

    def _reduce(t):
        n_shards = _dim0_shard_count(t) if isinstance(t, jax.Array) else 1
        value = np.asarray(_materialize(t))
        if state.num_processes > 1 and not isinstance(t, jax.Array):
            from jax.experimental import multihost_utils

            stacked = multihost_utils.process_allgather(value)
            out = stacked.sum(axis=0) * scale
            if reduction == "mean":
                out = out / state.num_processes
            return out
        if n_shards > 1 and value.shape[0] % n_shards == 0:
            stacked = value.reshape((n_shards, value.shape[0] // n_shards) + value.shape[1:])
            out = stacked.sum(axis=0) * scale
            if reduction == "mean":
                out = out / n_shards
            return out
        return value * scale

    return recursively_apply(_reduce, tensor)


@verify_operation
@_traced_collective
def pad_across_processes(tensor: Any, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
    """Pad each process's arrays to the max size along ``dim`` so a gather
    can concatenate them (reference :632)."""
    state = _state()

    def _pad(t):
        t = np.asarray(_materialize(t))
        if t.ndim == 0 or dim >= t.ndim:
            return t
        if state.num_processes == 1:
            return t
        from jax.experimental import multihost_utils

        sizes = multihost_utils.process_allgather(np.array([t.shape[dim]], dtype=np.int64))
        max_size = int(sizes.max())
        if max_size == t.shape[dim]:
            return t
        pad_width = [(0, 0)] * t.ndim
        pad_width[dim] = (max_size - t.shape[dim], 0) if pad_first else (0, max_size - t.shape[dim])
        return np.pad(t, pad_width, constant_values=pad_index)

    return recursively_apply(_pad, tensor)


def pad_input_tensors(tensor: Any, batch_size: int, num_processes: int, dim: int = 0):
    """Pad a batch so it divides evenly across processes by repeating final
    rows (reference ``pad_input_tensors`` :687)."""
    remainder = batch_size % num_processes
    if remainder == 0:
        return tensor
    missing = num_processes - remainder

    def _pad(t):
        t = np.asarray(t)
        if t.ndim == 0 or t.shape[dim] != batch_size:
            return t
        take = [t[-1:]] * missing
        return np.concatenate([t] + take, axis=dim)

    return recursively_apply(_pad, tensor)


# ---------------------------------------------------------------------------
# in-jit collectives over named mesh axes (for shard_map bodies / kernels)
# ---------------------------------------------------------------------------

class jops:
    """Named-axis collectives usable inside ``shard_map``. The normal pjit
    path never calls these explicitly — XLA inserts collectives from the
    shardings — but ring attention, local-SGD averaging and the trigger API
    (reference ``accelerator.py:2252-2309``) use them directly."""

    psum = staticmethod(lax.psum)
    pmean = staticmethod(lax.pmean)
    pmax = staticmethod(lax.pmax)
    pmin = staticmethod(lax.pmin)
    ppermute = staticmethod(lax.ppermute)
    all_to_all = staticmethod(lax.all_to_all)
    axis_index = staticmethod(lax.axis_index)

    @staticmethod
    def all_gather(x, axis_name: str, *, axis: int = 0, tiled: bool = True):
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)

    @staticmethod
    def ring_shift(x, axis_name: str, shift: int = 1):
        """Rotate shards around the ring (KV rotation for ring attention)."""
        n = axis_size(axis_name)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(x, axis_name, perm)


def gather_sizes_across_processes(size: int) -> list[int]:
    """All processes' values of a Python int (helper for uneven data ends)."""
    state = _state()
    if state.num_processes == 1:
        return [size]
    from jax.experimental import multihost_utils

    sizes = multihost_utils.process_allgather(np.array([size], dtype=np.int64))
    return [int(s) for s in sizes.reshape(-1)]


def copy_tensor_to_devices(tensor):
    """Replicate a host value onto every local device (reference
    ``copy_tensor_to_devices`` — XLA path)."""
    state = _state()
    return jax.device_put(tensor, NamedSharding(state.mesh, P()))
