"""The Accelerator facade — same user contract as the reference
(``/root/reference/src/accelerate/accelerator.py``, 3610 LoC), TPU-native
execution.

Design (SURVEY §7): ``prepare()`` does not mutate user objects in place; it
computes shardings over the named mesh and returns wrappers whose work runs
inside jit-compiled functions. ``backward(loss)`` consumes a deferred loss
(see :mod:`accelerate_tpu.lazy`) and runs a cached compiled
``value_and_grad``; the optimizer wrapper applies updates in a second jitted
step. Collectives (``gather``/``reduce``/…) come from
:mod:`accelerate_tpu.operations`.
"""

from __future__ import annotations

import contextlib
import math
import os
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import operations as ops
from .analysis.sanitizer import Sanitizer
from .analysis.sanitizer import get_active_sanitizer as _get_sanitizer
from .analysis.sanitizer import set_active_sanitizer as _set_sanitizer
from .data_loader import DataLoaderShard, prepare_data_loader, skip_first_batches
from .lazy import Deferred, clear_caches, grad_fn_for
from .logging import get_logger
from .mesh import data_sharding, replicated
from .modules import Model, PreparedModel, extract_model_from_parallel
from .optimizer import AcceleratedOptimizer
from .parallel.sharding import (
    infer_param_sharding,
    opt_state_sharding_like,
    shard_params,
)
from .scheduler import AcceleratedScheduler
from .state import AcceleratorState, GradientState, PartialState
from .utils.dataclasses import (
    DataLoaderConfiguration,
    DeepSpeedPlugin,
    DiagnosticsPlugin,
    DistributedDataParallelKwargs,
    DistributedType,
    FaultTolerancePlugin,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    GradScalerKwargs,
    InitProcessGroupKwargs,
    MegatronLMPlugin,
    MeshPlugin,
    PrecisionType,
    ProfileKwargs,
    ProjectConfiguration,
)

logger = get_logger(__name__)


class _PendingNorm:
    """Return value of a fused-path clip: the true pre-clip norm, resolved
    after the fused step ran (or by flushing to the split path on demand)."""

    def __init__(self, accelerator, opt):
        self._accelerator = accelerator
        self._opt = opt

    def _resolve(self):
        if self._opt._last_norm is not None:
            return self._opt._last_norm
        if self._opt._pending_loss is not None:
            self._accelerator._flush_pending(self._opt)  # sets _last_norm via clip
        return self._opt._last_norm if self._opt._last_norm is not None else jnp.asarray(0.0)

    def item(self):
        return float(np.asarray(self._resolve()))

    def __float__(self):
        return self.item()

    def __array__(self, dtype=None):
        return np.asarray(self._resolve(), dtype=dtype)

    def __lt__(self, o): return self.item() < o
    def __le__(self, o): return self.item() <= o
    def __gt__(self, o): return self.item() > o
    def __ge__(self, o): return self.item() >= o
    def __add__(self, o): return self.item() + o
    def __radd__(self, o): return o + self.item()
    def __mul__(self, o): return self.item() * o
    def __rmul__(self, o): return o * self.item()
    def __truediv__(self, o): return self.item() / o
    def __sub__(self, o): return self.item() - o
    def __rsub__(self, o): return o - self.item()

    def __repr__(self):
        return f"PendingNorm({self._opt._last_norm})"


class ProfileContext:
    """Schedule-driven ``jax.profiler`` session (the reference's
    torch.profiler schedule semantics, ``dataclasses.py:406-513``): call
    ``step()`` once per training step; capture runs only during 'active'
    phases of the wait/warmup/active/repeat cycle."""

    def __init__(self, handler: ProfileKwargs, trace_dir: str, telemetry=None):
        self.handler = handler
        self.trace_dir = trace_dir
        self.schedule = handler.build_schedule()
        self.step_num = 0
        self.active_steps = 0
        self._tracing = False
        self._telemetry = telemetry
        if handler.with_flops:
            # record XLA cost analyses of every compiled step executed
            # during the session (dumped to flops.json at exit)
            from .lazy import set_cost_collection

            set_cost_collection(True)

    def _maybe_start(self):
        if self.schedule(self.step_num) == "active" and not self._tracing:
            jax.profiler.start_trace(
                self.trace_dir,
                create_perfetto_trace=bool(self.handler.with_stack),
            )
            self._tracing = True

    def _maybe_stop(self):
        if self._tracing and self.schedule(self.step_num) != "active":
            jax.profiler.stop_trace()
            self._tracing = False

    def step(self):
        if self.schedule(self.step_num) == "active":
            self.active_steps += 1
        if self.handler.profile_memory and self.schedule(self.step_num) == "active":
            import os as _os

            jax.profiler.save_device_memory_profile(
                _os.path.join(self.trace_dir, f"memory_{self.step_num}.prof")
            )
        self.step_num += 1
        self._maybe_stop()
        self._maybe_start()

    def _finish(self):
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
        if self.handler.with_flops:
            import json as _json
            import os as _os

            from .lazy import PROFILE_COST_STATS, set_cost_collection

            set_cost_collection(False)
            # the tracer creates trace_dir only when a window went active
            _os.makedirs(self.trace_dir, exist_ok=True)
            with open(_os.path.join(self.trace_dir, "flops.json"), "w") as f:
                _json.dump(
                    {
                        "compiled_programs": PROFILE_COST_STATS,
                        "total_flops": sum(
                            s["flops"] for s in PROFILE_COST_STATS if s.get("flops")
                        ),
                    },
                    f,
                )
        if self._telemetry:
            self._telemetry.record_profile(
                trace_dir=self.trace_dir,
                steps=self.step_num,
                active_steps=self.active_steps,
            )


class Accelerator:
    """Create once, ``prepare()`` your objects, train (reference
    ``Accelerator`` class ``accelerator.py:162``)."""

    _os_kernel_checked = False  # one warning per process, not per instance
    _dynamo_warned = False      # ditto for the no-op dynamo_backend knob

    def __init__(
        self,
        device_placement: bool = True,
        split_batches: bool = False,
        mixed_precision: str | None = None,
        gradient_accumulation_steps: int = 1,
        cpu: bool = False,
        dataloader_config: DataLoaderConfiguration | None = None,
        deepspeed_plugin: DeepSpeedPlugin | dict[str, DeepSpeedPlugin] | None = None,
        fsdp_plugin: FullyShardedDataParallelPlugin | None = None,
        megatron_lm_plugin=None,
        mesh_plugin: MeshPlugin | None = None,
        context_parallel_plugin=None,
        rng_types: list[str] | None = None,
        log_with=None,
        project_dir: str | None = None,
        project_config: ProjectConfiguration | None = None,
        gradient_accumulation_plugin: GradientAccumulationPlugin | None = None,
        step_scheduler_with_optimizer: bool = True,
        kwargs_handlers: list | None = None,
        dynamo_backend=None,  # accepted for parity; XLA always compiles
        even_batches: bool = True,
        use_seedable_sampler: bool = False,
        telemetry: bool | None = None,
        fault_tolerance: FaultTolerancePlugin | bool | None = None,
        diagnostics: DiagnosticsPlugin | bool | None = None,
        sanitize: bool | None = None,
    ):
        self.project_configuration = project_config or ProjectConfiguration(project_dir=project_dir)
        if project_dir is not None and self.project_configuration.project_dir is None:
            self.project_configuration.set_directories(project_dir)

        # plugin resolution from args/env (reference :293-376)
        if deepspeed_plugin is None and os.environ.get("ACCELERATE_USE_DEEPSPEED", "false") == "true":
            deepspeed_plugin = DeepSpeedPlugin()
        if fsdp_plugin is None and os.environ.get("ACCELERATE_USE_FSDP", "false") == "true":
            fsdp_plugin = FullyShardedDataParallelPlugin()
        # several named plugins may coexist (reference supports a dict with
        # runtime selection, ``utils/deepspeed.py:25-41``); the first is
        # active until ``state.select_deepspeed_plugin(name)`` switches
        if isinstance(deepspeed_plugin, dict):
            if not deepspeed_plugin:
                raise ValueError("deepspeed_plugin dict must not be empty")
            for key, p in deepspeed_plugin.items():
                if not isinstance(p, DeepSpeedPlugin):
                    raise TypeError(
                        f"deepspeed_plugin[{key!r}] must be a DeepSpeedPlugin, "
                        f"got {type(p).__name__}"
                    )
                p._unselect()
            next(iter(deepspeed_plugin.values())).select(_from_accelerator_state=True)
        self._deepspeed_plugins = deepspeed_plugin
        active_ds = (
            next(p for p in deepspeed_plugin.values() if p.selected)
            if isinstance(deepspeed_plugin, dict)
            else deepspeed_plugin
        )
        if active_ds is not None and fsdp_plugin is None:
            fsdp_plugin = active_ds.to_fsdp_plugin()
        self.fsdp_plugin = fsdp_plugin
        self.megatron_lm_plugin = megatron_lm_plugin
        self.context_parallel_plugin = context_parallel_plugin

        # Megatron facade lowers onto mesh axes (SURVEY §2.2: tp_degree →
        # tp axis; pp_degree → pp axis, which runs the GPipe schedule in
        # parallel/pipeline.py for stacked-layer models). Megatron-SP shards
        # activations over the EXISTING tp group, which has no 1:1 GSPMD
        # mapping here; the cp axis is this framework's (strictly more
        # general) sequence sharding, so the flag only points users there
        # rather than silently multiplying the device requirement.
        if megatron_lm_plugin is not None and mesh_plugin is None:
            if getattr(megatron_lm_plugin, "sequence_parallelism", False):
                logger.info(
                    "Megatron sequence_parallelism maps onto the cp mesh axis "
                    "here; size it explicitly (MeshPlugin(cp=...) or "
                    "--mesh_cp) to shard sequence activations"
                )
            # duck-typed: upstream-accelerate MegatronLMPlugin objects have
            # the degree fields but not our to_mesh_axes()
            if hasattr(megatron_lm_plugin, "to_mesh_axes"):
                mesh_plugin = MeshPlugin(**megatron_lm_plugin.to_mesh_axes())
            else:
                mesh_plugin = MeshPlugin(
                    tp=getattr(megatron_lm_plugin, "tp_degree", 1),
                    pp=getattr(megatron_lm_plugin, "pp_degree", 1),
                )

        # torch.compile has no TPU meaning (XLA always compiles); accept the
        # knob for config parity but never silently — a user passing a real
        # backend should know it does nothing here.
        self.dynamo_backend = dynamo_backend
        if (
            dynamo_backend is not None
            and str(dynamo_backend).lower() != "no"  # reference spells it "NO"
            and not Accelerator._dynamo_warned
        ):
            Accelerator._dynamo_warned = True
            logger.warning(
                "dynamo_backend=%r has no effect on TPU: every prepared step "
                "is already XLA-compiled. The flag is accepted for config "
                "compatibility only.",
                dynamo_backend,
            )

        # kwargs handlers (reference :387-421)
        from .ops.fp8 import FP8RecipeKwargs

        self.scaler_handler = None
        self.init_handler = None
        self.profile_handler = None
        self.fp8_recipe_handler = None
        self.ddp_handler = None
        for handler in kwargs_handlers or []:
            if isinstance(handler, GradScalerKwargs):
                self.scaler_handler = handler
            elif isinstance(handler, InitProcessGroupKwargs):
                self.init_handler = handler
            elif isinstance(handler, ProfileKwargs):
                self.profile_handler = handler
            elif isinstance(handler, FP8RecipeKwargs):
                self.fp8_recipe_handler = handler
            elif isinstance(handler, DistributedDataParallelKwargs):
                self.ddp_handler = handler

        init_kwargs = self.init_handler.to_kwargs() if self.init_handler else {}
        self.state = AcceleratorState(
            mixed_precision=mixed_precision,
            cpu=cpu,
            mesh_plugin=mesh_plugin,
            fsdp_plugin=fsdp_plugin,
            _from_accelerator=True,
            **init_kwargs,
        )
        # AcceleratorState is shared (Borg): only publish plugins this
        # Accelerator actually brought — a later plain Accelerator() must
        # not clear an earlier one's registration
        if self._deepspeed_plugins is not None:
            self.state.deepspeed_plugins = self._deepspeed_plugins

        # attention routing: bake the cp mode + mesh into every step compiled
        # from here on (models read this at trace time)
        from .ops.attention import AttentionContext, set_attention_context

        cp_mode = None
        pp_microbatches = 0
        mesh_shape = dict(self.state.mesh.shape)
        if mesh_shape.get("pp", 1) > 1:
            # fail at construction, not at the first forward
            from .parallel.pipeline import validate_pipeline_axes

            validate_pipeline_axes(mesh_shape)

            # honour the requested schedule depth (reference field
            # ``num_micro_batches``, utils/dataclasses.py:1912). Our plugin
            # defaults to 0 (= auto) so an explicit 1 is honoured; foreign
            # duck-typed plugins default to 1, which means "unset" there —
            # see the MegatronLMPlugin docstring for the coercion rule
            _mb = getattr(megatron_lm_plugin, "num_micro_batches", 0) or 0
            if not isinstance(megatron_lm_plugin, MegatronLMPlugin):
                _mb = _mb if _mb > 1 else 0
            pp_microbatches = _mb
        if mesh_shape.get("cp", 1) > 1:
            if context_parallel_plugin is not None:
                cp_mode = context_parallel_plugin.mode
            else:
                # honour `launch --cp_mode` / config (written as ACCELERATE_CP_MODE);
                # a cp axis in the mesh defaults to ring attention
                cp_mode = os.environ.get("ACCELERATE_CP_MODE", "ring")
                if cp_mode not in ("ring", "ulysses", "allgather"):
                    raise ValueError(
                        f"ACCELERATE_CP_MODE={cp_mode!r} — expected ring|ulysses|allgather"
                    )
            import re as _re

            timeout_match = _re.search(
                r"collective_call_terminate_timeout_seconds=(\d+)",
                os.environ.get("XLA_FLAGS", ""),
            )
            # ≥300s gives a 1-core host room to schedule the subgroup
            # collectives; a smaller value is as unsafe as none. (A flag
            # exported after backend init is undetectable — the launcher
            # and test conftest both set it before.)
            timeout_ok = timeout_match is not None and int(timeout_match.group(1)) >= 300
            if (
                cp_mode == "ring"
                and self.device.platform == "cpu"
                and mesh_shape.get("dp", 1) > 1
                and not timeout_ok
            ):
                # On few-core hosts, XLA CPU's default 40s collective
                # rendezvous window ABORTS training programs that mix
                # per-dp-replica cp ppermute subgroups with dp reduction
                # groups (slow cross-subgroup scheduling, not a true
                # deadlock — verified to complete with the window raised).
                # The launcher/conftest set
                # --xla_cpu_collective_call_terminate_timeout_seconds, which
                # lets the real ring run; without it, protect the user with
                # the numerically identical allgather formulation:
                logger.warning(
                    "cp_mode='ring' with dp>1 runs as 'allgather' on the CPU "
                    "debug backend without "
                    "--xla_cpu_collective_call_terminate_timeout_seconds in "
                    "XLA_FLAGS (the default 40s rendezvous window aborts); "
                    "TPU executes the real ring"
                )
                cp_mode = "allgather"
        # Megatron-SP (reference dataclasses.py:1916-1919,2112): under
        # tp>1 the norm/residual-region activations are sequence-sharded
        # over the SAME tp group — models consult this flag at their
        # residual sharding constraints (models/llama.py residual_spec)
        # and GSPMD inserts the all-gather into / reduce-scatter out of
        # the matmul regions that Megatron codes by hand.
        megatron_sp = bool(
            megatron_lm_plugin is not None
            and getattr(megatron_lm_plugin, "sequence_parallelism", False)
            and mesh_shape.get("tp", 1) > 1
        )
        set_attention_context(
            AttentionContext(
                mesh=self.state.mesh, cp_mode=cp_mode,
                pipeline_microbatches=pp_microbatches, megatron_sp=megatron_sp,
            )
        )

        self.dataloader_config = dataloader_config or DataLoaderConfiguration(
            split_batches=split_batches,
            even_batches=even_batches,
            use_seedable_sampler=use_seedable_sampler,
        )
        if gradient_accumulation_plugin is None:
            env_steps = int(os.environ.get("ACCELERATE_GRADIENT_ACCUMULATION_STEPS", 1))
            steps = gradient_accumulation_steps if gradient_accumulation_steps > 1 else env_steps
            if steps == 1 and active_ds is not None:
                # a ds-config's accumulation governs the loop (reference
                # merges it in ``accelerator.py:1669-1830``)
                steps = active_ds.gradient_accumulation_steps
            gradient_accumulation_plugin = GradientAccumulationPlugin(num_steps=steps)
        self.gradient_state = GradientState(gradient_accumulation_plugin=gradient_accumulation_plugin)

        self.device_placement = device_placement
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer
        self.rng_types = rng_types or ["python", "numpy", "jax"]

        # one-time old-kernel warning (reference accelerator.py:544)
        if not Accelerator._os_kernel_checked:
            Accelerator._os_kernel_checked = True
            from .utils.other import check_os_kernel

            check_os_kernel()

        # fp16 → dynamic loss scaler (reference GradScaler semantics,
        # accelerator.py:496-520); bf16 needs none. GradScalerKwargs drives
        # init/growth/backoff; enabled=False opts out entirely.
        self._loss_scale = None
        if self.mixed_precision == "fp16" and (
            self.scaler_handler is None or self.scaler_handler.enabled
        ):
            from .optimizer import LossScaler

            h = self.scaler_handler
            self._loss_scale = LossScaler(
                init_scale=h.init_scale if h else 65536.0,
                growth_factor=h.growth_factor if h else 2.0,
                backoff_factor=h.backoff_factor if h else 0.5,
                growth_interval=h.growth_interval if h else 2000,
            )

        # DDP communication hook analog: compressed dp-axis gradient
        # reduction (reference DDPCommunicationHookType, dataclasses.py:117).
        # bf16/fp16 halve the gradient-sync bytes-on-wire — on a multi-slice
        # DCN mesh that is the same lever the reference's hook pulls on the
        # NCCL ring. DP-only, like the reference's DDP scope.
        self._grad_comm_hook = None
        hook = str(getattr(self.ddp_handler, "comm_hook", "no") or "no").lower()
        if hook not in ("no", "none"):
            shape = dict(self.mesh.shape) if self.mesh is not None else {}
            dp_only = all(shape.get(a, 1) == 1 for a in ("tp", "pp", "cp", "ep", "fsdp"))
            if hook in ("bf16", "fp16") and dp_only and shape.get("dp", 1) > 1:
                self._grad_comm_hook = hook
            elif hook in ("bf16", "fp16"):
                logger.warning(
                    "comm_hook=%r needs a data-parallel-only mesh with dp>1 "
                    "(got %s); gradients keep the default full-precision "
                    "reduction", hook, shape,
                )
            else:
                logger.warning(
                    "comm_hook=%r is not supported on TPU (powerSGD-style "
                    "hooks have no XLA lowering here); choose 'bf16' or "
                    "'fp16'", hook,
                )

        self._models: list[PreparedModel] = []
        self._optimizers: list[AcceleratedOptimizer] = []
        self._schedulers: list[AcceleratedScheduler] = []
        self._dataloaders: list[DataLoaderShard] = []
        self._custom_objects: list = []
        self.step = 0
        self.flag_tensor = None

        from .tracking import filter_trackers

        self.log_with = filter_trackers(log_with, self.logging_dir)
        self.trackers = []

        # step-level telemetry (telemetry.py): opt-in via the constructor or
        # ACCELERATE_TELEMETRY=1; disabled holds the no-op singleton so the
        # hot path pays one attribute read
        from .telemetry import NULL_TELEMETRY, TelemetryRecorder, set_active_recorder
        from .utils.environment import parse_flag_from_env

        if telemetry is None:
            telemetry = parse_flag_from_env("ACCELERATE_TELEMETRY")
        if telemetry:
            self.telemetry = TelemetryRecorder(
                logging_dir=self.logging_dir,
                tracker_sink=self._telemetry_tracker_sink,
            )
            set_active_recorder(self.telemetry)
        else:
            self.telemetry = NULL_TELEMETRY
            # Borg semantics: the newest Accelerator owns the process-wide
            # observability state — a disabled one must silence a stale
            # recorder left by an earlier telemetry=True instance, or
            # "disabled" keeps writing to the old run's trail
            from .lazy import set_compile_callback

            set_active_recorder(None)
            set_compile_callback(None)

        # in-process metrics registry (metrics/): ACCELERATE_METRICS=1 arms
        # the GET /metrics aggregation surface the telemetry/span hooks
        # feed (main-process-gated; the sidecar `accelerate-tpu metrics
        # export` covers jobs that leave this off)
        from .metrics.registry import MetricsRegistry, set_active_registry

        if parse_flag_from_env("ACCELERATE_METRICS"):
            self.metrics_registry = MetricsRegistry()
            set_active_registry(self.metrics_registry)
        else:
            from .metrics.registry import get_active_registry

            # no takeover here (unlike telemetry): a registry set by an
            # outer owner — the serve CLI's /metrics surface — must keep
            # aggregating across Accelerator constructions
            self.metrics_registry = get_active_registry()

        # diagnostics (tracing + hang watchdog, diagnostics/): opt-in via
        # the constructor or ACCELERATE_DIAGNOSTICS=1; same Borg takeover
        # semantics as telemetry — the newest Accelerator owns the
        # process-wide tracer/watchdog
        from .diagnostics import NULL_TRACER, Tracer, Watchdog, get_tracer, set_active_tracer
        from .diagnostics.watchdog import get_active_watchdog

        if diagnostics is None:
            diagnostics = parse_flag_from_env("ACCELERATE_DIAGNOSTICS")
        if diagnostics is True:
            diagnostics = DiagnosticsPlugin()
        elif diagnostics is False:
            diagnostics = None
        self.diagnostics_plugin: DiagnosticsPlugin | None = diagnostics
        self.tracer = NULL_TRACER
        self.watchdog = None
        stale_watchdog = get_active_watchdog()
        if stale_watchdog is not None:
            stale_watchdog.stop()
        stale_tracer = get_tracer()
        if stale_tracer:
            # flush+close BEFORE a new tracer appends its clock_sync: the
            # old instance's buffered events must not land after the new
            # epoch marker, or the merge shifts them with the wrong offset
            stale_tracer.close()
        if diagnostics is not None and diagnostics.tracing:
            self.tracer = Tracer(
                logging_dir=self.logging_dir,
                buffer_events=diagnostics.trace_buffer_events,
            )
            set_active_tracer(self.tracer)
        else:
            set_active_tracer(None)
        if diagnostics is not None and diagnostics.watchdog:
            self.watchdog = Watchdog(
                logging_dir=self.logging_dir,
                multiplier=diagnostics.watchdog_multiplier,
                floor_seconds=diagnostics.watchdog_floor_seconds,
                check_interval_seconds=diagnostics.watchdog_check_seconds,
                ema_alpha=diagnostics.watchdog_ema_alpha,
                heartbeat_interval_seconds=diagnostics.heartbeat_interval_seconds,
                grace_seconds=diagnostics.watchdog_grace_seconds,
                telemetry_tail=diagnostics.watchdog_telemetry_tail,
                preempt_on_hang=diagnostics.preempt_on_hang,
                telemetry=self.telemetry if self.telemetry else None,
            )
            self.watchdog.start()

        # runtime sanitizer (analysis/): opt-in via the constructor or
        # ACCELERATE_SANITIZE=1 — recompile naming, donation report,
        # per-host collective digests, NaN/inf loss probe. Same Borg
        # takeover as telemetry: the newest Accelerator owns the
        # process-wide sanitizer, and disabled mode is one global read
        # at every instrumentation site
        if sanitize is None:
            sanitize = parse_flag_from_env("ACCELERATE_SANITIZE")
        if sanitize:
            self.sanitizer = Sanitizer(logging_dir=self.logging_dir)
            _set_sanitizer(self.sanitizer)
        else:
            self.sanitizer = None
            _set_sanitizer(None)

        # fault tolerance (resilience subsystem): opt-in via the
        # constructor, ACCELERATE_FAULT_TOLERANCE=1, or — so launcher
        # restarts are preemption-safe too — ACCELERATE_AUTO_RESUME=1
        if fault_tolerance is None and (
            parse_flag_from_env("ACCELERATE_FAULT_TOLERANCE")
            or parse_flag_from_env("ACCELERATE_AUTO_RESUME")
        ):
            fault_tolerance = True
        if fault_tolerance is True:
            fault_tolerance = FaultTolerancePlugin()
        elif fault_tolerance is False:
            fault_tolerance = None
        self.fault_tolerance_plugin: FaultTolerancePlugin | None = fault_tolerance
        self._preemption_handler = None
        self._ft_boundary_count = 0
        if fault_tolerance is not None:
            fault_tolerance.export_io_env()
            from .resilience.preemption import PreemptionHandler

            self._preemption_handler = PreemptionHandler(
                handle_sigint=fault_tolerance.handle_sigint,
                monitor_maintenance=fault_tolerance.monitor_maintenance,
                poll_seconds=fault_tolerance.maintenance_poll_seconds,
                handle_signals=fault_tolerance.handle_signals,
            )
            self._preemption_handler.install()

    # ------------------------------------------------------------------
    # properties delegating to state (reference :525-760)
    # ------------------------------------------------------------------

    @property
    def distributed_type(self):
        return self.state.distributed_type

    @property
    def deepspeed_plugin(self):
        """The ACTIVE DeepSpeedPlugin (or None): with a dict of named
        plugins, selection via ``state.select_deepspeed_plugin(name)``
        changes what this returns (reference ``utils/deepspeed.py:25``)."""
        if self._deepspeed_plugins is None:
            return None
        from .utils.deepspeed import get_active_deepspeed_plugin

        return get_active_deepspeed_plugin(self.state)

    @property
    def deepspeed_plugins(self):
        return self._deepspeed_plugins

    @property
    def num_processes(self):
        return self.state.num_processes

    @property
    def process_index(self):
        return self.state.process_index

    @property
    def local_process_index(self):
        return self.state.local_process_index

    @property
    def device(self):
        return self.state.device

    @property
    def mesh(self):
        return self.state.mesh

    @property
    def is_main_process(self):
        return self.state.is_main_process

    @property
    def is_local_main_process(self):
        return self.state.is_local_main_process

    @property
    def is_last_process(self):
        return self.state.is_last_process

    @property
    def use_distributed(self):
        return self.state.use_distributed

    @property
    def mixed_precision(self):
        return self.state.mixed_precision

    @property
    def scaler(self):
        """The fp16 :class:`~accelerate_tpu.optimizer.LossScaler` (None
        outside fp16) — reference ``self.scaler``, ``accelerator.py:496``."""
        return self._loss_scale

    @property
    def split_batches(self):
        return self.dataloader_config.split_batches

    @property
    def even_batches(self):
        return self.dataloader_config.even_batches

    @even_batches.setter
    def even_batches(self, value):
        self.dataloader_config.even_batches = value

    @property
    def use_seedable_sampler(self):
        return self.dataloader_config.use_seedable_sampler

    @property
    def non_blocking(self):
        return self.dataloader_config.non_blocking

    @property
    def project_dir(self):
        return self.project_configuration.project_dir

    @property
    def logging_dir(self):
        return self.project_configuration.logging_dir

    @property
    def save_iteration(self):
        return self.project_configuration.iteration

    @property
    def sync_gradients(self):
        return self.gradient_state.sync_gradients

    @sync_gradients.setter
    def sync_gradients(self, value):
        self.gradient_state.sync_gradients = value

    @property
    def gradient_accumulation_steps(self):
        return self.gradient_state.num_steps

    @gradient_accumulation_steps.setter
    def gradient_accumulation_steps(self, value):
        self.gradient_state.plugin_kwargs.update({"num_steps": value})

    @property
    def compute_dtype(self):
        # fp8: non-matmul compute stays bf16; the zoo's dense projections
        # additionally lower to scaled-float8 matmuls (ops/fp8.py) via the
        # recipe attached in prepare_model
        return {
            "bf16": jnp.bfloat16,
            "fp16": jnp.float16,
            "fp8": jnp.bfloat16,
        }.get(self.mixed_precision)

    # ------------------------------------------------------------------
    # process control (delegation)
    # ------------------------------------------------------------------

    def wait_for_everyone(self):
        self.state.wait_for_everyone()

    def print(self, *args, **kwargs):
        self.state.print(*args, **kwargs)

    def on_main_process(self, function):
        return self.state.on_main_process(function)

    def on_local_main_process(self, function):
        return self.state.on_local_main_process(function)

    def on_last_process(self, function):
        return self.state.on_last_process(function)

    def on_process(self, function=None, process_index=None):
        return self.state.on_process(function, process_index)

    def on_local_process(self, function=None, local_process_index=None):
        return self.state.on_local_process(function, local_process_index)

    @contextlib.contextmanager
    def main_process_first(self):
        with self.state.main_process_first():
            yield

    @contextlib.contextmanager
    def local_main_process_first(self):
        with self.state.local_main_process_first():
            yield

    def split_between_processes(self, inputs, apply_padding: bool = False):
        return self.state.split_between_processes(inputs, apply_padding=apply_padding)

    # ------------------------------------------------------------------
    # prepare
    # ------------------------------------------------------------------

    def prepare(self, *args, device_placement: list[bool] | None = None):
        """Shard, place, and wrap objects (reference ``prepare``
        ``accelerator.py:1225``). Pass any combination of models
        (:class:`Model` / flax module+params), optax transformations,
        dataloaders and schedule fns; order is preserved."""
        from .diagnostics.tracing import trace_span

        # the module-level entry point (not self.tracer.span) so a
        # watchdog-only configuration still sees prepare as live progress
        with trace_span("prepare", n_objects=len(args)):
            return self._prepare_inner(*args, device_placement=device_placement)

    def _prepare_inner(self, *args, device_placement: list[bool] | None = None):
        import time as _time

        _prepare_t0 = _time.perf_counter()
        _models_before = len(self._models)
        if device_placement is None:
            device_placement = [None] * len(args)

        # ds-config-driven placeholders → real optax objects (reference
        # utils/deepspeed.py:229-290; engine-built at accelerator.py:1651+)
        from .utils.deepspeed import (
            DummyOptim,
            DummyScheduler,
            optimizer_from_ds_config,
            scheduler_from_ds_config,
        )

        ds_cfg = getattr(self.deepspeed_plugin, "deepspeed_config", None)
        if any(isinstance(a, (DummyOptim, DummyScheduler)) for a in args):
            if self.deepspeed_plugin is None:
                raise ValueError(
                    "DummyOptim/DummyScheduler require a DeepSpeedPlugin "
                    "(usually with a config file defining the "
                    "optimizer/scheduler sections)"
                )
            # resolve the optimizer lr first: an "auto" warmup_max_lr in the
            # scheduler section fills from it (reference semantics)
            opt_lr = None
            for a in args:
                if isinstance(a, DummyOptim):
                    opt_params = dict((ds_cfg or {}).get("optimizer", {}).get("params", {}))
                    raw_lr = opt_params.get("lr")
                    opt_lr = a.lr if raw_lr in (None, "auto") else float(raw_lr)
            args = tuple(
                optimizer_from_ds_config(ds_cfg, a) if isinstance(a, DummyOptim)
                else scheduler_from_ds_config(ds_cfg, a, optimizer_lr=opt_lr)
                if isinstance(a, DummyScheduler)
                else a
                for a in args
            )

        # pass 1: everything except schedulers (they need bound optimizers)
        prepared = []
        for obj, dp in zip(args, device_placement):
            if _is_model(obj):
                prepared.append(self.prepare_model(obj, device_placement=dp))
            elif _is_optimizer(obj):
                prepared.append(self.prepare_optimizer(obj, device_placement=dp))
            elif _is_dataloader(obj):
                prepared.append(self.prepare_data_loader(obj, device_placement=dp))
            else:
                prepared.append(obj)

        # bind optimizers to models by position pairing
        models = [p for p in prepared if isinstance(p, PreparedModel)]
        optimizers = [p for p in prepared if isinstance(p, AcceleratedOptimizer)]
        for i, opt in enumerate(optimizers):
            if opt.model is None:
                model = models[min(i, len(models) - 1)] if models else None
                if model is None:
                    raise ValueError("an optimizer was passed to prepare() without any model")
                opt_sharding = opt_state_sharding_like(
                    opt.optimizer, model.params, model.param_sharding, self.mesh
                )
                opt.bind(model, opt_state_sharding=opt_sharding)

        # pass 2: schedulers
        result = []
        for obj, p in zip(args, prepared):
            if p is obj and _is_scheduler(obj):
                result.append(self.prepare_scheduler(obj))
            else:
                result.append(p)
        if self.deepspeed_plugin is not None:
            self._fill_deepspeed_auto()
        self._maybe_auto_resume()
        if self.telemetry:
            self.telemetry.record_event(
                "prepare",
                seconds=_time.perf_counter() - _prepare_t0,
                n_objects=len(args),
                n_params=sum(
                    m.num_parameters() for m in self._models[_models_before:]
                ),
            )
        return result[0] if len(result) == 1 else tuple(result)

    # ------------------------------------------------------------------
    # fault tolerance (resilience subsystem)
    # ------------------------------------------------------------------

    @property
    def preemption_requested(self) -> bool:
        """Has a SIGTERM/SIGINT/maintenance event raised the LOCAL
        preemption flag? (Cross-host agreement happens in
        :meth:`check_preemption`.)"""
        return (
            self._preemption_handler is not None
            and self._preemption_handler.preemption_requested
        )

    def check_preemption(self):
        """Step-boundary preemption check (called from ``backward``; user
        loops that never call backward — eval sweeps — may call it
        directly). Every ``consensus_interval`` boundaries the local flag
        is all-reduced across hosts; on agreement, ONE synchronized
        emergency ``save_state()`` runs and the process exits cleanly with
        a sentinel file. Collective cadence: all processes count the same
        boundaries, so the all-reduce lines up.

        Mid-accumulation the save is DEFERRED to the window boundary (a
        save with half a gradient window pending would drop those
        micro-batches' work while their dataloader positions stay
        consumed), bounded at 2× the window so a pathological loop still
        saves before the preemption deadline. The batch whose ``backward``
        triggered the check never trains — resume is within ONE optimizer
        step of the kill, never worse."""
        handler = self._preemption_handler
        if handler is None:
            return
        plugin = self.fault_tolerance_plugin
        self._ft_boundary_count += 1
        multi = self.num_processes > 1
        if multi:
            if self._ft_boundary_count % plugin.consensus_interval != 0:
                return
            preempt = handler.consensus()
        else:
            preempt = handler.preemption_requested
        if not preempt:
            return
        # clean window boundary: no parked loss, no accumulated grads
        # (deterministic across hosts — every host runs the same schedule)
        clean = all(
            o._pending_loss is None and o._grads is None for o in self._optimizers
        )
        if not clean:
            self._ft_deferred_boundaries = getattr(self, "_ft_deferred_boundaries", 0) + 1
            if self._ft_deferred_boundaries <= max(2 * self.gradient_accumulation_steps, 4):
                return
            logger.warning(
                "emergency save forced mid-accumulation after %d deferrals "
                "(the partial gradient window is dropped)",
                self._ft_deferred_boundaries,
            )
        self._emergency_save_and_exit()

    def _emergency_save_and_exit(self):
        handler = self._preemption_handler
        plugin = self.fault_tolerance_plugin
        reason = handler.reason or "preemption"
        logger.warning("preemption consensus (%s): emergency checkpoint", reason)
        if self.watchdog is not None:
            # the emergency save may legitimately take longer than a step
            # deadline; a hang report fired *during* the save would be noise
            self.watchdog.stop()
        checkpoint = None
        if plugin.save_on_preemption:
            if self.project_dir is None:
                logger.warning(
                    "emergency save skipped: no project_dir configured on "
                    "this Accelerator"
                )
            else:
                try:
                    # synchronous on purpose: durability outranks step time
                    # when the host is about to disappear
                    checkpoint = self.save_state()
                except Exception:
                    logger.error("emergency save FAILED", exc_info=True)
        if self.telemetry:
            self.telemetry.record_event(
                "preemption", reason=reason, checkpoint=checkpoint, step=self.step
            )
            self.telemetry.close()
        if self.tracer:
            self.tracer.instant("preemption", reason=reason)
            self.tracer.close()
        sentinel_dir = (
            os.path.join(self.project_dir, "checkpoints")
            if self.project_dir is not None
            else os.getcwd()
        )
        if self.is_main_process:
            handler.write_sentinel(sentinel_dir, checkpoint, self.step)
        handler.uninstall()
        logger.warning(
            "exiting cleanly after preemption (checkpoint=%s, exit_code=%d)",
            checkpoint, plugin.exit_code,
        )
        raise SystemExit(plugin.exit_code)

    def _maybe_auto_resume(self):
        """Launcher fault tolerance: a run re-exec'd by ``accelerate-tpu
        launch --max_restarts`` carries ``ACCELERATE_AUTO_RESUME=true``; once
        the training objects are prepared, reload the latest ``checkpoint_*``
        under the project_dir so the restarted process continues where the
        crashed one last saved (SURVEY §5 checkpoint-autoresume — the
        TPU-native stand-in for torchrun's elastic restarts, reference
        ``launchers.py:231-245``)."""
        from .utils.environment import parse_flag_from_env

        # Re-resume on EVERY prepare() until training starts (first
        # backward): a script may prepare its objects across several calls
        # (loader first, model+opt later), and a resume that fired after
        # the first call would leave the later objects at fresh init —
        # silent divergence. Once grads have flowed, further prepare()
        # calls must NOT clobber live training state with the checkpoint.
        if getattr(self, "_training_started", False):
            return
        plugin_resume = (
            self.fault_tolerance_plugin is not None
            and self.fault_tolerance_plugin.auto_resume
        )
        if not (plugin_resume or parse_flag_from_env("ACCELERATE_AUTO_RESUME")):
            return
        if self.project_dir is None:
            return
        from .resilience.manifest import SENTINEL_NAME, find_latest_valid_checkpoint

        checkpoints_dir = os.path.join(self.project_dir, "checkpoints")
        # manifest-validated selection: corrupt/partial checkpoints (and
        # `.tmp` dirs from an interrupted save) are skipped for the newest
        # one that verifies completely. Multi-host: the MAIN process alone
        # validates (one CRC pass over the candidates, not host_count of
        # them) and broadcasts its choice — per-host selection could
        # diverge if validation raced a commit/rotation, silently resuming
        # different checkpoints on different hosts.
        if self.num_processes > 1:
            from .operations import broadcast_object_list

            choice = [
                find_latest_valid_checkpoint(checkpoints_dir)
                if self.is_main_process
                else None
            ]
            latest = broadcast_object_list(choice)[0]
        else:
            latest = find_latest_valid_checkpoint(checkpoints_dir)
        if latest is None:
            if not getattr(self, "_auto_resume_warned", False):
                self._auto_resume_warned = True
                logger.warning(
                    "auto-resume is on but no valid checkpoint_* exists under "
                    "%s; starting fresh", checkpoints_dir
                )
            return
        logger.info("auto-resuming from %s", latest)
        self.load_state(latest)
        sentinel = os.path.join(checkpoints_dir, SENTINEL_NAME)
        if self.is_main_process and os.path.exists(sentinel):
            # consumed: this run IS the resume the sentinel asked for
            try:
                os.remove(sentinel)
            except OSError:
                pass

    def _fill_deepspeed_auto(self):
        """Resolve ``"auto"`` entries of an ingested DeepSpeed config file
        from the prepared objects (reference ``accelerator.py:1669-1830``)."""
        values = {
            "gradient_accumulation_steps": self.gradient_accumulation_steps,
            "zero_optimization.stage": self.deepspeed_plugin.zero_stage,
        }
        if self.deepspeed_plugin.gradient_clipping is not None:
            values["gradient_clipping"] = self.deepspeed_plugin.gradient_clipping
        if self._dataloaders:
            try:
                total = self._dataloaders[0].total_batch_size
                micro = max(total // max(self.state.data_parallel_size, 1), 1)
                values["train_micro_batch_size_per_gpu"] = micro
                values["train_batch_size"] = total * self.gradient_accumulation_steps
            except (ValueError, AttributeError):
                pass
        if self._optimizers:
            lr = self._optimizers[0].learning_rate
            if lr is not None:
                values["optimizer.params.lr"] = lr
        self.deepspeed_plugin.fill_auto(values)

    def prepare_model(self, model, device_placement: bool | None = None, evaluation_mode: bool = False):
        """(Reference ``prepare_model`` ``accelerator.py:1361``.)"""
        if isinstance(model, PreparedModel):
            return model
        model = _as_model(model)
        # FSDP activation checkpointing → the model's remat knob (reference
        # wires torch's checkpoint_wrapper at ``accelerator.py:1523``). Only
        # upgrades: a model already configured to remat keeps its setting.
        if (
            self.fsdp_plugin is not None
            and getattr(self.fsdp_plugin, "activation_checkpointing", False)
            and hasattr(model, "config")
            and hasattr(model.config, "remat")
            and not model.config.remat
        ):
            model.config.remat = True
        rules = model.partition_rules
        sharding = infer_param_sharding(model.params, self.mesh, self.fsdp_plugin, rules)
        params = shard_params(model.params, sharding)
        prepared = PreparedModel(
            model,
            accelerator=self,
            compute_dtype=self.compute_dtype,
            param_sharding=sharding,
        )
        if self.mixed_precision == "fp8":
            from .ops.fp8 import FP8RecipeKwargs

            prepared.fp8_recipe = self.fp8_recipe_handler or FP8RecipeKwargs()
        prepared.params = params
        prepared.training = not evaluation_mode
        self._models.append(prepared)
        return prepared

    def prepare_optimizer(self, optimizer, device_placement: bool | None = None):
        if isinstance(optimizer, AcceleratedOptimizer):
            return optimizer
        wrapped = AcceleratedOptimizer(optimizer, scaler=self._loss_scale)
        if self._grad_comm_hook is not None:
            wrapped.comm_hook = (self._grad_comm_hook, self.mesh)
        if self.telemetry:
            wrapped.telemetry = self.telemetry
        if self.tracer:
            wrapped.tracer = self.tracer
        if self.watchdog is not None:
            wrapped.watchdog = self.watchdog
        self._optimizers.append(wrapped)
        return wrapped

    def prepare_data_loader(self, data_loader, device_placement: bool | None = None, slice_fn_for_dispatch=None):
        if isinstance(data_loader, DataLoaderShard):
            return data_loader
        prepared = prepare_data_loader(
            data_loader,
            num_processes=self.num_processes,
            process_index=self.process_index,
            split_batches=self.split_batches,
            put_on_device=device_placement if device_placement is not None else self.device_placement,
            rng_types=self.rng_types,
            dispatch_batches=self.dataloader_config.dispatch_batches,
            even_batches=self.even_batches,
            use_seedable_sampler=self.use_seedable_sampler,
            slice_fn_for_dispatch=slice_fn_for_dispatch,
            use_stateful_dataloader=self.dataloader_config.use_stateful_dataloader,
            sharding=data_sharding(self.mesh),
            prefetch_batches=self.dataloader_config.prefetch_batches,
        )
        self._dataloaders.append(prepared)
        return prepared

    def prepare_scheduler(self, scheduler):
        if isinstance(scheduler, AcceleratedScheduler):
            return scheduler
        wrapped = AcceleratedScheduler(
            scheduler,
            self._optimizers,
            step_with_optimizer=self.step_scheduler_with_optimizer,
            split_batches=self.split_batches,
        )
        self._schedulers.append(wrapped)
        return wrapped

    # ------------------------------------------------------------------
    # training step surface
    # ------------------------------------------------------------------

    def backward(self, loss, **kwargs):
        """Stage gradients of a deferred loss (reference ``backward``
        ``accelerator.py:2218``; 1/accumulation-steps scaling :2240).

        Fast path: in the common case (single bound optimizer, no
        accumulation in flight) nothing executes here — the loss graph is
        parked on the optimizer and ``opt.step()`` runs ONE donated compiled
        function doing forward+backward+clip+update, same cost as a
        hand-fused pjit step. Anything that breaks fusion (accumulation,
        multiple models, forcing the loss early) falls back to the split
        grad path transparently."""
        if not isinstance(loss, Deferred):
            raise TypeError(
                "backward() expects the deferred loss produced by a prepared "
                "model call; got a concrete value. Compute the loss from "
                "model outputs (e.g. model(**batch).loss)."
            )
        self._training_started = True  # freezes auto-resume (see _maybe_auto_resume)
        if self._preemption_handler is not None:
            # step boundary: the previous step is fully applied, this one
            # hasn't staged yet — the one consistent point to emergency-save
            self.check_preemption()
        from .diagnostics.tracing import trace_span

        with trace_span("backward/dispatch"):
            if self.telemetry:
                self._backward_instrumented(loss)
                return
            self._backward_core(loss)

    def _backward_core(self, loss):
        opt = self._fusable_optimizer(loss)
        if opt is not None:
            if opt._pending_loss is not None:
                self._flush_pending(opt)
            if opt._grads is None:  # may have been set by the flush above
                opt._pending_loss = loss
                opt._pending_clip = None
                opt._last_norm = None  # a stale norm must not satisfy _PendingNorm
                object.__setattr__(loss, "_pre_force_hook", lambda: self._flush_pending(opt))
                return
        self._backward_split(loss)

    def _backward_instrumented(self, loss):
        """Telemetry-enabled backward: feed the step's batch geometry (from
        the deferred graph's input leaves) and the host time spent here to
        the recorder; the matching ``record_step`` fires in
        ``AcceleratedOptimizer.step``."""
        import time as _time

        from .lazy import linearize
        from .telemetry import batch_geometry

        t0 = _time.perf_counter()
        try:
            _, inputs, _ = linearize(loss._node)
            self.telemetry.note_batch(*batch_geometry(inputs))
        except Exception:
            pass
        self._backward_core(loss)
        self.telemetry.note_backward(_time.perf_counter() - t0)

    def _fusable_optimizer(self, loss):
        """The single optimizer eligible for the fused step, or None."""
        if self.gradient_accumulation_steps != 1 or not self.gradient_state.sync_gradients:
            return None
        bound = [o for o in self._optimizers if o.model is not None]
        if len(bound) != 1 or bound[0]._grads is not None:
            return None
        from .lazy import linearize

        _, _, models = linearize(loss._node)
        if bound[0].model not in models:
            return None  # loss doesn't touch this model: split path degrades gracefully
        return bound[0]

    def _backward_split(self, loss):
        """Split path: compute grads now, accumulate into optimizers."""
        scale = float(self.gradient_accumulation_steps)
        dynamic = self._loss_scale is not None  # fp16: loss scaled UP on device
        trainable = [opt.model for opt in self._optimizers if opt.model is not None]
        if not trainable:
            trainable = list(self._models)
        hook = (
            (self._grad_comm_hook, self.mesh) if self._grad_comm_hook is not None else None
        )
        jitted, trainables, frozen, inputs = grad_fn_for(
            loss, trainable, scale, dynamic_scale=dynamic, comm_hook=hook
        )
        train_params = [m.params for m in trainables]
        frozen_params = [m.params for m in frozen]
        extra = (self._loss_scale.scale,) if dynamic else ()
        (scaled_loss, unscaled_loss), grads = jitted(
            train_params, frozen_params, inputs, *extra
        )
        loss._set_forced(unscaled_loss)
        sanitizer = _get_sanitizer()
        if sanitizer:
            # split path computes the loss here, so this is its step
            # boundary; the probe forces the value (sanitize-mode cost)
            sanitizer.check_loss(unscaled_loss, step=self.step)
        for model, g in zip(trainables, grads):
            opt = self._optimizer_for(model)
            if opt is not None:
                opt._accumulate_grads(g)
            else:
                # optimizer-less model: grads exposed via PreparedModel.grads
                # for manual updates (reference analog: .grad on parameters)
                model.accumulate_grads(g)

    def _flush_pending(self, opt):
        """Demote a parked fused loss to the split path (the user forced the
        loss, clipped with an immediate-norm need, or issued a second
        backward before stepping)."""
        loss = opt._pending_loss
        if loss is None:
            return
        opt._pending_loss = None
        pending_clip = opt._pending_clip
        opt._pending_clip = None
        object.__setattr__(loss, "_pre_force_hook", None)
        self._backward_split(loss)
        if pending_clip is not None:
            self.clip_grad_norm_(opt, pending_clip)

    def _optimizer_for(self, model) -> AcceleratedOptimizer | None:
        for opt in self._optimizers:
            if opt.model is model:
                return opt
        return None

    def _do_sync(self):
        """(Reference ``accelerator.py:1034-1041``.)"""
        if self.gradient_state.sync_with_dataloader and self.gradient_state.end_of_dataloader:
            self.step = 0
            self.gradient_state._set_sync_gradients(True)
        else:
            self.step += 1
            self.gradient_state._set_sync_gradients(
                (self.step % self.gradient_state.num_steps) == 0
            )

    @contextlib.contextmanager
    def accumulate(self, *models):
        """(Reference ``accumulate`` ``accelerator.py:1060``.)"""
        self._do_sync()
        with contextlib.ExitStack() as stack:
            if not self.sync_gradients:
                for m in models:
                    stack.enter_context(self.no_sync(m))
            yield

    @contextlib.contextmanager
    def no_sync(self, model):
        """Under GSPMD gradients are reduced inside the compiled step, so
        there is no cross-rank traffic to skip (reference ``no_sync``
        ``accelerator.py:945-983`` suppresses DDP allreduce); the context
        keeps the API and the ``sync_gradients`` bookkeeping."""
        old = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(False)
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(old)

    @contextlib.contextmanager
    def trigger_sync_in_backward(self, model):
        old = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(True)
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(old)

    @contextlib.contextmanager
    def join_uneven_inputs(self, joinables, even_batches=None):
        """Even batches are the default data contract on TPU (static shapes);
        this context only toggles the dataloader flag (reference
        ``accelerator.py:1105-1191``)."""
        if even_batches is not None:
            old = self.even_batches
            self.even_batches = even_batches
            try:
                yield
            finally:
                self.even_batches = old
        else:
            yield

    def clip_grad_norm_(self, parameters, max_norm, norm_type=2):
        """Clip accumulated grads; returns the pre-clip global norm
        (reference ``clip_grad_norm_`` ``accelerator.py:2346``; like the
        reference's ``unscale_gradients`` there, fp16 loss-scaled grads are
        unscaled before clipping so both the clip and the returned norm are
        in true gradient units)."""
        opt = self._match_optimizer_for_parameters(parameters)
        if opt is None:
            return jnp.asarray(0.0)
        if opt._pending_loss is not None:
            if opt._pending_clip is None:
                # fused path: record the clip; the fused step applies it and
                # the true pre-clip norm is available after step()
                opt._pending_clip = float(max_norm)
                return _PendingNorm(self, opt)
            # a second clip before step(): fused supports one — demote so
            # both clips apply sequentially like the split path
            self._flush_pending(opt)
        if opt.grads is None:
            return jnp.asarray(0.0)
        opt.unscale_gradients()
        clip = opt._jit_cache.get("clip_norm")
        if clip is None:
            def _clip(grads, max_norm):
                norm = optax.global_norm(grads)
                factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
                return jax.tree.map(lambda g: g * factor, grads), norm

            clip = jax.jit(_clip, donate_argnums=(0,))
            opt._jit_cache["clip_norm"] = clip
        new_grads, norm = clip(opt._grads, float(max_norm))
        opt._grads = new_grads
        opt._last_norm = norm
        return norm

    def clip_grad_value_(self, parameters, clip_value):
        """(Reference ``accelerator.py:2403``.)"""
        opt = self._match_optimizer_for_parameters(parameters)
        if opt is None:
            return
        if opt._pending_loss is not None:
            self._flush_pending(opt)  # value-clip is not fused; use split path
        if opt.grads is None:
            return
        opt.unscale_gradients()
        clip = opt._jit_cache.get("clip_value")
        if clip is None:
            def _clip(grads, v):
                return jax.tree.map(lambda g: jnp.clip(g, -v, v), grads)

            clip = jax.jit(_clip, donate_argnums=(0,))
            opt._jit_cache["clip_value"] = clip
        opt._grads = clip(opt._grads, float(clip_value))

    def unscale_gradients(self, optimizer=None):
        """(Reference ``unscale_gradients`` ``accelerator.py:2311``.)"""
        opts = [optimizer] if optimizer is not None else self._optimizers
        for opt in opts:
            opt.unscale_gradients()

    def _match_optimizer_for_parameters(self, parameters):
        if isinstance(parameters, PreparedModel):
            return self._optimizer_for(parameters)
        if isinstance(parameters, AcceleratedOptimizer):
            return parameters
        # params pytree: match by identity against bound models
        for opt in self._optimizers:
            if opt.model is not None and opt.model.params is parameters:
                return opt
        return self._optimizers[0] if self._optimizers else None

    # ------------------------------------------------------------------
    # collectives
    # ------------------------------------------------------------------

    def _force_deferred(self, tensor):
        return jax.tree.map(
            lambda t: t.force() if isinstance(t, Deferred) else t,
            tensor,
            is_leaf=lambda t: isinstance(t, Deferred),
        )

    def gather(self, tensor):
        """(Reference ``gather`` ``accelerator.py:2414``.)"""
        return ops.gather(self._force_deferred(tensor))

    def gather_for_metrics(self, input_data, use_gather_object: bool = False):
        """Gather + drop the duplicated tail on the last batch (reference
        ``accelerator.py:2462-2533`` using ``GradientState.remainder``)."""
        input_data = self._force_deferred(input_data)
        try:
            recursively_check = ops.find_batch_size(input_data) is not None
        except Exception:
            recursively_check = False
        if use_gather_object or not recursively_check:
            data = ops.gather_object(
                input_data if isinstance(input_data, list) else [input_data]
            )
            return data
        data = ops.gather(input_data)
        remainder = self.gradient_state.remainder
        if self.gradient_state.end_of_dataloader and remainder > 0:
            def _truncate(t):
                return t[:remainder] if hasattr(t, "ndim") and t.ndim > 0 else t

            data = jax.tree.map(_truncate, data)
        return data

    def reduce(self, tensor, reduction="sum", scale=1.0):
        return ops.reduce(self._force_deferred(tensor), reduction=reduction, scale=scale)

    def pad_across_processes(self, tensor, dim=0, pad_index=0, pad_first=False):
        return ops.pad_across_processes(
            self._force_deferred(tensor), dim=dim, pad_index=pad_index, pad_first=pad_first
        )

    # -- trigger API (reference ``accelerator.py:2252-2309``) ----------------

    def set_trigger(self):
        self.flag_tensor = np.ones((), dtype=np.int32)

    def check_trigger(self) -> bool:
        flag = self.flag_tensor if self.flag_tensor is not None else np.zeros((), dtype=np.int32)
        total = ops.reduce(flag, reduction="sum")
        triggered = bool(np.asarray(total) >= 1)
        if triggered:
            self.flag_tensor = None
        return triggered

    # ------------------------------------------------------------------
    # contexts
    # ------------------------------------------------------------------

    @contextlib.contextmanager
    def autocast(self, autocast_handler=None):
        """Precision is a trace-time dtype policy on TPU; with
        ``AutocastKwargs(enabled=False)`` the compute-dtype cast is suspended
        for the context — a full-precision island inside a mixed-precision
        run (reference ``accelerator.py:3435``)."""
        if autocast_handler is not None and not getattr(autocast_handler, "enabled", True):
            # suspend BOTH precision policies: the dtype cast and the fp8
            # matmul recipe (deferred calls snapshot them at record time)
            saved = [(m, m.compute_dtype, m.fp8_recipe) for m in self._models]
            for m, _, _ in saved:
                m.compute_dtype = None
                m.fp8_recipe = None
            try:
                yield
            finally:
                for m, dtype, recipe in saved:
                    m.compute_dtype = dtype
                    m.fp8_recipe = recipe
            return
        yield

    @contextlib.contextmanager
    def profile(self, profile_handler: ProfileKwargs | None = None):
        """``jax.profiler`` capture (reference builds torch.profiler,
        ``accelerator.py:3462-3519``). Yields a :class:`ProfileContext`
        whose ``step()`` drives the wait/warmup/active schedule — tracing
        starts on entering an active window and stops on leaving it, exactly
        the reference's ``torch.profiler.schedule`` contract.
        ``profile_memory`` additionally writes ``memory_<step>.prof``
        (pprof-format device memory snapshots)."""
        handler = profile_handler or self.profile_handler or ProfileKwargs()
        trace_dir = handler.output_trace_dir
        if trace_dir is None:
            yield None
            return
        ctx = ProfileContext(handler, trace_dir, telemetry=self.telemetry)
        try:
            ctx._maybe_start()
            yield ctx
        finally:
            ctx._finish()

    # ------------------------------------------------------------------
    # model/optimizer interop
    # ------------------------------------------------------------------

    def unwrap_model(self, model, keep_fp32_wrapper: bool = True):
        return extract_model_from_parallel(model, keep_fp32_wrapper)

    def free_memory(self, *objects):
        """Release prepared references + compiled-step caches (reference
        ``free_memory`` ``accelerator.py:3282``)."""
        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self.step = 0
        clear_caches()
        jax.clear_caches()
        return objects

    def clear(self, *objects):
        return self.free_memory(*objects)

    def get_state_dict(self, model, unwrap=True):
        if isinstance(model, PreparedModel):
            return model.state_dict()
        if isinstance(model, Model):
            return PreparedModel(model).state_dict()
        raise TypeError(f"cannot extract state dict from {type(model)}")

    # ------------------------------------------------------------------
    # checkpointing facade (impl in checkpointing.py)
    # ------------------------------------------------------------------

    def register_for_checkpointing(self, *objects):
        for obj in objects:
            if not (hasattr(obj, "state_dict") and hasattr(obj, "load_state_dict")):
                raise ValueError(
                    f"{obj} must define state_dict/load_state_dict to be registered"
                )
        self._custom_objects.extend(objects)

    def save_state(self, output_dir: str | None = None, **save_model_func_kwargs):
        from .checkpointing import save_accelerator_state

        return save_accelerator_state(self, output_dir, **save_model_func_kwargs)

    def load_state(self, input_dir: str | None = None, **load_model_func_kwargs):
        from .checkpointing import load_accelerator_state

        return load_accelerator_state(self, input_dir, **load_model_func_kwargs)

    def save_model(self, model, save_directory: str, max_shard_size="10GB", safe_serialization=True):
        from .checkpointing import save_model_weights

        return save_model_weights(self, model, save_directory, max_shard_size, safe_serialization)

    def save(self, obj, f, safe_serialization=False):
        from .checkpointing import save_object

        if self.is_main_process:
            save_object(obj, f, safe_serialization=safe_serialization)

    # ------------------------------------------------------------------
    # tracking facade (impl in tracking.py)
    # ------------------------------------------------------------------

    def init_trackers(self, project_name: str, config: dict | None = None, init_kwargs: dict | None = None):
        from .tracking import init_trackers

        self.trackers = init_trackers(
            self.log_with, project_name, self.logging_dir, config, init_kwargs or {}
        )

    def get_tracker(self, name: str, unwrap: bool = False):
        for tracker in self.trackers:
            if getattr(tracker, "name", None) == name:
                return tracker.tracker if unwrap else tracker
        from .tracking import GeneralTracker

        return GeneralTracker(_blank=True)

    def log(self, values: dict, step: int | None = None, log_kwargs: dict | None = None):
        for tracker in self.trackers:
            tracker.log(values, step=step, **(log_kwargs or {}).get(tracker.name, {}))

    def _telemetry_tracker_sink(self, values: dict, step: int | None):
        """Telemetry → tracker fan-out (the recorder gates this to the main
        process, matching ``tracking.on_main_process``)."""
        self.log(values, step=step)

    def end_training(self):
        for tracker in self.trackers:
            tracker.finish()
        self.telemetry.close()
        if self.sanitizer is not None:
            # release only OUR sanitizer — a newer Accelerator's Borg
            # takeover must not be clobbered by an old one's teardown
            if _get_sanitizer() is self.sanitizer:
                _set_sanitizer(None)
        if self.watchdog is not None:
            self.watchdog.stop()
        self.tracer.close()
        if self._preemption_handler is not None:
            self._preemption_handler.uninstall()
        from .checkpointing import _join_writer_then_barrier

        # a trailing async save must land AND commit before exit — the
        # barriered join is the only place a multi-host commit is safe
        _join_writer_then_barrier(self)
        self.wait_for_everyone()

    # ------------------------------------------------------------------
    # misc parity helpers
    # ------------------------------------------------------------------

    def skip_first_batches(self, dataloader, num_batches: int = 0):
        return skip_first_batches(dataloader, num_batches)

    def __repr__(self):
        return repr(self.state)


# ---------------------------------------------------------------------------
# type sniffing for prepare()
# ---------------------------------------------------------------------------


def _is_model(obj) -> bool:
    return isinstance(obj, (Model, PreparedModel))


def _as_model(obj) -> Model:
    if isinstance(obj, Model):
        return obj
    raise TypeError(
        f"cannot prepare {type(obj)} as a model; wrap it in accelerate_tpu.Model "
        "(for flax modules: Model.from_flax(module, variables))"
    )


def _is_optimizer(obj) -> bool:
    if isinstance(obj, AcceleratedOptimizer):
        return True
    return isinstance(obj, optax.GradientTransformation) or (
        hasattr(obj, "init") and hasattr(obj, "update") and not hasattr(obj, "apply_fn")
    )


def _is_dataloader(obj) -> bool:
    if isinstance(obj, DataLoaderShard):
        return True
    if hasattr(obj, "dataset") and (hasattr(obj, "batch_size") or hasattr(obj, "batch_sampler")):
        return True
    mod = type(obj).__module__ or ""
    return mod.startswith("torch.utils.data")


def _is_scheduler(obj) -> bool:
    """A schedule is an optax schedule fn (closure from the optax package, or
    a 1-arg function whose parameter is step-like) or a torch-style
    scheduler object (step + get_last_lr). Everything else passes through
    prepare() untouched, matching the reference's behaviour for
    unrecognized objects (loss fns, tokenizers, collate fns, …)."""
    import functools as _ft
    import inspect
    import types as _t

    if isinstance(obj, AcceleratedScheduler):
        return True
    if hasattr(obj, "step") and hasattr(obj, "get_last_lr"):
        return True
    if not isinstance(obj, (_t.FunctionType, _ft.partial)) or _is_optimizer(obj):
        return False
    if (getattr(obj, "__module__", "") or "").startswith("optax"):
        return True
    try:
        params = list(inspect.signature(obj).parameters.values())
    except (TypeError, ValueError):
        return False
    return len(params) == 1 and params[0].name in (
        "step", "count", "t", "epoch", "iteration", "step_count", "global_step"
    )
