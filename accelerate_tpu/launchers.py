"""notebook_launcher / debug_launcher (reference ``launchers.py:40,269``).

On TPU with JAX there is no per-device process fork (the reference's
``xmp.spawn``): ONE process drives all local chips, so ``notebook_launcher``
validates the environment, sets the env-var contract, and calls the
function inline. ``debug_launcher`` runs the function on a virtual
N-device CPU mesh in a subprocess (fresh JAX runtime) — the analog of the
reference's gloo-on-localhost debug path.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import textwrap


def notebook_launcher(
    function,
    args=(),
    num_processes: int | None = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: str = "127.0.0.1",
    node_rank: int = 0,
    num_nodes: int = 1,
    **kwargs,
):
    """Run a training function on the attached TPU(s) from a notebook.

    ``num_processes`` is accepted for API parity but on JAX-TPU a single
    process drives every local chip; it is validated against the actual
    device count rather than used to fork.
    """
    import jax

    from .state import AcceleratorState, PartialState

    if AcceleratorState._shared_state or PartialState._shared_state:
        in_use = AcceleratorState if AcceleratorState._shared_state else PartialState
        raise ValueError(
            f"A {in_use.__name__} was already initialized in this process; "
            "notebook_launcher must run before any Accelerator is created "
            "(restart the kernel) — reference semantics, launchers.py:165-255."
        )
    n_dev = jax.local_device_count()
    if num_processes is not None and num_processes > n_dev:
        raise ValueError(
            f"num_processes={num_processes} but only {n_dev} local devices exist"
        )
    if mixed_precision not in ("no", "bf16", "fp16"):
        raise ValueError(f"unknown mixed_precision {mixed_precision!r}")
    os.environ["ACCELERATE_MIXED_PRECISION"] = mixed_precision
    if num_nodes > 1:
        os.environ.setdefault("ACCELERATE_COORDINATOR_ADDR", f"{master_addr}:{use_port}")
        os.environ.setdefault("ACCELERATE_NUM_PROCESSES", str(num_nodes))
        os.environ.setdefault("ACCELERATE_PROCESS_ID", str(node_rank))
    print(f"Launching training on {n_dev} device(s).")
    try:
        return function(*args)
    finally:
        os.environ.pop("ACCELERATE_MIXED_PRECISION", None)


def _can_import(function) -> bool:
    mod = getattr(function, "__module__", None)
    name = getattr(function, "__qualname__", getattr(function, "__name__", ""))
    return bool(mod and mod != "__main__" and "." not in name and "<" not in name)


def debug_launcher(function, args=(), num_processes: int = 2):
    """Run ``function`` against a virtual ``num_processes``-device CPU mesh
    in a fresh subprocess (JAX platform flags are fixed at first import, so
    in-process re-init is impossible — the subprocess IS the fresh runtime).
    The function must be importable (defined in a module, not a closure) or
    picklable."""
    import pickle

    with tempfile.TemporaryDirectory() as td:
        payload = os.path.join(td, "payload.pkl")
        if _can_import(function):
            spec = ("import", function.__module__, function.__qualname__)
        else:
            spec = ("pickle", None, None)
        with open(payload, "wb") as f:
            if spec[0] == "pickle":
                pickle.dump((function, args), f)
            else:
                pickle.dump((None, args), f)
        runner = textwrap.dedent(
            f"""
            import os, pickle, sys
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count={num_processes}"
            ).strip()
            sys.path.insert(0, {os.getcwd()!r})
            with open({payload!r}, "rb") as f:
                fn, args = pickle.load(f)
            if fn is None:
                import importlib
                fn = getattr(importlib.import_module({spec[1]!r}), {spec[2]!r})
            fn(*args)
            """
        )
        proc = subprocess.run(
            [sys.executable, "-c", runner],
            env={
                **os.environ,
                "ACCELERATE_DEBUG_RDV": "1",
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": (
                    os.environ.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count={num_processes}"
                ).strip(),
                # don't open a TPU-plugin session from a CPU-mesh child
                "PALLAS_AXON_POOL_IPS": "",
            },
        )
        if proc.returncode != 0:
            raise RuntimeError(f"debug_launcher function failed (exit {proc.returncode})")
