"""Big-model inference: meta init, device-map dispatch, HBM↔host↔disk tiers.

Reference: ``/root/reference/src/accelerate/big_modeling.py`` (637 LoC) +
the ``AlignDevicesHook`` machinery (``hooks.py``). The torch design mutates
``module.forward`` with pre/post hooks that move weights on and off the GPU
(reference ``hooks.py:220-397``). The TPU-native design has no module
mutation to hook — instead:

* ``init_empty_weights`` → abstract params via ``jax.eval_shape``
  (zero-RAM skeletons, reference ``big_modeling.py:58``);
* a *device map* assigns param-tree prefixes to tiers — chip HBM, host
  DRAM (numpy), disk (memmapped ``.dat`` via OffloadedWeightsLoader);
* ``dispatch_model`` returns a model whose apply **streams** offloaded
  segments through HBM with double buffering: ``jax.device_put`` of
  segment i+1 is issued (async) before segment i computes, the per-layer
  compiled fn is reused across layers, and consumed buffers are dropped —
  the pipelined analog of the reference's pre/post-forward hook pair,
  and the difference between the OPT-30B row being seconds vs minutes
  per token (SURVEY §7 "disk-offload throughput").

Models opt into streaming by exposing ``segments()`` (our model zoo does);
anything else falls back to materialise-then-apply.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from .logging import get_logger
from .modules import Model, ModelOutput
from .utils.modeling import (
    compute_module_sizes,
    flat_param_shapes,
    get_balanced_memory,
    get_max_memory,
    infer_auto_device_map,
    load_state_dict_from_files,
)
from .utils.offload import OffloadedWeightsLoader, offload_state_dict, save_offload_index

logger = get_logger(__name__)

_EMPTY_INIT = {"active": False, "include_buffers": True}


@contextlib.contextmanager
def init_empty_weights(include_buffers: bool = True):
    """Initialise models as shape/dtype skeletons with zero memory
    (reference ``init_empty_weights`` ``big_modeling.py:58``). Model
    factories consult :func:`is_empty_init` and build params with
    ``jax.eval_shape``."""
    old = dict(_EMPTY_INIT)
    _EMPTY_INIT.update(active=True, include_buffers=include_buffers)
    try:
        yield
    finally:
        _EMPTY_INIT.update(old)


@contextlib.contextmanager
def init_on_device(device):
    """(Reference ``init_on_device`` ``big_modeling.py:94``.)"""
    if device in ("meta", None):
        with init_empty_weights():
            yield
        return
    yield  # concrete init is already host-side; placement happens at prepare


def is_empty_init() -> bool:
    return _EMPTY_INIT["active"]


def materialize_params(abstract_params, init_fn: Callable | None = None, seed: int = 0):
    """Turn a ShapeDtypeStruct skeleton into concrete params — via the
    model's init when available, else zeros (the reference's meta→empty
    semantics: values are garbage until a checkpoint loads)."""
    if init_fn is not None:
        return init_fn(jax.random.PRNGKey(seed))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), abstract_params)


# ---------------------------------------------------------------------------
# tier placement
# ---------------------------------------------------------------------------


_UNMAPPED = object()


def _entry_for(path: str, device_map: Mapping[str, Any], default=_UNMAPPED):
    """Longest-prefix device-map lookup for a dot path."""
    probe = path
    while True:
        if probe in device_map:
            return device_map[probe]
        if "." not in probe:
            break
        probe = probe.rsplit(".", 1)[0]
    if "" in device_map:
        return device_map[""]
    return default


class TieredParams:
    """The param pytree split across tiers, addressable by dot path.
    ``resident_slices`` holds per-layer HBM slices of stacked leaves whose
    layers straddle tiers (the OPT-30B shape: first N layers resident,
    the rest streamed from host/disk)."""

    def __init__(
        self,
        resident,
        host: dict,
        disk_index: Mapping | None,
        offload_dir: str | None,
        resident_slices: dict | None = None,
        host_slices: dict | None = None,
        stack_layouts: dict | None = None,
    ):
        self.resident = resident  # {path: jax.Array} fully-resident leaves
        self.host = host  # {path: np.ndarray}
        self.disk = (
            OffloadedWeightsLoader(save_folder=offload_dir) if disk_index is not None else None
        )
        self.resident_slices = resident_slices or {}  # {(path, layer): jax.Array}
        self.host_slices = host_slices or {}  # {(path, layer): np.ndarray}
        self.stack_layouts = stack_layouts or {}  # {path: [tier per layer]}

    def fetch_host_or_disk(self, path: str, idx: int | None = None):
        if idx is not None:
            if (path, idx) in self.host_slices:
                return self.host_slices[(path, idx)]
            if self.disk is not None and f"{path}.{idx}" in self.disk:
                return self.disk[f"{path}.{idx}"]
        if path in self.host:
            value = self.host[path]
            return value if idx is None else value[idx]
        if self.disk is not None and path in self.disk:
            value = self.disk[path]
            return value if idx is None else value[idx]
        raise KeyError((path, idx))


def dispatch_model(
    model: Model,
    device_map: Mapping[str, Any],
    main_device=None,
    state_dict: Mapping | None = None,
    offload_dir: str | None = None,
    offload_buffers: bool = False,
    skip_keys=None,
    preload_module_classes=None,
    force_hooks: bool = False,
):
    """Place a model's params per ``device_map`` and return a
    :class:`DispatchedModel` (reference ``dispatch_model``
    ``big_modeling.py:307``)."""
    params = model.params
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = ".".join(_ppart(p) for p in path)
        flat[key] = leaf

    from .utils.modeling import stacked_prefix_of, stacked_prefixes

    prefixes = stacked_prefixes(getattr(model, "stacked_params_prefix", None))
    devices = jax.local_devices()
    resident_paths, host_paths, disk_paths = [], [], []
    slice_plans: dict[str, list] = {}  # path -> per-layer tiers (straddling stacks)
    unmapped = []
    for key in flat:
        stack_prefix = stacked_prefix_of(key, prefixes)
        if stack_prefix is not None:
            # per-layer lookup: 'layers.wq' layer i probes 'layers.i.wq' (the
            # expanded granularity auto maps use), falling back to the
            # unexpanded 'layers.wq' entry
            rest = key[len(stack_prefix) + 1 :]
            n_layers = flat[key].shape[0]
            whole = _entry_for(key, device_map, default=_UNMAPPED)
            tiers = [
                _entry_for(f"{stack_prefix}.{i}.{rest}", device_map, default=whole)
                for i in range(n_layers)
            ]
            if any(t is _UNMAPPED for t in tiers):
                unmapped.append(key)
                continue
            if len(set(map(str, tiers))) > 1:
                slice_plans[key] = tiers
                continue
            tier = tiers[0]
        else:
            tier = _entry_for(key, device_map)
        if tier is _UNMAPPED:
            unmapped.append(key)
        elif tier == "cpu":
            host_paths.append(key)
        elif tier == "disk":
            disk_paths.append(key)
        else:
            resident_paths.append((key, tier))
    if unmapped:
        raise ValueError(
            f"device_map does not cover {len(unmapped)} parameters "
            f"(e.g. {unmapped[:3]}); add entries or a '' catch-all"
        )

    # HBM-resident leaves
    def _resident(key, leaf, tier):
        dev = devices[int(tier)] if not isinstance(tier, str) else devices[0]
        value = leaf
        if isinstance(value, jax.ShapeDtypeStruct):
            value = jnp.zeros(value.shape, value.dtype)
        return jax.device_put(value, dev)

    resident_map = {k: _resident(k, flat[k], t) for k, t in resident_paths}

    def _host_value(leaf):
        if isinstance(leaf, jax.ShapeDtypeStruct):
            return np.zeros(leaf.shape, _np_dtype(leaf.dtype))
        return np.asarray(jax.device_get(leaf))

    host_map = {k: _host_value(flat[k]) for k in host_paths}

    # straddling stacks: each layer goes to exactly one tier — chip slices
    # become resident, cpu slices stay as per-layer host arrays, disk slices
    # are written as individual '<path>.<i>' entries. No full-stack copy is
    # retained anywhere.
    resident_slices = {}
    host_slices = {}
    to_disk = {}
    stack_layouts = {}
    for k, tiers in slice_plans.items():
        value = _host_value(flat[k])
        stack_layouts[k] = list(tiers)
        for i, tier in enumerate(tiers):
            if tier == "cpu":
                host_slices[(k, i)] = np.ascontiguousarray(value[i])
            elif tier == "disk":
                to_disk[f"{k}.{i}"] = np.ascontiguousarray(value[i])
            else:
                resident_slices[(k, i)] = jax.device_put(value[i], devices[int(tier)])
        del value

    if disk_paths or to_disk:
        if offload_dir is None:
            raise ValueError("device_map sends weights to 'disk' but no offload_dir given")
        for k in disk_paths:
            to_disk[k] = _host_value(flat[k])
        disk_index = offload_state_dict(offload_dir, to_disk)
    else:
        disk_index = None

    tiered = TieredParams(
        resident_map, host_map, disk_index, offload_dir, resident_slices,
        host_slices=host_slices, stack_layouts=stack_layouts,
    )
    return DispatchedModel(model, tiered, device_map)


def _np_dtype(dtype):
    try:
        return np.dtype(dtype)
    except TypeError:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)


def _ppart(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(getattr(p, "name", p))


class DispatchedModel:
    """Callable model over tiered params. With a cooperating model
    (``model.segments``) execution streams segment-by-segment with
    double-buffered H2D copies; otherwise offloaded leaves are materialised
    for the duration of one call."""

    def __init__(self, model: Model, tiered: TieredParams, device_map):
        self._model = model
        self.tiered = tiered
        self.hf_device_map = dict(device_map)  # reference-compatible attr name
        self._jit_apply = None
        self._segment_fns: dict[str, Any] = {}
        self._io_executor = None      # lazy single-worker disk-read stage
        self._decode_executor = None  # lazy single-worker decode+place stage

    def close(self):
        """Release the prefetch workers (also runs on GC so dispatched models
        don't each pin idle OS threads for the process lifetime)."""
        for attr in ("_io_executor", "_decode_executor"):
            ex = getattr(self, attr, None)
            if ex is not None:
                ex.shutdown(wait=False, cancel_futures=True)
                setattr(self, attr, None)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- generic path --------------------------------------------------------

    def _materialize_full(self):
        flat = {}
        flat.update(self.tiered.resident)
        for k in self.tiered.host:
            flat[k] = jax.device_put(self.tiered.host[k])
        if self.tiered.disk is not None:
            for k in self.tiered.disk:
                if "." in k and k.rsplit(".", 1)[0] in self.tiered.stack_layouts:
                    continue  # per-layer slice; reassembled below
                flat[k] = jax.device_put(np.asarray(self.tiered.disk[k]))
        for k, tiers in self.tiered.stack_layouts.items():
            layers = []
            for i in range(len(tiers)):
                if (k, i) in self.tiered.resident_slices:
                    layers.append(self.tiered.resident_slices[(k, i)])
                else:
                    layers.append(jax.device_put(np.asarray(self.tiered.fetch_host_or_disk(k, i))))
            flat[k] = jnp.stack(layers)
        return _unflatten_by_paths(self._model.params, flat)

    def __call__(self, *args, **kwargs):
        segments = getattr(self._model, "segments", None)
        if segments is not None:
            return self._call_streaming(segments, *args, **kwargs)
        params = self._materialize_full()
        if self._jit_apply is None:
            self._jit_apply = jax.jit(self._model.apply_fn)
        return self._jit_apply(params, *args, **kwargs)

    # -- streaming path ------------------------------------------------------

    # -- stage 1: disk → page cache (IO worker; no decode, no device work) --

    @staticmethod
    def _page_in(arr: np.ndarray) -> np.ndarray:
        """Touch one element per page so the kernel reads a memmap-backed
        leaf NOW, on the IO stage — without this the ``np.asarray`` below is
        a lazy view and every real disk read would page-fault later, inside
        the decode worker or the consuming GEMM, collapsing the pipeline to
        two stages. No bytes are copied: stage 2's ``device_put`` still
        aliases the (now resident) mapped pages. On host-RAM leaves the
        touch is a few thousand adds — noise."""
        flat = arr.reshape(-1) if arr.flags.c_contiguous else arr
        step = max(1, 4096 // max(arr.dtype.itemsize, 1))
        if flat.size:
            float(np.asarray(flat[::step], dtype=np.float64).sum())
        return arr

    def _fetch_raw_leaf(self, p, idx):
        """Host numpy bytes for an offloaded leaf, or the device array
        itself when the leaf is resident. KeyError when the path is absent.
        A ``(path, i)`` entry addresses layer i of a stacked leaf — for
        host/disk tiers this slices the numpy/memmap view, so one layer's
        bytes move, not the whole stack."""
        if idx is not None and (p, idx) in self.tiered.resident_slices:
            return self.tiered.resident_slices[(p, idx)]
        if p in self.tiered.resident:
            value = self.tiered.resident[p]
            return value if idx is None else value[idx]
        return self._page_in(np.asarray(self.tiered.fetch_host_or_disk(p, idx)))

    def _segment_fetch_raw(self, seg_name, paths):
        """One segment's leaves as (kind, payload) host material. Quantized
        leaves live as ``<path>.q``/``<path>.scale`` pairs (int8) or the
        five 4-bit planes — the quantized bytes are what cross disk→host."""
        out = {}
        for entry in paths:
            p, idx = entry if isinstance(entry, tuple) else (entry, None)
            try:
                out[p] = ("dense", self._fetch_raw_leaf(p, idx))
            except KeyError:
                try:
                    out[p] = ("qt", (
                        self._fetch_raw_leaf(f"{p}.q", idx),
                        self._fetch_raw_leaf(f"{p}.scale", idx),
                    ))
                except KeyError:
                    # 4-bit leaves: all-array children, path-addressed (the
                    # [16] codebook is per-tensor, never layer-sliced)
                    planes = {
                        leaf: self._fetch_raw_leaf(f"{p}.{leaf}", idx)
                        for leaf in ("packed", "scale_q", "scale_offset", "scale_scale")
                    }
                    planes["code"] = self._fetch_raw_leaf(f"{p}.code", None)
                    out[p] = ("q4", planes)
        return out

    # -- stage 2: decode + place (decode worker) -----------------------------

    @staticmethod
    def _put(x):
        return jax.device_put(x) if isinstance(x, np.ndarray) else x

    def _segment_decode_put(self, raw):
        """Host material → device-ready segment params. 4-bit packed planes
        unpack nibbles → int8 codes via the native pshufb decoder (host-only
        work, 64-byte-aligned output so the CPU-backend ``device_put``
        aliases instead of copying) so the segment program runs a straight
        int8 GEMM instead of in-jit nibble decoding — the decode was the
        4-bit offload compute floor. int8 leaves stay :class:`QTensor`s and
        the compiled fn dequantizes in-kernel (fused into the consuming
        matmul — no materialised full-precision copy)."""
        from .native import q4_decode_codes
        from .utils.quantization import Q4DecodedTensor, Q4Tensor, QTensor

        out = {}
        for p, (kind, payload) in raw.items():
            if kind == "dense":
                out[p] = self._put(payload)
            elif kind == "qt":
                out[p] = QTensor(self._put(payload[0]), self._put(payload[1]))
            else:
                packed = payload["packed"]
                if isinstance(packed, np.ndarray) and packed.ndim == 2:
                    # the [16] codebook may be HBM-resident even when the
                    # packed plane is offloaded (per-path device maps)
                    code = np.asarray(payload["code"])
                    c8 = q4_decode_codes(packed, np.round(code * 127.0).astype(np.int8))
                    if c8 is not None:
                        out[p] = Q4DecodedTensor(
                            jax.device_put(c8),
                            self._put(payload["scale_q"]),
                            self._put(payload["scale_offset"]),
                            self._put(payload["scale_scale"]),
                        )
                        continue
                out[p] = Q4Tensor(
                    self._put(payload["packed"]),
                    self._put(payload["scale_q"]),
                    self._put(payload["scale_offset"]),
                    self._put(payload["scale_scale"]),
                    self._put(payload["code"]),
                )
        return out

    def _call_streaming(self, segments, *args, **kwargs):
        """segments: list of (name, param_paths, fn) where
        ``fn(params_dict, carry) -> carry``; first carry built from inputs,
        last carry is the output.

        Three-stage pipeline over two background workers: while segment i
        computes, the decode worker unpacks/places segment i+1 and the IO
        worker reads segment i+2 off disk — steady-state step time is
        max(read, decode+place, compute) instead of their sum (SURVEY §7
        calls this path the difference between 2 s/tok and 30 s/tok; the
        reference's analog is AlignDevicesHook prefetch). The GIL does not
        serialise the stages: disk reads, the ctypes nibble decoder, and
        XLA execution all release it. Peak extra memory is one segment's
        raw bytes + one segment's decoded arrays (vs one segment before).
        """
        from concurrent.futures import ThreadPoolExecutor

        plan = segments(*args, **kwargs) if callable(segments) else segments
        steps = plan["steps"]
        carry = plan["init"]()
        if self._io_executor is None:
            self._io_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="offload-fetch"
            )
        if self._decode_executor is None:
            self._decode_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="offload-decode"
            )

        param_futures: dict[int, Any] = {}

        def _schedule(i: int) -> None:
            if i < len(steps) and i not in param_futures:
                raw = self._io_executor.submit(self._segment_fetch_raw, *steps[i][:2])
                # drain the raw future's exception here; the consumer still
                # sees it re-raised through r.result() in the decode task
                raw.add_done_callback(lambda f: f.exception())
                param_futures[i] = self._decode_executor.submit(
                    lambda r=raw: self._segment_decode_put(r.result())
                )

        # lookahead depth 2: i computes, i+1 decodes, i+2 reads
        _schedule(0)
        _schedule(1)
        try:
            return self._run_streaming_loop(steps, plan, carry, param_futures, _schedule)
        finally:
            # a failed segment must not strand the in-flight prefetches:
            # cancel what's still queued, drain what already ran, so no
            # exception goes unretrieved and (beyond one bounded in-flight
            # read) no stale task runs ahead of the next call's work on
            # these single-worker pools
            for fut in param_futures.values():
                if not fut.cancel():
                    fut.add_done_callback(lambda f: f.exception())

    def _run_streaming_loop(self, steps, plan, carry, param_futures, _schedule):
        for i, (name, paths, fn) in enumerate(steps):
            seg_params = param_futures.pop(i).result()
            _schedule(i + 2)
            key = name if isinstance(name, str) else name[0]
            jit_fn = self._segment_fns.get(key)
            if jit_fn is None:
                # quantized leaves enter the compiled segment AS
                # QTensor/Q4Tensor pytree nodes: the model zoo's dense()
                # routes int8 weights through an int8 GEMM (activations
                # row-quantized, bnb Linear8bitLt semantics — the int8
                # bytes are both what crossed the tiers AND what the
                # matmul reads) and 4-bit weights through the slab GEMMs;
                # embedding gathers hit the nodes' __getitem__ (int8 /
                # packed rows move, scaled after). jnp-function ops on the
                # nodes fall back through __jax_array__ = full dequant.
                jit_fn = jax.jit(fn)
                try:
                    carry = jit_fn(seg_params, carry)
                except (TypeError, AttributeError) as first_err:
                    # a non-zoo segment fn used bare operators/methods the
                    # quantized nodes don't implement (`w * 0.5`,
                    # `w.astype(...)`) — retrace with every quantized leaf
                    # dequantized up front, the pre-round-4 semantics. Only
                    # quantized leaves justify the retry: a plain-fp32
                    # segment raising TypeError is a genuine user bug whose
                    # traceback must not be swallowed by a retrace.
                    from .utils.quantization import (
                        Q4DecodedTensor,
                        Q4Tensor,
                        QTensor,
                        dequantize_tree,
                    )

                    q_types = (QTensor, Q4Tensor, Q4DecodedTensor)
                    has_quant = any(
                        isinstance(leaf, q_types)
                        for leaf in jax.tree.leaves(
                            seg_params, is_leaf=lambda x: isinstance(x, q_types)
                        )
                    )
                    if not has_quant:
                        raise
                    jit_fn = jax.jit(lambda seg, c: fn(dequantize_tree(seg), c))
                    try:
                        carry = jit_fn(seg_params, carry)
                    except (TypeError, AttributeError):
                        # the dequantized retry failed the same way — the
                        # quantized nodes were a red herring; surface the
                        # ORIGINAL failure with its traceback
                        raise first_err from None
                self._segment_fns[key] = jit_fn
            else:
                carry = jit_fn(seg_params, carry)
        return plan["finalize"](carry)

    # -- misc ----------------------------------------------------------------

    @property
    def params(self):
        return self._materialize_full()

    def unwrap(self):
        return self._model


def _unflatten_by_paths(template, flat: dict):
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, _ in paths:
        key = ".".join(_ppart(p) for p in path)
        leaves.append(flat[key])
    return jax.tree.unflatten(jax.tree.structure(template), leaves)


# ---------------------------------------------------------------------------
# convenience wrappers (reference API)
# ---------------------------------------------------------------------------


def cpu_offload(model: Model, execution_device=None, offload_buffers=False, state_dict=None):
    """All weights on host, streamed per segment (reference ``cpu_offload``
    ``big_modeling.py:171``)."""
    return dispatch_model(model, {"": "cpu"})


def disk_offload(model: Model, offload_dir: str, execution_device=None, offload_buffers=False):
    """(Reference ``disk_offload`` ``big_modeling.py:261``.)"""
    return dispatch_model(model, {"": "disk"}, offload_dir=offload_dir)


def load_checkpoint_in_model(
    model: Model,
    checkpoint: str,
    device_map: Mapping | None = None,
    offload_folder: str | None = None,
    dtype=None,
    offload_state_dict_flag: bool = False,
    strict: bool = False,
    key_map: Callable[[dict], dict] | None = None,
):
    """Load a (possibly sharded, possibly torch-format) checkpoint into the
    model's params (reference ``load_checkpoint_in_model``
    ``utils/modeling.py:1796``). ``key_map`` converts foreign naming (e.g.
    HF transformers llama names) into this model's paths — the model zoo
    provides converters."""
    flat_ckpt = load_state_dict_from_files(checkpoint)
    paths, treedef = jax.tree_util.tree_flatten_with_path(model.params)
    native_keys = {".".join(_ppart(p) for p in path) for path, _ in paths}
    # only run the foreign-name converter when the checkpoint isn't already
    # in this model's native naming
    if len(native_keys & set(flat_ckpt)) < max(1, len(native_keys) // 2):
        if key_map is None:
            key_map = getattr(model, "convert_state_dict", None)
        if key_map is not None:
            flat_ckpt = key_map(flat_ckpt)
    # With a device_map in play, loaded values stay HOST-side (numpy): the
    # model may exceed HBM and dispatch_model does the placement. Only a
    # map-less load materialises on device.
    keep_on_host = device_map is not None

    def _materialise(value, target_dtype):
        if keep_on_host:
            return np.asarray(value).astype(_np_dtype(target_dtype), copy=False)
        return jnp.asarray(np.asarray(value), dtype=target_dtype)

    leaves = []
    missing = []
    for path, leaf in paths:
        key = ".".join(_ppart(p) for p in path)
        if key in flat_ckpt:
            value = flat_ckpt[key]
            target_dtype = dtype or getattr(leaf, "dtype", np.asarray(value).dtype)
            leaves.append(_materialise(value, target_dtype))
        else:
            missing.append(key)
            if strict:
                raise KeyError(f"checkpoint missing {key}")
            if isinstance(leaf, jax.ShapeDtypeStruct):
                zeros = np.zeros(leaf.shape, _np_dtype(leaf.dtype))
                leaves.append(zeros if keep_on_host else jnp.asarray(zeros))
            else:
                leaves.append(leaf)
    if missing:
        logger.warning(f"{len(missing)} params not found in checkpoint (e.g. {missing[:3]})")
    model.params = jax.tree.unflatten(jax.tree.structure(model.params), leaves)
    return model


def load_checkpoint_and_dispatch(
    model: Model,
    checkpoint: str,
    device_map: Mapping | str | None = None,
    max_memory: Mapping | None = None,
    no_split_module_classes=None,
    offload_folder: str | None = None,
    offload_buffers: bool = False,
    dtype=None,
    offload_state_dict_flag: bool | None = None,
    skip_keys=None,
    preload_module_classes=None,
    force_hooks: bool = False,
):
    """(Reference ``load_checkpoint_and_dispatch`` ``big_modeling.py:508``.)"""
    if isinstance(device_map, str):
        # expand stacked layer dims so the map splits at layer granularity —
        # dispatch_model probes the same 'layers.<i>.<name>' keys
        shapes = flat_param_shapes(
            model, expand_stacked=getattr(model, "stacked_params_prefix", None)
        )
        if device_map == "balanced":
            max_memory = get_balanced_memory(
                shapes, max_memory, no_split_module_classes, dtype=dtype
            )
        device_map = infer_auto_device_map(
            shapes,
            max_memory=max_memory,
            no_split_module_classes=no_split_module_classes,
            dtype=dtype,
            tied_parameters=list(getattr(model, "tied_parameters", []) or []),
        )
    load_checkpoint_in_model(
        model, checkpoint, device_map=device_map, offload_folder=offload_folder, dtype=dtype
    )
    if device_map is None:
        return model
    return dispatch_model(model, device_map, offload_dir=offload_folder)
