from .pipeline import gpipe, pipeline_microbatches
from .sharding import (
    PlacementDecision,
    explain_partition_spec,
    infer_param_sharding,
    opt_state_sharding_like,
    partition_spec_for,
    shard_params,
)
