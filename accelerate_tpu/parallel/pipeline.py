"""Pipeline-parallel training: a GPipe schedule over the ``pp`` mesh axis.

The reference delegates pipeline-parallel *training* to Megatron
(``pp_degree``/``num_micro_batches``, reference ``utils/dataclasses.py:1836,1912``)
and covers *inference* pipelining with PiPPy (``inference.py:31-184``; our
analog is :mod:`accelerate_tpu.inference`). This module is the TPU-native
training analog: instead of per-stage processes exchanging activations over
NCCL P2P, the whole pipeline is ONE jitted SPMD program —

* layer-stacked parameters (leading ``[layers]`` axis, the same layout the
  training scan uses) are sharded over the ``pp`` mesh axis, so each device
  group holds ``layers/num_stages`` contiguous layers;
* a ``jax.shard_map`` manual only over ``pp`` (every other mesh axis stays
  GSPMD-auto, so dp/fsdp/tp sharding *composes* with pipelining) runs the
  classic GPipe tick loop as a ``lax.scan``: at tick ``t`` stage ``s``
  processes microbatch ``t - s``, then hands its activation to stage
  ``s + 1`` via ``jax.lax.ppermute``;
* forward + backward through the schedule is plain ``jax.grad`` — ppermute
  transposes to the reverse permutation, so the backward pipeline falls out
  of autodiff instead of a hand-written 1F1B runtime.

Bubble fraction is the textbook ``(S-1)/(M+S-1)`` for ``S`` stages and
``M`` microbatches — choose ``M >= 4*S`` to keep it under ~20%.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from ..utils.compat import shard_map

P = PartitionSpec


def set_default_microbatches(n: int) -> None:
    """Set the session default for the GPipe microbatch count (0 = auto).

    The default rides the parallelism context (``AttentionContext``) set by
    ``Accelerator.__init__`` from ``MegatronLMPlugin.num_micro_batches``
    (reference field ``utils/dataclasses.py:1912``), so it shares the mesh's
    lifecycle instead of living in a module global. Model configs that set
    their own ``pipeline_microbatches`` take precedence.
    """
    from ..ops.attention import get_attention_context, set_attention_context
    from dataclasses import replace

    set_attention_context(replace(get_attention_context(), pipeline_microbatches=int(n)))


def remat_wrap(body, remat):
    """Apply the configured rematerialisation to a scan body.

    ``remat`` is False (save everything), True (full recompute), or a
    ``jax.checkpoint_policies`` name — e.g. ``"dots_saveable"`` keeps
    matmul outputs resident and recomputes only elementwise work, trading
    a fraction of full-remat's FLOPs for most of its memory win (the
    activation_checkpointing knob of the FSDP plugin maps here; reference
    wires torch's ``checkpoint_wrapper`` at ``accelerator.py:1523``)."""
    if not remat:
        return body
    policy = None
    if isinstance(remat, str):
        policy = getattr(jax.checkpoint_policies, remat, None)
        if policy is None:
            raise ValueError(
                f"unknown remat policy {remat!r}: expected a "
                "jax.checkpoint_policies name, e.g. 'dots_saveable' or "
                "'dots_with_no_batch_dims_saveable'"
            )
    return jax.checkpoint(body, prevent_cse=False, policy=policy)


def validate_pipeline_axes(mesh_shape: dict) -> None:
    """pp×cp compose since round 4: the cp attention's shard_map claims
    only its own axes (``parallel/context.py`` passes ``axis_names``), so
    it nests inside the GPipe stage body whose shard_map is manual over
    ``pp`` alone. Kept as the single owner of any future composition
    rule; currently every combination is accepted."""


def active_pipeline_mesh():
    """The active mesh when GPipe pipeline training is configured (``pp``
    axis extent > 1), else None. The mesh comes from the parallelism
    context ``Accelerator.prepare`` sets for attention routing."""
    from ..ops.attention import get_attention_context

    mesh = get_attention_context().mesh
    if mesh is None or dict(mesh.shape).get("pp", 1) <= 1:
        return None
    validate_pipeline_axes(dict(mesh.shape))
    return mesh


def ensure_no_pipeline_axis(model_name: str) -> None:
    """Guard for models without a GPipe execution path: a ``pp`` axis > 1
    would otherwise silently run un-pipelined while the sharding planner
    still splits their stacked layers across stages."""
    if active_pipeline_mesh() is not None:
        raise NotImplementedError(
            f"pipeline-parallel execution is not implemented for "
            f"{model_name}; use a mesh with pp=1 (every built-in family "
            f"implements the GPipe path via parallel.pipeline_layer_stack)"
        )


def pipeline_microbatches(batch: int, num_microbatches: int, num_stages: int) -> int:
    """Validate/resolve the microbatch count for a GPipe run.

    ``num_microbatches == 0`` means auto: the session default from
    :func:`set_default_microbatches` if set AND it divides ``batch``
    (an inherited default that doesn't divide falls through to auto
    resolution rather than raising at trace time), else the smallest
    divisor of ``batch`` that is >= ``num_stages``, so the schedule always
    has at least one microbatch in flight per stage (falls back to
    ``batch`` itself).
    """
    if num_microbatches == 0:
        from ..ops.attention import get_attention_context

        inherited = get_attention_context().pipeline_microbatches
        if inherited < 0:
            raise ValueError(f"num_microbatches must be >= 1, got {inherited}")
        if inherited >= 1:
            if batch % inherited == 0:
                num_microbatches = inherited
            else:
                import logging

                logging.getLogger(__name__).warning(
                    "configured num_micro_batches=%d does not divide global "
                    "batch %d; falling back to auto microbatch resolution",
                    inherited,
                    batch,
                )
    if num_microbatches:
        if num_microbatches < 1:
            raise ValueError(f"num_microbatches must be >= 1, got {num_microbatches}")
        if batch % num_microbatches != 0:
            raise ValueError(
                f"global batch {batch} is not divisible by "
                f"num_microbatches={num_microbatches}"
            )
        return num_microbatches
    for m in range(num_stages, batch + 1):
        if batch % m == 0:
            return m
    return batch


def pipeline_layer_stack(
    layer_fn: Callable,
    stage_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    remat=False,
    positions: jax.Array | None = None,
    mask: jax.Array | None = None,
    extra_aligned: tuple = (),
    rope: tuple = (),
    num_microbatches: int = 0,
    with_aux: bool = False,
):
    """Run a transformer layer stack as a GPipe pipeline — the one owner of
    the operand convention every model family shares.

    ``layer_fn(layer, x_mb, positions_mb, mask_mb, *extra_mb, *rope) ->
    y_mb`` (or ``(y_mb, aux_scalar)`` with ``with_aux``) applies ONE
    unstacked layer. ``positions``/``mask`` are per-example ``[batch, ...]``
    operands that ride the microbatch schedule (either may be None), as do
    ``extra_aligned`` operands (e.g. t5's encoder output for
    cross-attention); ``rope`` tables are broadcast to every stage call.
    The scan over each stage's local layers (with ``remat`` applied per
    block) is built here so models don't duplicate the aligned/broadcast
    packing or the aux carry.
    """
    aligned = tuple(a for a in (positions, mask) if a is not None) + tuple(extra_aligned)
    has_pos = positions is not None
    has_mask = mask is not None

    def stage_fn(local_layers, x_mb, *ops):
        pos_mb = ops[0] if has_pos else None
        mask_mb = ops[int(has_pos)] if has_mask else None
        extra_mb = ops[int(has_pos) + int(has_mask) : len(aligned)]
        rope_ops = ops[len(aligned):]
        if with_aux:
            def body(carry, layer):
                h, aux_sum = carry
                h, aux = layer_fn(layer, h, pos_mb, mask_mb, *extra_mb, *rope_ops)
                return (h, aux_sum + aux), None

            (y, aux), _ = jax.lax.scan(
                remat_wrap(body, remat),
                (x_mb, jnp.asarray(0.0, jnp.float32)),
                local_layers,
            )
            return y, aux

        def body(h, layer):
            return layer_fn(layer, h, pos_mb, mask_mb, *extra_mb, *rope_ops), None

        y, _ = jax.lax.scan(remat_wrap(body, remat), x_mb, local_layers)
        return y

    return gpipe(
        stage_fn, stage_params, x,
        mesh=mesh,
        aligned=aligned,
        broadcast=rope,
        num_microbatches=num_microbatches,
        with_aux=with_aux,
    )


def _validate_layer_stack(stage_params, nstages: int, axis: str) -> None:
    """Stacked-layer shape agreement + stage divisibility (shared by the
    training and generation pipeline engines)."""
    layer_lens = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if len(layer_lens) > 1:
        raise ValueError(
            f"stage_params leaves disagree on the stacked layer axis "
            f"(leading dims {sorted(layer_lens)}); every leaf must share "
            f"the same [layers] leading axis"
        )
    for n_layers in layer_lens:
        if n_layers % nstages != 0:
            raise ValueError(
                f"stacked layer axis of length {n_layers} must divide "
                f"evenly into {axis}={nstages} pipeline stages"
            )


def pipeline_cached_stack(
    stage_fn: Callable,
    stage_params,
    kv_cache: tuple,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "pp",
    broadcast: tuple = (),
):
    """Run a layer stack with STAGE-LOCAL KV caches over the ``pp`` axis —
    the generation (prefill/decode) counterpart of :func:`gpipe`.

    Training pipelining wants microbatch overlap; cached generation wants
    the cache to stay where its layers live. This engine runs the classic
    single-microbatch tick chain: every stage applies its local layers each
    tick, activations hop forward over ``ppermute``, and each stage commits
    its cache update only at ITS tick (``t == stage``), when the activation
    reaching it is the real one. The K/V cache never leaves its stage —
    decode moves one ``[b, 1, h]`` activation across ICI per hop instead of
    all-gathering ``layers/S`` weight shards per token (reference-side
    analog: PiPPy serves generation by feeding microbatches through stages,
    ``inference.py:99-122``).

    Args:
      stage_fn: ``(local_layers, local_k, local_v, x, *broadcast) ->
        (y, new_local_k, new_local_v)`` — applies this stage's layer slice,
        returning updated local caches (same shapes).
      stage_params: ``[L, ...]`` pytree split over ``axis`` like gpipe.
      kv_cache: ``(k, v)`` arrays ``[L, b, ...]`` split over ``axis`` on
        dim 0 (zeros for prefill).
      x: activations entering stage 0 (already embedded).
      broadcast: operands handed to every stage call unchanged.

    Returns ``(y, (k, v))``: last-stage output replicated over ``axis``,
    caches still split over it.
    """
    nstages = dict(mesh.shape).get(axis, 1)
    k_cache, v_cache = kv_cache
    if nstages <= 1:
        y, k2, v2 = stage_fn(stage_params, k_cache, v_cache, x, *broadcast)
        return y, (k2, v2)
    _validate_layer_stack(stage_params, nstages, axis)

    fwd_perm = [(i, i + 1) for i in range(nstages - 1)]
    back_perm = [(i + 1, i) for i in range(nstages - 1)]
    # On TPU, skip the ticks where this stage's activation hasn't arrived
    # yet (lax.cond): the predicate is uniform across the auto axes (tp/dp
    # peers share the pp coordinate), so auto-axis collectives inside the
    # branch stay uniform, and inactive stages idle instead of computing
    # discarded work. XLA:CPU's collective rendezvous stalls on the
    # branch-gated collectives, so the CPU debug backend computes every
    # tick and masks with `where` — same results, correctness-only backend.
    use_cond = jax.devices()[0].platform != "cpu"

    def body(local_params, kc, vc, x, *broadcast_ops):
        stage = jax.lax.axis_index(axis)

        def tick(carry, t):
            state, kc, vc, out = carry
            active = t == stage

            def run(args):
                state, kc, vc = args
                return stage_fn(local_params, kc, vc, state, *broadcast_ops)

            def skip(args):
                return args

            if use_cond:
                y, kc, vc = jax.lax.cond(active, run, skip, (state, kc, vc))
            else:
                y, kc_new, vc_new = run((state, kc, vc))
                kc = jnp.where(active, kc_new, kc)
                vc = jnp.where(active, vc_new, vc)
            out = jnp.where(active & (stage == nstages - 1), y, out)
            state = jax.lax.ppermute(y, axis, fwd_perm)
            return (state, kc, vc, out), None

        (_, kc, vc, out), _ = jax.lax.scan(
            tick, (x, kc, vc, jnp.zeros_like(x)), jnp.arange(nstages)
        )
        # replicate the last stage's output backward (same ppermute chain
        # rationale as gpipe: psum's reduction region trips XLA:CPU's
        # AllReducePromotion under check_vma=False)
        for _ in range(nstages - 1):
            incoming = jax.lax.ppermute(out, axis, back_perm)
            out = jnp.where(stage == nstages - 1, out, incoming)
        return out, kc, vc

    n_b = len(broadcast)
    y, k2, v2 = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P()) + (P(),) * n_b,
        out_specs=(P(), P(axis), P(axis)),
        axis_names={axis},
        check_vma=False,
    )(stage_params, k_cache, v_cache, x, *broadcast)
    return y, (k2, v2)


def decode_stack(decode_layer_fn: Callable, layers, kv_cache: dict, x: jax.Array,
                 *, broadcast: tuple = ()):
    """Run a per-layer cached decode over the whole stack — plain
    ``lax.scan`` on a pp=1 mesh, :func:`pipeline_cached_stack` otherwise.
    The one owner of the "scan decode layer over (layers, k, v)" wrapper
    every causal family shares.

    ``decode_layer_fn(layer, h, kc_l, vc_l, *broadcast, pp_manual=...) ->
    (h, kc_l, vc_l)`` applies one UNstacked layer; ``pp_manual`` tells it
    the call runs inside the pp-manual shard_map (see the models'
    ``write_kv_cache`` pinning). Returns ``(h, {"k": ..., "v": ...})``.
    """
    mesh = active_pipeline_mesh()
    if mesh is None:

        def body(h, xs):
            layer, kc_l, vc_l = xs
            h, kc_l, vc_l = decode_layer_fn(layer, h, kc_l, vc_l, *broadcast, pp_manual=False)
            return h, (kc_l, vc_l)

        x, (kc, vc) = jax.lax.scan(body, x, (layers, kv_cache["k"], kv_cache["v"]))
        return x, {"k": kc, "v": vc}

    def stage_fn(local_layers, kc, vc, h, *ops):
        def body(carry, xs):
            layer, kc_l, vc_l = xs
            h2, kc_l, vc_l = decode_layer_fn(layer, carry, kc_l, vc_l, *ops, pp_manual=True)
            return h2, (kc_l, vc_l)

        y, (kc2, vc2) = jax.lax.scan(body, h, (local_layers, kc, vc))
        return y, kc2, vc2

    x, (kc, vc) = pipeline_cached_stack(
        stage_fn, layers, (kv_cache["k"], kv_cache["v"]), x, mesh=mesh, broadcast=broadcast
    )
    return x, {"k": kc, "v": vc}


def prefill_stack(prefill_layer_fn: Callable, layers, x: jax.Array,
                  cache_shape: tuple, *, broadcast: tuple = ()):
    """Forward the stack while collecting each layer's (padded) K/V — the
    prefill counterpart of :func:`decode_stack`.

    ``prefill_layer_fn(layer, h, *broadcast) -> (h, (k_pad, v_pad))``
    applies one UNstacked layer and returns its cache row already padded
    to ``cache_shape[2:]``. Returns ``(h, {"k": ..., "v": ...})`` with
    caches ``cache_shape`` = ``[L, b, max_cache, n_kv, hd]``.
    """
    mesh = active_pipeline_mesh()
    if mesh is None:

        def body(h, layer):
            return prefill_layer_fn(layer, h, *broadcast)

        x, (kc, vc) = jax.lax.scan(body, x, layers)
        return x, {"k": kc, "v": vc}

    cache0 = jnp.zeros(cache_shape, x.dtype)

    def stage_fn(local_layers, kc, vc, h, *ops):
        def body(h, layer):
            return prefill_layer_fn(layer, h, *ops)

        y, (knew, vnew) = jax.lax.scan(body, h, local_layers)
        return y, knew, vnew

    x, (kc, vc) = pipeline_cached_stack(
        stage_fn, layers, (cache0, cache0), x, mesh=mesh, broadcast=broadcast
    )
    return x, {"k": kc, "v": vc}


def prefill_layer_stack(layer_fn: Callable, layers, x: jax.Array,
                        cache_shape: tuple, *, positions=None, mask=None,
                        rope: tuple = ()):
    """Convention-owning wrapper over :func:`prefill_stack` (the prefill
    analog of :func:`pipeline_layer_stack`): models hand over their
    operands once and ``layer_fn(layer, h, positions, mask, *rope) ->
    (h, (k_pad, v_pad))`` receives them positionally inside any backend —
    no per-family packing/unpacking of the broadcast tuple to keep in
    sync. ``positions``/``mask`` may be None."""
    has_pos = positions is not None
    has_mask = mask is not None
    ops = tuple(o for o in (positions, mask) if o is not None) + tuple(rope)

    def fn(layer, h, *rest):
        pos_b = rest[0] if has_pos else None
        mask_b = rest[int(has_pos)] if has_mask else None
        rope_ops = rest[int(has_pos) + int(has_mask):]
        return layer_fn(layer, h, pos_b, mask_b, *rope_ops)

    return prefill_stack(fn, layers, x, cache_shape, broadcast=ops)


def gpipe(
    stage_fn: Callable,
    stage_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    aligned: tuple = (),
    broadcast: tuple = (),
    num_microbatches: int = 0,
    axis: str = "pp",
    with_aux: bool = False,
):
    """Run ``stage_fn`` as a GPipe pipeline over ``mesh`` axis ``axis``.

    Args:
      stage_fn: ``(local_stage_params, x_mb, *aligned_mb, *broadcast) ->
        y_mb`` — applies this stage's slice of the layer stack to one
        microbatch. Called inside a ``shard_map`` that is manual over
        ``axis`` only; sharding constraints over other axes inside are
        legal (they stay auto).
      stage_params: pytree whose leaves have a leading ``[layers]`` axis
        divisible by the ``pp`` extent. The leading axis is split across
        stages (stage ``s`` gets layers ``[s*L/S, (s+1)*L/S)``).
      x: ``[batch, ...]`` activations entering the first stage.
      aligned: per-example operands ``[batch, ...]`` (attention mask,
        positions) — microbatched like ``x``; at tick ``t`` stage ``s``
        receives the slice for the microbatch it is processing (``t - s``).
      broadcast: operands passed to every stage call unchanged (rope
        tables, scalars).
      num_microbatches: GPipe microbatch count (0 = auto, see
        :func:`pipeline_microbatches`).
      with_aux: ``stage_fn`` additionally returns a f32 scalar per call
        (e.g. an MoE load-balancing statistic); gpipe returns
        ``(outputs, aux)`` where aux is the mean over microbatches of the
        per-stage sums, psum'd over the pipeline — i.e. the same
        "sum over layers, averaged over the batch it was computed on"
        contract the dense scan has, computed per microbatch (standard
        MoE×GPipe semantics: routing statistics are per-microbatch).

    Returns ``[batch, ...]`` activations out of the last stage, replicated
    over ``axis`` (other-axis sharding untouched); with ``with_aux``,
    ``(outputs, aux_scalar)``.
    """
    nstages = dict(mesh.shape).get(axis, 1)
    if nstages <= 1:
        return stage_fn(stage_params, x, *aligned, *broadcast)
    _validate_layer_stack(stage_params, nstages, axis)
    b = x.shape[0]
    m = pipeline_microbatches(b, num_microbatches, nstages)
    mb = b // m

    # XLA:CPU hardening: shard_map's check_vma=False transpose inserts
    # psums over the manual axis whose reduction regions are copy-rooted;
    # AllReducePromotion then check-fails on any that are bf16 ("Invalid
    # binary instruction opcode copy"). Keep every value crossing the
    # shard_map boundary (and the inter-stage ppermute traffic) f32 on the
    # CPU backend; stage compute still runs in the original dtype. On TPU
    # the pass doesn't run and bf16 rides the ICI links natively.
    _narrow = (jnp.bfloat16, jnp.float16)
    cpu_widen = jax.devices()[0].platform == "cpu" and (
        x.dtype in _narrow
        or any(a.dtype in _narrow for a in aligned)
        or any(b.dtype in _narrow for b in broadcast)
    )
    compute_dtype = x.dtype
    # original dtypes of the other operands — differentiable bf16 operands
    # (t5's rel-bias tables, encoder output) must also cross the boundary
    # in f32 or their cotangent psums hit the same XLA:CPU crash
    aligned_dtypes = tuple(a.dtype for a in aligned)
    broadcast_dtypes = tuple(b.dtype for b in broadcast)

    def _widen(v):
        return v.astype(jnp.float32) if v.dtype in _narrow else v

    if cpu_widen:
        x = x.astype(jnp.float32)
        aligned = tuple(_widen(a) for a in aligned)
        broadcast = tuple(_widen(b) for b in broadcast)

    x_mb = x.reshape(m, mb, *x.shape[1:])
    aligned_mb = tuple(a.reshape(m, mb, *a.shape[1:]) for a in aligned)

    fwd_perm = [(i, i + 1) for i in range(nstages - 1)]

    def body(local_params, x_mb, *rest):
        aligned_ops = rest[: len(aligned_mb)]
        broadcast_ops = rest[len(aligned_mb) :]
        stage = jax.lax.axis_index(axis)
        state0 = jnp.zeros_like(x_mb[0])
        outputs0 = jnp.zeros_like(x_mb)
        aux0 = jnp.asarray(0.0, jnp.float32)

        def tick(carry, t):
            state_in, outputs, aux_acc = carry
            inject = x_mb[jnp.clip(t, 0, m - 1)]
            state_in = jnp.where(stage == 0, inject, state_in)
            # microbatch id this stage is processing at tick t (clipped:
            # out-of-range ticks compute on garbage whose output is masked)
            mb_idx = jnp.clip(t - stage, 0, m - 1)
            valid = (t - stage >= 0) & (t - stage < m)
            aligned_t = tuple(
                jax.lax.dynamic_index_in_dim(a, mb_idx, axis=0, keepdims=False)
                for a in aligned_ops
            )
            if cpu_widen:
                # stage compute still runs at the original dtypes; only the
                # boundary crossing (and its transpose psums) is f32
                state_arg = state_in.astype(compute_dtype)
                aligned_t = tuple(
                    a.astype(d) for a, d in zip(aligned_t, aligned_dtypes)
                )
                broadcast_args = tuple(
                    b.astype(d) for b, d in zip(broadcast_ops, broadcast_dtypes)
                )
            else:
                state_arg = state_in
                broadcast_args = broadcast_ops
            res = stage_fn(local_params, state_arg, *aligned_t, *broadcast_args)
            if with_aux:
                y, aux = res
                aux_acc = aux_acc + jnp.where(valid, aux.astype(jnp.float32), 0.0)
            else:
                y = res
            if cpu_widen:
                y = y.astype(jnp.float32)
            out_idx = t - (nstages - 1)
            emit = (stage == nstages - 1) & (out_idx >= 0)
            idx = jnp.clip(out_idx, 0, m - 1)
            prev = jax.lax.dynamic_index_in_dim(outputs, idx, axis=0, keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(emit, y, prev), idx, axis=0
            )
            # hand activation to the next stage; stage 0 receives zeros
            # (no wraparound edge) and overwrites them with its injection
            state_out = jax.lax.ppermute(y, axis, fwd_perm)
            return (state_out, outputs, aux_acc), None

        (_, outputs, aux_acc), _ = jax.lax.scan(
            tick, (state0, outputs0, aux0), jnp.arange(m + nstages - 1)
        )
        # Replicate the last stage's outputs to every stage so downstream
        # (final norm / lm head / loss) runs replicated over pp. Done as a
        # backward ppermute chain rather than a masked psum: the psum's
        # reduction region acquires a copy-rooted computation under
        # check_vma=False, and XLA CPU's AllReducePromotion pass
        # check-fails cloning it ("Invalid binary instruction opcode
        # copy"); collective-permutes sidestep the pass, and the chain has
        # the same S-1 hop latency the psum ring would.
        back_perm = [(i + 1, i) for i in range(nstages - 1)]
        for _ in range(nstages - 1):
            incoming = jax.lax.ppermute(outputs, axis, back_perm)
            outputs = jnp.where(stage == nstages - 1, outputs, incoming)
        if with_aux:
            # total over stages (each stage summed its own layers' aux over
            # its m valid ticks), averaged over microbatches; stays f32 so
            # the psum never enters XLA:CPU's bf16 promotion pass
            aux_total = jax.lax.psum(aux_acc, axis) / m
            return outputs, aux_total
        return outputs

    n_rest = len(aligned_mb) + len(broadcast)
    out_specs = (P(), P()) if with_aux else P()
    res = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P()) + (P(),) * n_rest,
        out_specs=out_specs,
        axis_names={axis},
        check_vma=False,
    )(stage_params, x_mb, *aligned_mb, *broadcast)
    y_mb, aux = res if with_aux else (res, None)
    y = y_mb.reshape(b, *x.shape[1:]).astype(compute_dtype)
    return (y, aux) if with_aux else y
