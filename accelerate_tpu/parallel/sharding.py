"""GSPMD sharding planner — the FSDP/ZeRO-3 equivalent.

The reference wraps modules in ``torch.distributed.fsdp`` with plugin-driven
kwargs (``/root/reference/src/accelerate/accelerator.py:1473-1592``). Here
"fully sharded" is a *placement decision*, not a wrapper: every parameter
gets a ``NamedSharding`` over the ``fsdp`` mesh axis (and ``tp`` when rules
say so), XLA inserts the all-gathers on use and reduce-scatters on grads —
ZeRO-3's gather-on-use is GSPMD's native execution model.

Sharding policy, in priority order:
1. model-provided partition rules (path-regex → PartitionSpec), for tensor
   parallelism and hand-tuned layouts;
2. FSDP policy: shard the largest dimension divisible by the ``fsdp`` axis
   extent, for params with ≥ ``min_num_params`` elements;
3. replicate.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..utils.dataclasses import FullyShardedDataParallelPlugin

logger = logging.getLogger(__name__)

P = PartitionSpec

#: (param path, axis repr) pairs already warned about — the divisibility
#: fallback warns ONCE per site, not once per step (the runtime twin of
#: shard-check's SP003 finding)
_DIVISIBILITY_WARNED: set[tuple[str, str]] = set()


def _path_to_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return ".".join(parts)


@dataclass(frozen=True)
class PlacementDecision:
    """One parameter's placement, with the *why* attached — the record the
    ``shard-check`` static analyzer turns into SP001/SP002/SP003 findings.

    ``dropped`` lists rule entries the divisibility validation discarded:
    ``(dim, axis_repr, extent)`` triples, ``extent`` 0 when the axis is
    absent from the mesh entirely."""

    spec: PartitionSpec
    #: "rule" (a partition rule matched), "fsdp" (size policy), or
    #: "replicated" (no rule, policy declined or found no divisible dim)
    source: str
    rule_index: int | None
    dropped: tuple[tuple[int, str, int], ...]


def explain_partition_spec(
    path_str: str,
    shape: tuple[int, ...],
    mesh,
    plugin: FullyShardedDataParallelPlugin | None,
    rules: list[tuple[str, PartitionSpec]] | None,
) -> PlacementDecision:
    """Decide one parameter's PartitionSpec and say why. ``mesh`` only needs
    a ``.shape`` mapping — the shard-check analyzer passes a virtual axis
    map, the runtime passes a real :class:`jax.sharding.Mesh`."""
    # GPipe stage placement: layer-stacked params (leading [layers] axis,
    # path under "layers") split their stack over the pp axis so each stage
    # group holds only its own layers. Applied as an overlay on whatever
    # rule/policy decides for the other dims.
    sizes = dict(mesh.shape)
    pp_size = sizes.get("pp", 1)
    stacked = (
        pp_size > 1
        and re.search(r"(^|\.)layers(\.|$)", path_str) is not None
        and len(shape) >= 1
        and shape[0] % pp_size == 0
    )

    def overlay(spec: PartitionSpec) -> PartitionSpec:
        if not stacked:
            return spec
        entries = list(tuple(spec)) + [None] * (len(shape) - len(tuple(spec)))
        if entries[0] is None:
            entries[0] = "pp"
        return P(*entries)

    if rules:
        for i, (pattern, spec) in enumerate(rules):
            if re.search(pattern, path_str):
                validated, dropped = _validated(spec, shape, mesh)
                return PlacementDecision(overlay(validated), "rule", i, dropped)
    if plugin is None or not plugin.shards_params:
        return PlacementDecision(overlay(P()), "replicated", None, ())
    fsdp_size = sizes.get("fsdp", 1)
    if fsdp_size <= 1:
        return PlacementDecision(overlay(P()), "replicated", None, ())
    n_elements = int(np.prod(shape)) if shape else 0
    if n_elements < max(plugin.min_num_params, 2):
        return PlacementDecision(overlay(P()), "replicated", None, ())
    # shard the largest divisible dim over fsdp (dim 0 is reserved for the
    # stage split when the pp overlay applies)
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for dim in order:
        if stacked and dim == 0:
            continue
        if shape[dim] % fsdp_size == 0:
            spec = [None] * len(shape)
            spec[dim] = "fsdp"
            return PlacementDecision(overlay(P(*spec)), "fsdp", None, ())
    return PlacementDecision(overlay(P()), "replicated", None, ())


def partition_spec_for(
    path_str: str,
    shape: tuple[int, ...],
    mesh: Mesh,
    plugin: FullyShardedDataParallelPlugin | None,
    rules: list[tuple[str, PartitionSpec]] | None,
) -> PartitionSpec:
    """Decide the PartitionSpec for one parameter. A rule entry the
    divisibility validation discards is warned about once per (param, axis)
    — silently replicating a dim a rule asked to shard is exactly the
    surprise ``shard-check``'s SP003 exists to catch before the run."""
    decision = explain_partition_spec(path_str, shape, mesh, plugin, rules)
    for dim, axis, extent in decision.dropped:
        key = (path_str, axis)
        if key in _DIVISIBILITY_WARNED:
            continue
        _DIVISIBILITY_WARNED.add(key)
        if extent:
            logger.warning(
                "partition rule for %r asks to shard dim %d (size %s) over "
                "axis %s (extent %d), which does not divide — falling back "
                "to unsharded for that dim (shard-check names this SP003)",
                path_str, dim, shape[dim] if dim < len(shape) else "?",
                axis, extent,
            )
        else:
            logger.warning(
                "partition rule for %r names axis %s, which is not a mesh "
                "axis — entry ignored (shard-check names this SP003; lint "
                "rule TPU012 catches the literal)",
                path_str, axis,
            )
    return decision.spec


def _validated(
    spec: PartitionSpec, shape: tuple[int, ...], mesh
) -> tuple[PartitionSpec, tuple[tuple[int, str, int], ...]]:
    """Drop axes that don't divide the dim (defensive against bad rules).
    Returns the surviving spec plus the dropped entries as
    ``(dim, axis_repr, extent)`` — extent 0 for an axis the mesh lacks."""
    sizes = dict(mesh.shape)
    out = []
    dropped: list[tuple[int, str, int]] = []
    for i, entry in enumerate(tuple(spec)):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        extent = 1
        known = True
        for ax in axes:
            if ax not in sizes:
                known = False
                continue
            extent *= sizes[ax]
        if known and i < len(shape) and shape[i] % extent == 0:
            out.append(entry)
        else:
            out.append(None)
            dropped.append((i, repr(entry), extent if known else 0))
    return P(*out), tuple(dropped)


def infer_param_sharding(
    params: Any,
    mesh: Mesh,
    plugin: FullyShardedDataParallelPlugin | None = None,
    rules: list[tuple[str, PartitionSpec]] | None = None,
):
    """NamedSharding pytree matching ``params`` structure."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    shardings = []
    for path, leaf in flat:
        spec = partition_spec_for(
            _path_to_str(path), tuple(np.shape(leaf)), mesh, plugin, rules
        )
        shardings.append(NamedSharding(mesh, spec))
    return jax.tree.unflatten(jax.tree.structure(params), shardings)


def shard_params(params: Any, shardings: Any):
    """Place params per the sharding tree (idempotent for already-placed)."""
    return jax.tree.map(lambda p, s: jax.device_put(p, s), params, shardings)


def paged_kv_sharding(mesh: Mesh, num_kv_heads: int) -> NamedSharding:
    """Sharding for the serving engine's block-paged KV pools
    (``[layers, num_blocks, block_size, n_kv, head_dim]``): the kv-head dim
    over ``tp`` — K/V are *produced* tp-sharded by the wk/wv projections
    (see ``LLAMA_PARTITION_RULES``), so storing the pool the same way keeps
    the block scatter/gather collective-free. Falls back to replicated when
    ``tp`` doesn't divide the head count (GQA models with few kv heads)."""
    tp = mesh.shape["tp"]
    if tp > 1 and num_kv_heads % tp == 0:
        return NamedSharding(mesh, P(None, None, None, "tp", None))
    return NamedSharding(mesh, P())


def paged_kv_scale_sharding(mesh: Mesh, num_kv_heads: int) -> NamedSharding:
    """Sharding for the quantized pool's amax scale arrays
    (``[layers, num_blocks, block_size, n_kv]``): the kv-head dim follows
    :func:`paged_kv_sharding` exactly — a scale row must live with the
    payload rows it dequantizes, or every fused-attention block read
    becomes a collective."""
    tp = mesh.shape["tp"]
    if tp > 1 and num_kv_heads % tp == 0:
        return NamedSharding(mesh, P(None, None, None, "tp"))
    return NamedSharding(mesh, P())


def opt_state_sharding_like(tx, params, param_shardings, mesh: Mesh):
    """Sharding tree for ``tx.init(params)``'s state: param-shaped leaves
    inherit the param's sharding (matched via optax's param-tree mirroring),
    scalars replicate. The torch analog is FSDP sharding optimizer state
    alongside flat params (reference ``utils/fsdp_utils.py``)."""
    import optax

    state_shape = jax.eval_shape(tx.init, params)
    replicated = NamedSharding(mesh, P())

    # Build shape→sharding lookup from params (the default policy makes the
    # spec a pure function of shape, so collisions are consistent).
    shape_map: dict[tuple, Any] = {}
    for leaf, sh in zip(jax.tree.leaves(params), jax.tree.leaves(param_shardings)):
        shape_map.setdefault(tuple(np.shape(leaf)), sh)

    def _sharding_for(leaf):
        return shape_map.get(tuple(leaf.shape), replicated)

    try:
        # Precise structural matching when optax can mirror the param tree.
        spec = optax.tree_map_params(
            tx,
            lambda _, s: s,
            state_shape,
            param_shardings,
            transform_non_params=lambda leaf: _sharding_for(leaf)
            if hasattr(leaf, "shape")
            else replicated,
        )
        return spec
    except Exception:
        return jax.tree.map(_sharding_for, state_shape)
