"""Context parallelism: ring attention and Ulysses over the ``cp`` mesh axis.

The reference has NO long-context machinery (SURVEY §5: grep finds only
Megatron's SP flag) — this module is capability the TPU build adds. Design:

* **ring attention** — activations stay sequence-sharded on ``cp``; each
  device holds one Q chunk and streams every KV chunk past it with
  ``jax.lax.ppermute`` (one ICI hop per step), merging per-chunk partial
  attention with the online-softmax rule. Peak memory is O(s_local · s_local)
  per step instead of O(s²); comm is the KV chunk, fully overlappable.
* **Ulysses** — ``all_to_all`` reshards [seq-sharded, all heads] →
  [all seq, head-sharded], runs dense (flash) attention locally, reshards
  back. Cheaper compute (one softmax), more comm; wins when heads ≥ cp.
* **allgather** — baseline: gather full KV on every device (what GSPMD
  would do implicitly); kept for cross-checking and tiny cp sizes.

Gradients flow through ``ppermute``/``all_to_all`` natively (their
transposes are the inverse permutation / the reverse all_to_all), so one
``jax.grad`` over the whole step differentiates the ring.

These functions run *inside* ``shard_map``; :func:`context_parallel_attention`
is the jit-level entry that wraps them over the mesh.
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.flash_attention import NEG_INF, blockwise_attention, flash_attention

from ..utils.compat import axis_size, shard_map


# ---------------------------------------------------------------------------
# per-device building block: one Q-chunk × one KV-chunk online-softmax update
# ---------------------------------------------------------------------------


def _chunk_update(carry, q, k_chunk, v_chunk, kv_valid, q_offset, kv_offset, scale, causal):
    """Merge attention of local Q against one KV chunk into (acc, m, l).

    q: [b, sq, h, d]; k_chunk/v_chunk: [b, sk, h, d]; kv_valid: [b, sk] bool.
    q_offset/kv_offset are *global token offsets* (traced) of the chunks.
    """
    acc, m_run, l_run = carry
    b, sq, h, d = q.shape
    sk = k_chunk.shape[1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k_chunk.astype(jnp.float32)
    ) * scale
    mask = kv_valid[:, None, None, :]
    if causal:
        q_pos = q_offset + jnp.arange(sq)
        kv_pos = kv_offset + jnp.arange(sk)
        mask = mask & (q_pos[:, None] >= kv_pos[None, :])[None, None]
    s = jnp.where(mask, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1)  # [b,h,sq]
    m_new = jnp.maximum(m_run, m_cur)
    # fully-masked rows: m_new == NEG_INF (finite) would give exp(0)=1,
    # turning the row into mean(v); zero p so l stays 0 → output 0
    p = jnp.where(m_new[..., None] == NEG_INF, 0.0, jnp.exp(s - m_new[..., None]))
    alpha = jnp.exp(m_run - m_new)
    l_new = alpha * l_run + jnp.sum(p, axis=-1)
    acc = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_chunk.astype(jnp.float32)
    )
    return acc, m_new, l_new


def ring_attention_local(
    q: jax.Array,  # [b, s_local, h, d]
    k: jax.Array,
    v: jax.Array,
    kv_valid: jax.Array,  # [b, s_local] bool
    *,
    axis_name: str = "cp",
    causal: bool = True,
    scale: float | None = None,
    use_flash: bool | None = None,
    cp_index: jax.Array | None = None,
) -> jax.Array:
    """Ring attention body (call inside shard_map over ``axis_name``).

    On TPU the per-chunk compute runs the Mosaic flash kernel with a
    whole-ring custom VJP (``ops/ring_flash.py``) — O(s) memory and
    MXU-tiled chunk attention; elsewhere (and as the numerical oracle) the
    einsum online-softmax body below.

    ``cp_index`` (a ``[1]`` array holding this shard's ring position,
    plumbed in as data by :func:`context_parallel_attention`) replaces
    ``jax.lax.axis_index``: inside a NESTED manual region (cp attention in
    a GPipe 'pp' stage body) the axis_index lowering claims the parent's
    manual axes and the verifier rejects it."""
    if use_flash is None:
        use_flash = jax.devices()[0].platform == "tpu"
    if use_flash:
        from ..ops.ring_flash import ring_flash_attention_local

        return ring_flash_attention_local(
            q, k, v, kv_valid, axis_name=axis_name, causal=causal, scale=scale,
            cp_index=cp_index,
        )
    n = axis_size(axis_name)
    idx = (
        cp_index.reshape(()).astype(jnp.int32)
        if cp_index is not None
        else jax.lax.axis_index(axis_name)
    )
    b, s_loc, h, d = q.shape
    scale = scale if scale is not None else 1.0 / float(np.sqrt(d))

    acc = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)

    q_offset = idx * s_loc
    k_cur, v_cur, valid_cur = k, v, kv_valid
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        src = (idx - step) % n  # chunk id currently held
        acc, m, l = _chunk_update(
            (acc, m, l), q, k_cur, v_cur, valid_cur, q_offset, src * s_loc, scale, causal
        )
        if step != n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            valid_cur = jax.lax.ppermute(valid_cur, axis_name, perm)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ulysses_attention_local(
    q: jax.Array,  # [b, s_local, h, d] — h divisible by cp size
    k: jax.Array,
    v: jax.Array,
    kv_valid: jax.Array,  # [b, s_local]
    *,
    axis_name: str = "cp",
    causal: bool = True,
    scale: float | None = None,
    use_flash: bool | None = None,
    cp_index: jax.Array | None = None,  # unused: no per-shard offsets here
) -> jax.Array:
    """Ulysses body: all_to_all seq↔head reshard around dense local attention."""
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    # [b, s_loc, h, d] -> [b, s, h/n, d]
    qg = a2a(q, split_axis=2, concat_axis=1)
    kg = a2a(k, split_axis=2, concat_axis=1)
    vg = a2a(v, split_axis=2, concat_axis=1)
    valid_g = jax.lax.all_gather(kv_valid, axis_name, axis=1, tiled=True)  # [b, s]
    if use_flash is None:
        use_flash = jax.devices()[0].platform == "tpu"
    if use_flash:
        out = flash_attention(qg, kg, vg, segment_mask=valid_g, causal=causal, scale=scale)
    else:
        out = blockwise_attention(qg, kg, vg, segment_mask=valid_g, causal=causal, scale=scale)
    # [b, s, h/n, d] -> [b, s_loc, h, d]
    return a2a(out, split_axis=1, concat_axis=2)


def allgather_attention_local(
    q, k, v, kv_valid, *, axis_name="cp", causal=True, scale=None, use_flash=None,
    cp_index=None,
):
    """Baseline: gather all KV chunks, run dense attention on the local Q
    chunk with the right global offset."""
    n = axis_size(axis_name)
    idx = (
        cp_index.reshape(()).astype(jnp.int32)
        if cp_index is not None
        else jax.lax.axis_index(axis_name)
    )
    b, s_loc, h, d = q.shape
    kg = jax.lax.all_gather(k, axis_name, axis=1, tiled=True)
    vg = jax.lax.all_gather(v, axis_name, axis=1, tiled=True)
    valid_g = jax.lax.all_gather(kv_valid, axis_name, axis=1, tiled=True)
    scale = scale if scale is not None else 1.0 / float(np.sqrt(d))
    # causal with offset: reuse the chunk-update math in one shot
    acc = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)
    acc, m, l = _chunk_update((acc, m, l), q, kg, vg, valid_g, idx * s_loc, 0, scale, causal)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe[..., None]).transpose(0, 2, 1, 3).astype(q.dtype)


_LOCAL_BODIES = {
    "ring": ring_attention_local,
    "ulysses": ulysses_attention_local,
    "allgather": allgather_attention_local,
}


# ---------------------------------------------------------------------------
# jit-level entry: shard_map the body over the mesh
# ---------------------------------------------------------------------------


def context_parallel_attention(
    q: jax.Array,  # [b, s, h, d] global (GSPMD-sharded) arrays
    k: jax.Array,
    v: jax.Array,
    segment_mask: jax.Array | None = None,  # [b, s] 1 = valid KV token
    *,
    mesh: Mesh,
    mode: Literal["ring", "ulysses", "allgather"] = "ring",
    causal: bool = True,
    scale: float | None = None,
    cp_axis: str = "cp",
    batch_axes: tuple[str, ...] = ("dp", "fsdp"),
    head_axis: str = "tp",
) -> jax.Array:
    """Sequence-parallel attention over ``cp``, batch over dp/fsdp, heads
    over tp. GQA KV heads are repeated to full head count first (they must
    divide the tp extent anyway)."""
    b, s, nh, d = q.shape
    if k.shape[2] != nh:
        rep = nh // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if segment_mask is None:
        segment_mask = jnp.ones((b, s), dtype=bool)
    else:
        segment_mask = segment_mask.astype(bool)

    # Adapt specs to the actual shapes: drop sharding axes that don't divide
    # the corresponding dim (e.g. batch 1 on a dp=2 mesh stays replicated).
    from ..ops.attention import adapt_attention_specs

    shape = dict(mesh.shape)
    batch_entry, head_entry = adapt_attention_specs(
        shape, b, nh, nh, batch_axes, head_axis
    )
    cp_extent = shape.get(cp_axis, 1)
    if s % cp_extent != 0:
        raise ValueError(
            f"sequence length {s} must be divisible by the {cp_axis!r} mesh "
            f"extent {cp_extent} for context parallelism"
        )
    if mode == "ulysses":
        # the all_to_all splits the *local* head dim (after any tp sharding)
        local_heads = nh // shape.get(head_axis, 1) if head_entry else nh
        if local_heads % cp_extent != 0:
            raise ValueError(
                f"ulysses context parallelism re-shards heads over {cp_axis!r}: "
                f"per-shard head count {local_heads} (= {nh} heads"
                + (f" / {head_axis}={shape.get(head_axis, 1)}" if head_entry else "")
                + f") must be divisible by the {cp_axis!r} mesh extent {cp_extent}"
            )
    qkv_spec = P(batch_entry, cp_axis, head_entry, None)
    mask_spec = P(batch_entry, cp_axis)
    body = _LOCAL_BODIES[mode]

    # claim ONLY the axes this shard_map actually uses: every other mesh
    # axis stays auto, which is what lets the cp attention nest inside the
    # GPipe stage body (gpipe's shard_map is manual over 'pp' alone — a
    # nested map claiming 'pp' again would be rejected)
    used: set = {cp_axis}
    for entry in (batch_entry, head_entry):
        if entry is None:
            continue
        used.update(entry if isinstance(entry, tuple) else (entry,))

    # when tracing inside another manual region (the GPipe stage body is
    # shard_map'd over 'pp'), the nested map must be built on the CURRENT
    # abstract mesh — the one where 'pp' is already Manual — not the
    # concrete mesh, or jax rejects the mismatch
    mesh_arg = mesh
    try:
        am = jax.sharding.get_abstract_mesh()
        if getattr(am, "shape", None):
            mesh_arg = am
    except Exception:
        pass

    # this shard's ring position as DATA (a cp-sharded iota): inside a
    # nested manual region jax.lax.axis_index's lowering claims the
    # parent's manual axes, so the bodies take the index as an argument
    cp_pos = jnp.arange(cp_extent, dtype=jnp.float32)

    @functools.partial(
        shard_map,
        mesh=mesh_arg,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec, P(cp_axis)),
        out_specs=qkv_spec,
        axis_names=used,
        check_vma=False,
    )
    def _sharded(q_, k_, v_, valid_, cp_pos_):
        return body(
            q_, k_, v_, valid_, axis_name=cp_axis, causal=causal, scale=scale,
            cp_index=cp_pos_,
        )

    return _sharded(q, k, v, segment_mask, cp_pos)
