"""Deferred computation graph — the define-by-run autodiff shim.

The reference's user contract is imperative: ``outputs = model(**batch)``
then ``accelerator.backward(loss)`` (reference ``accelerator.py:2218``)
relies on torch's define-by-run autograd. JAX is define-then-run, so the
prepared model does **not** execute eagerly: calling it records a
:class:`Node` graph and returns :class:`Deferred` proxies. When the user
calls ``backward(loss)`` (or forces a value, e.g. ``.item()`` /
``gather_for_metrics``), the graph is replayed inside a single
``jit``-compiled function — compiled **once per graph signature** and cached,
so step 2..N of a training loop reuse the same executable with fresh batch
leaves. SURVEY §7 "API impedance" is resolved here.

Supported deferred surface: arithmetic (+,-,*,/,**,negation, comparisons),
reductions (mean/sum/max/min), shaping (reshape/transpose/squeeze/getitem),
``argmax``/``astype``, attribute/item access on model outputs, and
:func:`defer_call` for arbitrary traceable functions. Anything outside this
follows the same restriction class as ``torch.compile`` in the reference.
"""

from __future__ import annotations

import functools
import operator
import time
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from .analysis.sanitizer import get_active_sanitizer as _get_sanitizer
from .diagnostics.tracing import get_tracer as _get_tracer, trace_span as _trace_span


# ---------------------------------------------------------------------------
# graph nodes
# ---------------------------------------------------------------------------


class Node:
    __slots__ = ("op", "args", "static")

    def __init__(self, op: str, args: tuple, static: tuple = ()):
        self.op = op          # operation name
        self.args = args      # operand Nodes / raw leaves
        self.static = static  # hashable non-array parameters (axis, fn id, …)


class InputNode(Node):
    """A concrete array fed in at execution time (a batch tensor, a constant).
    Concrete operands are *always* lifted to inputs — never baked into the
    trace — so a cached executable replays correctly with fresh data."""

    __slots__ = ("value", "_input_idx")

    def __init__(self, value):
        super().__init__("input", ())
        self.value = value
        self._input_idx = -1


class ModelCallNode(Node):
    """Application of a prepared model to a pytree of (possibly deferred)
    inputs. ``model`` is static (closed over at trace time); array leaves of
    args/kwargs become graph inputs.

    ``compute_dtype``/``fp8_recipe`` snapshot the model's precision policy
    AT CALL TIME — replay happens later (at ``step()``/``force()``), by
    which point an ``autocast(enabled=False)`` island has exited; the
    snapshot is what makes the island apply to deferred calls made inside
    it. Both are part of the jit-cache signature (see ``linearize``)."""

    __slots__ = ("model", "call_args", "call_kwargs", "compute_dtype", "fp8_recipe")

    def __init__(self, model, call_args: tuple, call_kwargs: dict):
        super().__init__("model_call", ())
        self.model = model
        self.call_args = call_args
        self.call_kwargs = call_kwargs
        self.compute_dtype = getattr(model, "compute_dtype", None)
        self.fp8_recipe = getattr(model, "fp8_recipe", None)


def _is_array(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray)) or np.isscalar(x)


def as_node(x) -> Node:
    if isinstance(x, Deferred):
        return x._node
    if isinstance(x, Node):
        return x
    return InputNode(x)


# ---------------------------------------------------------------------------
# signature + linearisation
# ---------------------------------------------------------------------------


def _leaf_sig(v) -> tuple:
    if isinstance(v, (jax.Array, np.ndarray)):
        return ("arr", tuple(v.shape), str(v.dtype))
    return ("scalar", type(v).__name__)


def linearize(root: Node):
    """Topological walk collecting (signature, input_leaves, model_set).

    ``signature`` is a hashable canonical description of the graph with
    array leaves abstracted to shape/dtype — the jit-cache key.
    ``input_leaves`` are the concrete arrays in deterministic order.
    """
    sig_parts: list = []
    inputs: list = []
    models: list = []
    seen: dict[int, int] = {}

    def walk(node: Node) -> int:
        nid = id(node)
        if nid in seen:
            return seen[nid]
        if isinstance(node, InputNode):
            idx = len(inputs)
            inputs.append(node.value)
            my_id = len(sig_parts)
            sig_parts.append(("input", idx, _leaf_sig(node.value)))
        elif isinstance(node, ModelCallNode):
            if node.model not in models:
                models.append(node.model)
            m_idx = models.index(node.model)
            # split args/kwargs into structure + leaves; deferred leaves recurse
            flat, treedef = jax.tree.flatten(
                (node.call_args, node.call_kwargs),
                is_leaf=lambda x: isinstance(x, Deferred),
            )
            arg_ids = []
            for leaf in flat:
                if isinstance(leaf, Deferred):
                    arg_ids.append(("node", walk(leaf._node)))
                else:
                    idx = len(inputs)
                    inputs.append(leaf)
                    arg_ids.append(("leaf", idx, _leaf_sig(leaf)))
            my_id = len(sig_parts)
            sig_parts.append(
                (
                    "model_call", m_idx, str(treedef), tuple(arg_ids),
                    str(node.compute_dtype),
                    getattr(node.fp8_recipe, "fp8_format", None),
                )
            )
        else:
            child_ids = tuple(walk(as_node(a)) for a in node.args)
            my_id = len(sig_parts)
            sig_parts.append((node.op, child_ids, node.static))
        seen[nid] = my_id
        return my_id

    root_id = walk(root)
    return tuple(sig_parts) + (("root", root_id),), inputs, models


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------

_BINARY = {
    "add": operator.add, "sub": operator.sub, "mul": operator.mul,
    "truediv": operator.truediv, "pow": operator.pow, "mod": operator.mod,
    "matmul": operator.matmul,
    "radd": lambda a, b: b + a, "rsub": lambda a, b: b - a,
    "rmul": lambda a, b: b * a, "rtruediv": lambda a, b: b / a,
    "lt": operator.lt, "le": operator.le, "gt": operator.gt, "ge": operator.ge,
    "eq": lambda a, b: a == b, "ne": lambda a, b: a != b,
}

_REDUCTIONS = {"mean": jnp.mean, "sum": jnp.sum, "max": jnp.max, "min": jnp.min}


def replay(root: Node, input_values: list, params_env: dict[int, Any]):
    """Execute the graph. ``params_env`` maps id(model) → params pytree to
    use for each model call (this is how ``value_and_grad`` threads the
    differentiated params in)."""
    cache: dict[int, Any] = {}

    def ev(node: Node):
        nid = id(node)
        if nid in cache:
            return cache[nid]
        if isinstance(node, InputNode):
            out = input_values[node._input_idx]
        elif isinstance(node, ModelCallNode):
            flat, treedef = jax.tree.flatten(
                (node.call_args, node.call_kwargs),
                is_leaf=lambda x: isinstance(x, Deferred),
            )
            resolved = [
                ev(leaf._node) if isinstance(leaf, Deferred)
                else input_values[leaf_idx_map[id(node)][i]]
                for i, leaf in enumerate(flat)
            ]
            args, kwargs = jax.tree.unflatten(treedef, resolved)
            params = params_env.get(id(node.model))
            out = node.model._raw_apply(
                params, *args,
                _compute_dtype=node.compute_dtype,
                _fp8_recipe=node.fp8_recipe,
                **kwargs,
            )
        elif node.op in _BINARY:
            out = _BINARY[node.op](ev(as_node(node.args[0])), ev(as_node(node.args[1])))
        elif node.op in _REDUCTIONS:
            a = ev(as_node(node.args[0]))
            axis = node.static[0] if node.static else None
            out = _REDUCTIONS[node.op](a, axis=axis)
        elif node.op == "getattr":
            out = getattr(ev(as_node(node.args[0])), node.static[0])
        elif node.op == "getitem":
            key = node.static[0]
            out = ev(as_node(node.args[0]))[key]
        elif node.op == "getitem_node":
            out = ev(as_node(node.args[0]))[ev(as_node(node.args[1]))]
        elif node.op == "neg":
            out = -ev(as_node(node.args[0]))
        elif node.op == "abs":
            out = jnp.abs(ev(as_node(node.args[0])))
        elif node.op == "astype":
            out = ev(as_node(node.args[0])).astype(node.static[0])
        elif node.op == "reshape":
            out = ev(as_node(node.args[0])).reshape(node.static[0])
        elif node.op == "transpose":
            out = jnp.transpose(ev(as_node(node.args[0])), node.static[0] or None)
        elif node.op == "squeeze":
            out = jnp.squeeze(ev(as_node(node.args[0])), node.static[0])
        elif node.op == "argmax":
            out = jnp.argmax(ev(as_node(node.args[0])), axis=node.static[0])
        elif node.op == "call_fn":
            fn = node.static[0]
            kwargs = dict(node.static[1])
            vals = [ev(as_node(a)) for a in node.args]
            out = fn(*vals, **kwargs)
        else:
            raise NotImplementedError(f"deferred op {node.op!r}")
        cache[nid] = out
        return out

    # Pre-compute per-model-call leaf index maps (aligned with linearize order)
    leaf_idx_map: dict[int, dict[int, int]] = {}
    _assign_input_indices(root, leaf_idx_map)
    return ev(root)


def _assign_input_indices(root: Node, leaf_idx_map: dict):
    """Mirror linearize()'s walk to annotate nodes with their input slots."""
    counter = [0]
    seen: set[int] = set()

    def walk(node: Node):
        nid = id(node)
        if nid in seen:
            return
        seen.add(nid)
        if isinstance(node, InputNode):
            node._input_idx = counter[0]
            counter[0] += 1
        elif isinstance(node, ModelCallNode):
            flat, _ = jax.tree.flatten(
                (node.call_args, node.call_kwargs),
                is_leaf=lambda x: isinstance(x, Deferred),
            )
            idx_map = {}
            for i, leaf in enumerate(flat):
                if isinstance(leaf, Deferred):
                    walk(leaf._node)
                else:
                    idx_map[i] = counter[0]
                    counter[0] += 1
            leaf_idx_map[nid] = idx_map
        else:
            for a in node.args:
                if isinstance(a, (Node, Deferred)):
                    walk(as_node(a))

    walk(root)


# ---------------------------------------------------------------------------
# Deferred proxy
# ---------------------------------------------------------------------------


class Deferred:
    """Lazy array/namespace proxy. Cheap to build; forcing compiles+runs."""

    __slots__ = ("_node", "_forced", "_pre_force_hook", "_children")

    def __init__(self, node: Node):
        object.__setattr__(self, "_node", node)
        object.__setattr__(self, "_forced", None)
        object.__setattr__(self, "_pre_force_hook", None)
        object.__setattr__(self, "_children", None)

    def _child(self, key, build):
        """Memoize derived proxies so ``out.loss`` is the SAME object on
        every access — forced values and pending-step hooks must be shared."""
        children = self._children
        if children is None:
            children = {}
            object.__setattr__(self, "_children", children)
        if key not in children:
            children[key] = build()
        return children[key]

    # -- graph builders ------------------------------------------------------

    def _bin(self, op, other):
        return Deferred(Node(op, (self._node, as_node(other))))

    def __add__(self, o): return self._bin("add", o)
    def __radd__(self, o): return self._bin("radd", o)
    def __sub__(self, o): return self._bin("sub", o)
    def __rsub__(self, o): return self._bin("rsub", o)
    def __mul__(self, o): return self._bin("mul", o)
    def __rmul__(self, o): return self._bin("rmul", o)
    def __truediv__(self, o): return self._bin("truediv", o)
    def __rtruediv__(self, o): return self._bin("rtruediv", o)
    def __pow__(self, o): return self._bin("pow", o)
    def __matmul__(self, o): return self._bin("matmul", o)
    def __neg__(self): return Deferred(Node("neg", (self._node,)))
    def __abs__(self): return Deferred(Node("abs", (self._node,)))
    def __lt__(self, o): return self._bin("lt", o)
    def __le__(self, o): return self._bin("le", o)
    def __gt__(self, o): return self._bin("gt", o)
    def __ge__(self, o): return self._bin("ge", o)
    def __eq__(self, o): return self._bin("eq", o)
    def __ne__(self, o): return self._bin("ne", o)
    __hash__ = object.__hash__  # identity hash despite custom __eq__

    def mean(self, axis=None): return Deferred(Node("mean", (self._node,), (axis,)))
    def sum(self, axis=None): return Deferred(Node("sum", (self._node,), (axis,)))
    def max(self, axis=None): return Deferred(Node("max", (self._node,), (axis,)))
    def min(self, axis=None): return Deferred(Node("min", (self._node,), (axis,)))
    def argmax(self, axis=-1): return Deferred(Node("argmax", (self._node,), (axis,)))
    def astype(self, dtype): return Deferred(Node("astype", (self._node,), (jnp.dtype(dtype).name,)))
    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return Deferred(Node("reshape", (self._node,), (shape,)))

    def transpose(self, *axes):
        return Deferred(Node("transpose", (self._node,), (axes or None,)))

    def squeeze(self, axis=None): return Deferred(Node("squeeze", (self._node,), (axis,)))

    def __getitem__(self, key):
        if isinstance(key, Deferred):
            return Deferred(Node("getitem_node", (self._node, key._node)))
        try:
            hash(key)
        except TypeError:
            key = tuple(key)
        return self._child(
            ("getitem", key), lambda: Deferred(Node("getitem", (self._node,), (key,)))
        )

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return self._child(
            ("getattr", name), lambda: Deferred(Node("getattr", (self._node,), (name,)))
        )

    # -- forcing -------------------------------------------------------------

    def _set_forced(self, value):
        object.__setattr__(self, "_forced", value)

    def force(self):
        if self._forced is not None:
            return self._forced
        if self._pre_force_hook is not None:
            hook = self._pre_force_hook
            object.__setattr__(self, "_pre_force_hook", None)
            hook()  # e.g. flush a pending fused backward, which sets _forced
            if self._forced is not None:
                return self._forced
        value = force_value(self)
        self._set_forced(value)
        return value

    def item(self) -> float:
        v = self.force()
        return np.asarray(v).item() if hasattr(v, "shape") else v

    def __float__(self): return float(self.item())
    def __int__(self): return int(self.item())

    def __bool__(self):
        # force so `if a == b:` is truthful; numpy raises on non-scalars,
        # matching torch's "Boolean value of Tensor is ambiguous"
        return bool(np.asarray(self.force()))
    def __array__(self, dtype=None):
        return np.asarray(self.force(), dtype=dtype)

    def __repr__(self):
        if self._forced is not None:
            return f"Deferred(forced={self._forced!r})"
        return f"Deferred(op={self._node.op!r})"

    def float(self):  # torch-style alias
        return self.astype(jnp.float32)

    @property
    def shape(self):
        return self.force().shape


def defer_call(fn: Callable, *args, **kwargs) -> Deferred:
    """Defer an arbitrary jnp-traceable function over deferred/concrete args.
    ``fn`` must be a stable (module-level) callable — its identity is part of
    the compile-cache key. Keyword args must be hashable statics."""
    node = Node("call_fn", tuple(as_node(a) for a in args), (fn, tuple(sorted(kwargs.items()))))
    return Deferred(node)


def is_deferred(x) -> bool:
    return isinstance(x, Deferred)


# ---------------------------------------------------------------------------
# compile cache
# ---------------------------------------------------------------------------

_FORCE_CACHE: dict = {}
_GRAD_CACHE: dict = {}

#: compiled-step cost analyses collected while a profile session with
#: ``with_flops`` is live (reference wires the flag into torch.profiler,
#: ``dataclasses.py:487-513``; here the XLA compiler's own cost model is
#: the source of truth)
PROFILE_COST_STATS: list = []
_COLLECT_COSTS = False
#: (label, signature) → (AOT-compiled executable, cost facts), so each
#: signature compiles ONCE (the executable both serves the calls and
#: answers cost_analysis); a (None, None) entry marks a backend where AOT
#: lowering is unavailable, so the plain jit path serves without re-probing
_AOT_CACHE: dict = {}
#: signatures already appended to PROFILE_COST_STATS this collection session
_COST_SEEN: set = set()

#: telemetry compile-miss hook: called with a cost-facts dict every time a
#: signature compiles while instrumentation is active (see telemetry.py)
_COMPILE_CALLBACK = None


def set_cost_collection(enabled: bool) -> None:
    global _COLLECT_COSTS
    _COLLECT_COSTS = bool(enabled)
    if enabled:
        PROFILE_COST_STATS.clear()
        _COST_SEEN.clear()


def set_compile_callback(callback) -> None:
    """Register the compile-event observer (one per process; the telemetry
    recorder owns it). None unregisters."""
    global _COMPILE_CALLBACK
    _COMPILE_CALLBACK = callback


def get_compile_callback():
    return _COMPILE_CALLBACK


def _compile_facts(jitted, args, label: str) -> tuple:
    """AOT-compile one signature, timing trace+lower and compile separately
    and extracting the program's static cost facts: XLA-cost-model FLOPs /
    bytes accessed, and collective bytes parsed from the compiled HLO.

    The phases are wrapped in diagnostics spans (``compile/trace_lower``,
    ``compile/compile``) and the facts carry the phases' raw *monotonic*
    timestamps (``mono``) so telemetry's compile records line up with the
    trace timeline, not just the wall clock."""
    t0 = time.perf_counter()
    with _trace_span("compile/trace_lower", label=label):
        lowered = jitted.lower(*args)
    t1 = time.perf_counter()
    with _trace_span("compile/compile", label=label):
        compiled = lowered.compile()
    t2 = time.perf_counter()
    try:
        stats = compiled.cost_analysis() or {}
    except Exception:
        stats = {}
    if isinstance(stats, (list, tuple)):  # older jax: one dict per device
        stats = stats[0] if stats else {}
    facts = {
        "label": label,
        "lower_s": t1 - t0,
        "compile_s": t2 - t1,
        "mono": {"lower_start": t0, "compile_start": t1, "compile_end": t2},
        "flops": stats.get("flops"),
        "bytes_accessed": stats.get("bytes accessed"),
        "collective_bytes": None,
    }
    try:
        from .utils.hlo import total_collective_bytes

        facts["collective_bytes"] = total_collective_bytes(compiled.as_text())
    except Exception:
        pass
    return compiled, facts


def _cost_aware_jit(fn, donate_argnums=(), label="", arg_names=()):
    """``jax.jit`` that, while instrumentation is active (a profile session
    with ``with_flops``, or a telemetry recorder's compile callback),
    AOT-compiles each new signature explicitly — timing trace+lower+compile
    and recording the program's cost analysis once. The executable is kept
    and serves the calls, so instrumentation never compiles a program
    twice. Zero overhead when both are off (one global read per call)."""
    jitted = jax.jit(fn, donate_argnums=donate_argnums)

    def call(*args):
        callback = _COMPILE_CALLBACK
        sanitizer = _get_sanitizer()
        # an active tracer also wants the explicit AOT path: it is what
        # separates trace/lower/compile into spans a flame graph shows.
        # An active sanitizer does too: the donation / fingerprint /
        # collective-digest checks need the compiled artifact in hand.
        if (
            not (_COLLECT_COSTS or callback is not None or sanitizer)
            and not _get_tracer()
        ):
            return jitted(*args)
        # every leaf participates: truncating the signature would hand
        # a cached executable mismatched avals if two calls differ only
        # in later-leaf shapes (shape/dtype tuples are cheap to hash).
        # Shardings are part of the key for the same reason jit keys on
        # them: step 1 compiles against the as-prepared placement, the
        # donated outputs come back with GSPMD's chosen shardings, and an
        # executable replayed against re-sharded args raises instead of
        # recompiling. ``fn`` itself (not id(fn)) keys the entry: the
        # reference pins the closure alive, so a recycled id can never
        # alias two programs.
        sig = (label, fn) + tuple(
            (
                tuple(getattr(l, "shape", ())),
                str(getattr(l, "dtype", "")),
                getattr(l, "sharding", None),
            )
            for l in jax.tree.leaves(args)
        )
        entry = _AOT_CACHE.get(sig)
        if entry is None:
            try:
                entry = _compile_facts(jitted, args, label)
            except Exception:  # AOT path unavailable on this backend
                entry = (None, None)
            _AOT_CACHE[sig] = entry
            if entry[1] is not None:
                # recompile fingerprint: hash the abstract signature with
                # leaf PATHS attached, so a later compile of the same label
                # can NAME the argument whose shape/dtype changed. Shared
                # global history — the telemetry record, the sanitizer's
                # stderr report, and the serving engine's assertion all
                # diff against the same baseline.
                from .analysis.compiled import (
                    format_signature_diff,
                    note_signature,
                    signature_entries,
                )

                try:
                    # leaf paths read as ['inputs'][0] instead of [3][0]
                    # when the call site named its positional args
                    if arg_names and len(args) <= len(arg_names):
                        named = dict(zip(arg_names, args))
                    else:
                        named = args
                    entries = signature_entries(named)
                    fingerprint, diff = note_signature(label, entries)
                    entry[1]["fingerprint"] = fingerprint
                    if diff is not None:
                        entry[1]["changed_args"] = format_signature_diff(diff)
                except Exception:
                    entries, diff = (), None
                if sanitizer:
                    # predicted-vs-actual per-device arg bytes: the static
                    # shard-plan model (global bytes / sharding extents)
                    # against the real shard buffers — a drift means the
                    # placement the planner promised is not the placement
                    # the program got
                    try:
                        from .analysis.shardplan import arg_bytes_report

                        predicted, actual = arg_bytes_report(args)
                        entry[1]["arg_bytes_predicted"] = predicted
                        entry[1]["arg_bytes_actual"] = actual
                    except Exception:
                        pass
                    # the digest also rides the compile record so the
                    # telemetry trail carries cross-host-comparable state;
                    # observe_compile already computed it for the host
                    # digest file — reuse it rather than rendering the
                    # (multi-MB) HLO text a second time
                    digest = sanitizer.observe_compile(
                        label,
                        entries,
                        diff,
                        fn=fn,
                        args=args,
                        donate_argnums=donate_argnums,
                        compiled=entry[0],
                    )
                    if digest is not None:
                        entry[1]["collective_digest"] = digest
            if entry[1] is not None and callback is not None:
                # the human-readable shape key: label + the leaf signature
                # (the part of the cache key a batch-shape change perturbs).
                # A big step's args include every param/opt-state leaf, so
                # cap the readable part and pin identity with a digest —
                # distinct shapes must stay distinct without writing a
                # multi-KB key into every compile record.
                key = f"{label}:{sig[2:]}"
                if len(key) > 512:
                    import hashlib

                    digest = hashlib.sha1(key.encode()).hexdigest()[:16]
                    key = f"{key[:480]}...#{digest}"
                callback(dict(entry[1], static_key=key))
        compiled, facts = entry
        if compiled is None:
            return jitted(*args)
        if _COLLECT_COSTS and sig not in _COST_SEEN:
            _COST_SEEN.add(sig)
            PROFILE_COST_STATS.append(
                {
                    "label": facts["label"],
                    "flops": facts["flops"],
                    "bytes_accessed": facts["bytes_accessed"],
                }
            )
        return compiled(*args)

    return call


def clear_caches():
    _FORCE_CACHE.clear()
    _GRAD_CACHE.clear()
    _FUSED_CACHE.clear()
    _AOT_CACHE.clear()
    _COST_SEEN.clear()
    from .analysis.compiled import GLOBAL_FINGERPRINTS

    GLOBAL_FINGERPRINTS.clear()


def force_value(deferred: Deferred):
    """Execute the graph (forward only), jitted + cached per signature."""
    root = deferred._node
    sig, inputs, models = linearize(root)
    key = (sig, tuple(id(m) for m in models))
    entry = _FORCE_CACHE.get(key)
    if entry is None:
        def fn(model_params: list, input_values: list):
            env = {id(m): p for m, p in zip(models, model_params)}
            return replay(root, input_values, env)

        entry = (
            _cost_aware_jit(fn, label="forward", arg_names=("model_params", "inputs")),
            models,
        )
        _FORCE_CACHE[key] = entry
    jitted, cached_models = entry
    params = [m.params for m in cached_models]
    return jitted(params, inputs)


def grad_fn_for(
    loss: Deferred,
    trainable_models: list,
    loss_scale: float = 1.0,
    dynamic_scale: bool = False,
    comm_hook: tuple | None = None,  # (hook_str, mesh) → ddp_compressed_vag
):
    """Compiled ``(loss, grads_per_model) = f(params_list, inputs[, scale])``
    for the loss graph; cached per signature. ``loss_scale`` divides the loss
    (the reference divides by gradient_accumulation_steps inside ``backward``,
    ``accelerator.py:2240``). With ``dynamic_scale`` the jitted fn takes one
    extra device-scalar argument that MULTIPLIES the loss — the fp16
    LossScaler's current scale, traced so backoff/growth never recompiles."""
    root = loss._node
    sig, inputs, models = linearize(root)
    trainables = [m for m in models if m in trainable_models]
    frozen = [m for m in models if m not in trainable_models]
    key = (sig, tuple(id(m) for m in models), tuple(id(m) for m in trainables), loss_scale,
           dynamic_scale, comm_hook[0] if comm_hook else None)
    entry = _GRAD_CACHE.get(key)
    if entry is None:
        def loss_fn(train_params: list, frozen_params: list, input_values: list, *scale):
            env = {id(m): p for m, p in zip(trainables, train_params)}
            env.update({id(m): p for m, p in zip(frozen, frozen_params)})
            out = replay(root, input_values, env)
            out = jnp.asarray(out)
            if out.ndim != 0:
                raise ValueError(
                    f"backward() needs a scalar loss; got shape {out.shape}. "
                    "Reduce it (e.g. .mean()) first."
                )
            unscaled = out.astype(jnp.float32)
            scaled = unscaled / loss_scale
            if dynamic_scale:
                scaled = scaled * scale[0]
            return scaled, unscaled

        if comm_hook is not None:
            vag = ddp_compressed_vag(loss_fn, comm_hook[1], inputs, comm_hook[0])
        else:
            vag = jax.value_and_grad(loss_fn, argnums=0, has_aux=True)
        entry = (
            _cost_aware_jit(
                vag,
                label="grad",
                arg_names=("params", "frozen_params", "inputs", "loss_scale"),
            ),
            trainables,
            frozen,
        )
        _GRAD_CACHE[key] = entry
    jitted, trainables, frozen = entry
    return jitted, trainables, frozen, inputs


def ddp_compressed_vag(loss_fn, mesh, input_values, hook: str):
    """``value_and_grad`` with an EXPLICIT data-parallel gradient reduction
    whose wire dtype is compressed — the TPU-native analog of the
    reference's DDP communication hooks (``fp16_compress_hook`` /
    ``bf16_compress_hook``, reference ``utils/dataclasses.py:117-214``).

    Under plain GSPMD the cross-replica grad all-reduce is implicit (XLA
    inserts it in the grads' dtype), so there is no seam to compress. This
    helper creates that seam: the loss/grad computation runs under
    ``shard_map`` over the batch axes, each shard computes LOCAL grads, and
    the cross-shard reduction is an explicit ``psum`` in bf16/fp16 — on a
    multi-slice DCN mesh that halves bytes-on-wire for the gradient sync,
    which is the whole point of the reference's hook. Semantics match DDP:
    gradients are AVERAGED across shards; the returned loss is the
    cross-shard mean of local losses.

    Scope (same as the reference's DDP hooks, which are DP-only): a mesh
    whose non-batch axes (tp/pp/cp/ep/fsdp) all have extent 1 — params
    replicated, batch sharded.
    """
    from jax.sharding import PartitionSpec as P

    wire = {"bf16": jnp.bfloat16, "fp16": jnp.float16}[hook]
    shape = dict(mesh.shape)
    batch_axes = tuple(a for a in ("dp", "fsdp") if shape.get(a, 1) > 1)
    n_shards = 1
    for a in batch_axes:
        n_shards *= shape[a]

    def _spec_for(x):
        spec = getattr(getattr(x, "sharding", None), "spec", None)
        if not spec:
            return P()
        names: set = set()
        for entry in spec:
            if entry is None:
                continue
            names.update(entry if isinstance(entry, (tuple, list)) else (entry,))
        return P(*spec) if names & set(batch_axes) else P()

    input_specs = [_spec_for(x) for x in input_values]

    def vag(params, frozen_params, inputs, *rest):
        from .utils.compat import shard_map

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(), input_specs) + (P(),) * len(rest),
            out_specs=((P(), P()), P()),
            check_vma=False,
        )
        def inner(params, frozen_params, inputs, *rest):
            (scaled, unscaled), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, frozen_params, inputs, *rest
            )
            grads = jax.tree.map(
                lambda g: jax.lax.psum(g.astype(wire), batch_axes).astype(g.dtype)
                / n_shards,
                grads,
            )
            return (
                jax.lax.pmean(scaled, batch_axes),
                jax.lax.pmean(unscaled, batch_axes),
            ), grads

        return inner(params, frozen_params, inputs, *rest)

    return vag


_FUSED_CACHE: dict = {}


def fused_step_fn_for(
    loss: Deferred,
    model,
    tx,
    *,
    clip_norm: bool = False,
    grad_scaler=None,  # optimizer.LossScaler | None
    comm_hook: tuple | None = None,  # (hook_str, mesh) → ddp_compressed_vag
):
    loss_scale = 1.0  # fusion only engages without accumulation in flight
    """One donated, jitted train step for the common single-model loop:
    forward + backward + (unscale) + (clip) + optimizer update. This is the
    fast path `backward()`/`step()` take when nothing forces a split
    (no accumulation in flight, single bound optimizer) — it makes the
    compat loop cost what a hand-fused pjit step costs.

    Returns (jitted, frozen_models, inputs). jitted signature:
      (params, opt_state, frozen_params, inputs, max_norm, scaler_state)
        -> (new_params, new_opt_state, loss, grad_norm, step_ok,
            new_scaler_state)
    ``step_ok`` is False when fp16 grads were non-finite (update skipped).
    With fp16, ``scaler_state`` is the LossScaler's (scale, good_steps)
    device pair: the scale is a traced INPUT (growth/backoff never
    recompiles; only the grow/backoff constants are baked into the trace)
    and the updated pair comes back as the last output. Without a scaler,
    pass ``()`` and ``()`` is returned.
    """
    import optax

    root = loss._node
    sig, inputs, models = linearize(root)
    if model not in models:
        raise ValueError("the pending loss does not involve the optimizer's model")
    frozen = [m for m in models if m is not model]
    key = (sig, id(model), id(tx), tuple(id(m) for m in frozen), loss_scale, clip_norm,
           None if grad_scaler is None else grad_scaler.trace_key,
           comm_hook[0] if comm_hook else None)
    entry = _FUSED_CACHE.get(key)
    if entry is None:
        def loss_fn(params, frozen_params, input_values, scale):
            env = {id(model): params}
            env.update({id(m): p for m, p in zip(frozen, frozen_params)})
            out = jnp.asarray(replay(root, input_values, env))
            if out.ndim != 0:
                raise ValueError(
                    f"backward() needs a scalar loss; got shape {out.shape}."
                )
            unscaled = out.astype(jnp.float32)
            scaled = unscaled / loss_scale
            if grad_scaler is not None:
                scaled = scaled * scale  # fp16: scale up against underflow
            return scaled, unscaled

        if comm_hook is not None:
            _vag = ddp_compressed_vag(loss_fn, comm_hook[1], inputs, comm_hook[0])
        else:
            _vag = jax.value_and_grad(loss_fn, has_aux=True)

        def step(params, opt_state, frozen_params, input_values, max_norm, scaler_state):
            scale = scaler_state[0] if grad_scaler is not None else jnp.float32(1.0)
            (_, loss_value), grads = _vag(
                params, frozen_params, input_values, scale
            )
            step_ok = jnp.bool_(True)
            new_scaler_state = scaler_state
            if grad_scaler is not None:
                inv = 1.0 / scale
                grads = jax.tree.map(lambda g: g * inv, grads)
                finite = [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]
                step_ok = jnp.all(jnp.stack(finite))
                new_scaler_state = grad_scaler.next_state(
                    scale, scaler_state[1], step_ok
                )
            if clip_norm:
                norm = optax.global_norm(grads)
                factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
                grads = jax.tree.map(lambda g: g * factor, grads)
            else:
                # no clip requested: don't pay a full reduction pass over the
                # grads just to report a norm nobody asked for
                norm = jnp.asarray(0.0, jnp.float32)
            updates, new_opt_state = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            # fp16 non-finite: keep old state (structure-preserving select)
            if grad_scaler is not None:
                keep = lambda new, old: jax.tree.map(
                    lambda a, b: jnp.where(step_ok, a, b), new, old
                )
                new_params = keep(new_params, params)
                new_opt_state = keep(new_opt_state, opt_state)
            return new_params, new_opt_state, loss_value, norm, step_ok, new_scaler_state

        entry = (
            _cost_aware_jit(
                step,
                donate_argnums=(0, 1),
                label="fused_step",
                arg_names=(
                    "params", "opt_state", "frozen_params", "inputs",
                    "max_norm", "scaler_state",
                ),
            ),
            frozen,
        )
        _FUSED_CACHE[key] = entry
    jitted, frozen = entry
    return jitted, frozen, inputs
