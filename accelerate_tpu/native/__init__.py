"""Native helpers for the host-side data path (SURVEY: "native code is
allowed and expected" for the runtime around the XLA compute path). Each
helper compiles lazily from the vendored C source with the system
compiler and degrades gracefully — callers MUST handle a None export."""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_Q4_LIB = None
_Q4_TRIED = False


def _isa_tag() -> str:
    """Cache key component for the host ISA: -march=native binaries built on
    a newer machine must not be reused on an older one sharing the cache
    dir (NFS home) — that SIGILLs at call time, past the build guard. The
    CPU feature flags identify what ``native`` resolves to; read from
    /proc/cpuinfo (no subprocess — this runs in every worker that touches
    the decoder), falling back to the bare machine arch."""
    import hashlib
    import platform

    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    digest = hashlib.sha256(feats.encode()).hexdigest()[:12]
                    return f"{platform.machine()}-{digest}"
    except Exception:
        pass
    return platform.machine() or "unknown"


def _build_q4decode():
    src = os.path.join(os.path.dirname(os.path.abspath(__file__)), "q4decode.c")
    cache_dir = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "accelerate_tpu",
    )
    os.makedirs(cache_dir, exist_ok=True)
    lib_path = os.path.join(cache_dir, f"libq4decode-{_isa_tag()}.so")
    if not (
        os.path.exists(lib_path)
        and os.path.getmtime(lib_path) >= os.path.getmtime(src)
    ):
        with tempfile.NamedTemporaryFile(
            suffix=".so", dir=cache_dir, delete=False
        ) as tmp:
            tmp_path = tmp.name
        cmd = [
            os.environ.get("CC", "cc"), "-O3", "-march=native", "-shared",
            "-fPIC", src, "-o", tmp_path,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp_path, lib_path)  # atomic vs concurrent builders
    lib = ctypes.CDLL(lib_path)
    lib.q4_decode_codes.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int8),
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_int8),
    ]
    lib.q4_decode_codes.restype = None
    return lib


def aligned_empty(shape, dtype, align: int = 64) -> np.ndarray:
    """Uninitialised array whose data pointer is ``align``-byte aligned.
    XLA:CPU's ``device_put`` is ZERO-COPY for 64-byte-aligned host buffers
    and a full memcpy otherwise — for the streaming decoder's output (2×
    the packed bytes) that memcpy was the single largest avoidable cost on
    the nf4 offload path."""
    n = int(np.prod(shape)) * np.dtype(dtype).itemsize
    raw = np.empty(n + align, dtype=np.uint8)
    offset = (-raw.ctypes.data) % align
    return raw[offset:offset + n].view(dtype).reshape(shape)


def q4_decode_codes(packed: np.ndarray, lut16: np.ndarray):
    """packed uint8 [..., n] → int8 code values [..., 2n] via the native
    pshufb LUT; returns None when the native library is unavailable (no
    compiler / non-x86 without the scalar build succeeding). The output is
    64-byte aligned so the following ``device_put`` aliases instead of
    copying (see :func:`aligned_empty`)."""
    global _Q4_LIB, _Q4_TRIED
    if _Q4_LIB is None:
        if _Q4_TRIED:
            return None
        _Q4_TRIED = True
        try:
            _Q4_LIB = _build_q4decode()
        except Exception:
            return None
    packed = np.ascontiguousarray(packed, dtype=np.uint8)
    lut = np.ascontiguousarray(lut16, dtype=np.int8)
    out = aligned_empty(packed.shape[:-1] + (packed.shape[-1] * 2,), np.int8)
    _Q4_LIB.q4_decode_codes(
        packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        packed.size,
        lut.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
    )
    return out
