/* Native 4-bit → int8 codebook decoder for the offload streaming path.
 *
 * The role the reference delegates to bitsandbytes' CUDA dequant kernels
 * (reference utils/bnb.py loads Linear4bit weights whose dequant runs in
 * native code) is played here by an AVX2 pshufb decode: _mm256_shuffle_epi8
 * IS a 16-entry LUT applied to 32 nibbles per instruction, so decoding a
 * packed [K, N/2] plane to int8 codes runs at memory speed instead of the
 * ~1.3 GB/s XLA:CPU's scalar gather manages. Scalar fallback keeps every
 * other arch correct.
 */
#include <stddef.h>
#include <stdint.h>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

void q4_decode_codes(const uint8_t *packed, int8_t *out, size_t n,
                     const int8_t *lut) {
  size_t i = 0;
#if defined(__AVX2__)
  const __m256i lutv =
      _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i *)lut));
  const __m256i maskf = _mm256_set1_epi8(0x0F);
  for (; i + 32 <= n; i += 32) {
    __m256i b = _mm256_loadu_si256((const __m256i *)(packed + i));
    __m256i lo = _mm256_and_si256(b, maskf);
    __m256i hi = _mm256_and_si256(_mm256_srli_epi16(b, 4), maskf);
    __m256i vlo = _mm256_shuffle_epi8(lutv, lo);
    __m256i vhi = _mm256_shuffle_epi8(lutv, hi);
    /* interleave (hi, lo) pairs in byte order; unpack* works per 128-bit
     * lane, the permutes restore sequential order across lanes */
    __m256i first = _mm256_unpacklo_epi8(vhi, vlo);
    __m256i second = _mm256_unpackhi_epi8(vhi, vlo);
    __m256i out0 = _mm256_permute2x128_si256(first, second, 0x20);
    __m256i out1 = _mm256_permute2x128_si256(first, second, 0x31);
    _mm256_storeu_si256((__m256i *)(out + 2 * i), out0);
    _mm256_storeu_si256((__m256i *)(out + 2 * i + 32), out1);
  }
#endif
  for (; i < n; i++) {
    uint8_t b = packed[i];
    out[2 * i] = lut[b >> 4];
    out[2 * i + 1] = lut[b & 0x0F];
  }
}
