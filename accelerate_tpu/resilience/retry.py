"""Bounded-retry wrapper for checkpoint IO.

GCS-fuse and NFS mounts fail transiently (stale handles, 5xx-backed
EIO, ESTALE after a server failover); a multi-day run must not die because
one ``write()`` hiccuped. Every file operation in the checkpoint path goes
through :func:`run_with_retries`: exponential backoff, bounded attempts,
and a surfaced exception only once the budget is spent.

Defaults come from ``ACCELERATE_FT_IO_ATTEMPTS`` / of
``ACCELERATE_FT_IO_BACKOFF`` (seconds), overridable per call — the
``FaultTolerancePlugin`` exports its knobs through those env vars so the
whole process agrees.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, TypeVar

from ..logging import get_logger

logger = get_logger(__name__)

T = TypeVar("T")

#: Exception classes considered transient. ValueError/TypeError etc. are
#: programming errors and retrying them only delays the traceback.
TRANSIENT_ERRORS: tuple[type[BaseException], ...] = (OSError, IOError)


def default_attempts() -> int:
    try:
        return max(1, int(os.environ.get("ACCELERATE_FT_IO_ATTEMPTS", 3)))
    except ValueError:
        return 3


def default_backoff() -> float:
    try:
        return max(0.0, float(os.environ.get("ACCELERATE_FT_IO_BACKOFF", 0.5)))
    except ValueError:
        return 0.5


def run_with_retries(
    fn: Callable[[], T],
    what: str = "checkpoint IO",
    attempts: int | None = None,
    backoff: float | None = None,
    transient: tuple[type[BaseException], ...] = TRANSIENT_ERRORS,
    sleep: Callable[[float], Any] = time.sleep,
) -> T:
    """Call ``fn`` up to ``attempts`` times, sleeping ``backoff * 2**i``
    between tries; re-raises the last error once the budget is spent.
    Only ``transient`` exception types are retried."""
    attempts = default_attempts() if attempts is None else max(1, int(attempts))
    backoff = default_backoff() if backoff is None else float(backoff)
    last: BaseException | None = None
    for i in range(attempts):
        try:
            return fn()
        except transient as e:  # noqa: PERF203 — retry loop by design
            last = e
            if i + 1 >= attempts:
                break
            delay = backoff * (2**i)
            logger.warning(
                "%s failed (%s: %s) — retry %d/%d in %.2fs",
                what, type(e).__name__, e, i + 1, attempts - 1, delay,
            )
            if delay > 0:
                sleep(delay)
    assert last is not None
    raise last
