"""Preemption detection: signal handlers + GCE maintenance-event poller.

TPU pods are preempted for boring reasons — maintenance events, spot VM
reclamation — and the warning arrives as SIGTERM (or, ~60s earlier, on the
GCE metadata server). The handler only *sets a flag*; the training loop
observes it at step boundaries (``Accelerator.backward`` →
``check_preemption``), reaches cross-host consensus with a tiny all-gather,
and triggers ONE synchronized emergency ``save_state()`` followed by a
clean exit with a sentinel file. Saving from inside a signal handler would
race the step in flight; saving at the boundary is always consistent.

A second SIGINT while the flag is already set restores the previous
handler's behaviour (usually KeyboardInterrupt) so a user mashing Ctrl-C
still gets out.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Any

from ..logging import get_logger
from .manifest import SENTINEL_NAME

logger = get_logger(__name__)

#: GCE metadata endpoint announcing host maintenance (returns ``NONE`` or
#: ``TERMINATE_ON_HOST_MAINTENANCE``); absent outside GCE.
GCE_MAINTENANCE_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/maintenance-event"
)

#: the handler currently owning the process signals (one per process; a new
#: install replaces the previous one, Borg-style like AcceleratorState)
_ACTIVE_HANDLER: "PreemptionHandler | None" = None


def get_active_handler() -> "PreemptionHandler | None":
    return _ACTIVE_HANDLER


class PreemptionHandler:
    """Installs SIGTERM/SIGINT handlers (and optionally a maintenance-event
    poller thread) that raise a preemption flag for the training loop.

    Args:
        handle_sigint: also treat Ctrl-C as a preemption request (second
            SIGINT falls through to the previous handler).
        monitor_maintenance: poll the GCE metadata server for host
            maintenance events on a daemon thread.
        poll_seconds: maintenance poll interval.
    """

    def __init__(
        self,
        handle_sigint: bool = True,
        monitor_maintenance: bool = False,
        poll_seconds: float = 30.0,
        handle_signals: bool = True,
    ):
        self.handle_signals = bool(handle_signals)
        self.handle_sigint = bool(handle_sigint)
        self.monitor_maintenance = bool(monitor_maintenance)
        self.poll_seconds = float(poll_seconds)
        self._flag = threading.Event()
        self._reason: str | None = None
        self._previous: dict[int, Any] = {}
        self._poller: threading.Thread | None = None
        self._stop_poller = threading.Event()
        self._installed = False

    # -- flag ---------------------------------------------------------------

    @property
    def preemption_requested(self) -> bool:
        return self._flag.is_set()

    @property
    def reason(self) -> str | None:
        return self._reason

    def request_preemption(self, reason: str = "manual"):
        """Raise the flag programmatically (tests; in-band watchdogs)."""
        self._reason = reason
        self._flag.set()
        self._trace_flag()

    def reset(self):
        self._flag.clear()
        self._reason = None

    # -- install/uninstall ---------------------------------------------------

    def install(self) -> bool:
        """Register the signal handlers. Signals can only be bound from the
        main thread — elsewhere this degrades to flag-only operation (the
        poller still works) and returns False."""
        global _ACTIVE_HANDLER
        if _ACTIVE_HANDLER is not None and _ACTIVE_HANDLER is not self:
            _ACTIVE_HANDLER.uninstall()
        _ACTIVE_HANDLER = self
        ok = True
        if not self._installed and self.handle_signals:
            signals = [signal.SIGTERM]
            if self.handle_sigint:
                signals.append(signal.SIGINT)
            try:
                for sig in signals:
                    self._previous[sig] = signal.signal(sig, self._on_signal)
                self._installed = True
            except ValueError:  # not the main thread
                logger.warning(
                    "PreemptionHandler.install() outside the main thread: "
                    "signal handlers not registered (flag-only mode)"
                )
                ok = False
        if self.monitor_maintenance and self._poller is None:
            self._stop_poller.clear()
            self._poller = threading.Thread(
                target=self._poll_maintenance, name="preemption-poller", daemon=True
            )
            self._poller.start()
        return ok

    def uninstall(self):
        global _ACTIVE_HANDLER
        if self._installed:
            for sig, previous in self._previous.items():
                try:
                    signal.signal(sig, previous)
                except (ValueError, TypeError):
                    pass
            self._previous.clear()
            self._installed = False
        if self._poller is not None:
            self._stop_poller.set()
            self._poller = None
        if _ACTIVE_HANDLER is self:
            _ACTIVE_HANDLER = None

    # -- signal path ---------------------------------------------------------

    def _on_signal(self, signum, frame):
        if self._flag.is_set() and signum == signal.SIGINT:
            # second Ctrl-C: the user wants OUT, now — fall through
            previous = self._previous.get(signum)
            if callable(previous):
                previous(signum, frame)
                return
            raise KeyboardInterrupt
        self._reason = signal.Signals(signum).name
        self._flag.set()
        self._trace_flag()
        logger.warning(
            "%s received — emergency checkpoint at the next step boundary",
            self._reason,
        )

    def _trace_flag(self):
        """Mark the flag-raise on the diagnostics timeline: the gap between
        this instant and the `checkpoint/save` span is the preemption
        reaction latency, the number a save-cadence tuning session needs."""
        try:
            from ..diagnostics.tracing import trace_instant

            trace_instant("preemption/flag_raised", reason=self._reason)
        except Exception:
            pass

    def _poll_maintenance(self):
        import urllib.request

        while not self._stop_poller.wait(self.poll_seconds):
            try:
                req = urllib.request.Request(
                    GCE_MAINTENANCE_URL, headers={"Metadata-Flavor": "Google"}
                )
                with urllib.request.urlopen(req, timeout=5) as resp:
                    event = resp.read().decode().strip()
            except Exception:
                continue  # not on GCE / transient metadata failure
            if event and event != "NONE":
                self._reason = f"maintenance-event:{event}"
                self._flag.set()
                return

    # -- cross-host agreement ------------------------------------------------

    def consensus(self) -> bool:
        """Do ANY hosts want to preempt? A tiny all-gather of the local
        flag — collective, so every process must call it at the same step
        boundary (the Accelerator's consensus cadence guarantees that).
        Single-process: just the local flag."""
        from ..state import PartialState

        state = PartialState()
        return state.consensus_any(self._flag.is_set())

    # -- sentinel ------------------------------------------------------------

    def write_sentinel(self, directory: str, checkpoint: str | None, step: int | None):
        """Drop ``PREEMPTED.json`` next to the checkpoints: the restarted
        run (and the operator) can see why the process exited and where the
        emergency save landed."""
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, SENTINEL_NAME)
        payload = {
            "reason": self._reason or "preemption",
            "checkpoint": checkpoint,
            "step": step,
            "pid": os.getpid(),
            "timestamp": time.time(),
        }
        try:
            with open(path, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            logger.warning("could not write preemption sentinel %s", path, exc_info=True)
        return path
