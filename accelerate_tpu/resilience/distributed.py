"""Per-host sharded array IO for distributed checkpoints.

The legacy save path gathers every array to the main host
(``process_allgather``) and writes one file — an OOM and wall-clock
liability at FSDP scale. Here each host writes only the shards it can
address (``jax.Array.addressable_shards``) into its own
``shard_<process_index>/`` directory; replicated shards are deduplicated by
``replica_id == 0`` so every byte of a global array is written exactly once
across the fleet.

Load has two paths:

* **same-sharding fast path** — when the live array's addressable shard
  indices all appear in the piece table, each device shard is restored from
  exactly its own piece (``jax.make_array_from_single_device_arrays``), no
  host-side assembly of the full array.
* **gather-from-manifest fallback** — for a checkpoint written on a
  different mesh/sharding, the full array is assembled on host from the
  manifest's offsets and re-placed per the live sharding (the GSPMD analog
  of the reference's cross-world-size FSDP restore).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np

from ..logging import get_logger

logger = get_logger(__name__)


def shard_dirname(process_index: int) -> str:
    return f"shard_{process_index:05d}"


def _tree_items(tree) -> list[tuple[str, Any]]:
    """(dotted key, leaf) pairs in the same order/keying as
    ``checkpointing._flatten_tree`` — the two formats must agree on names."""
    from ..checkpointing import _path_part

    return [
        (".".join(_path_part(p) for p in path), leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    ]


def _normalize_index(index, shape) -> list[list[int]]:
    """A shard's ``index`` (tuple of slices, possibly open-ended) as
    concrete ``[start, stop]`` pairs."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _spec_repr(leaf) -> str | None:
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    return None if spec is None else str(spec)


def collect_addressable_pieces(tree) -> tuple[dict[str, np.ndarray], dict[str, Any]]:
    """Snapshot THIS host's addressable pieces of every leaf.

    Returns ``(pieces, table)``: ``pieces`` maps ``"<key>::p<i>" →
    np.ndarray`` (what this host writes to its shard file); ``table`` maps
    the dotted key to its manifest entry (global shape, dtype, sharding
    spec, and this host's piece offsets — the ``file`` field is filled in
    by the writer once the shard file name is known).

    Device→host copies happen here, on the calling thread — this is the
    snapshot point for async saves. No collectives: addressable shards are
    local by definition.
    """
    pieces: dict[str, np.ndarray] = {}
    table: dict[str, Any] = {}
    for key, leaf in _tree_items(tree):
        entry_pieces = []
        if isinstance(leaf, jax.Array) and getattr(leaf, "sharding", None) is not None:
            seen: set[tuple] = set()
            n = 0
            for shard in leaf.addressable_shards:
                if getattr(shard, "replica_id", 0) != 0:
                    continue
                offsets = _normalize_index(shard.index, leaf.shape)
                dedup_key = tuple(tuple(p) for p in offsets)
                if dedup_key in seen:
                    continue
                seen.add(dedup_key)
                piece_key = f"{key}::p{n}"
                pieces[piece_key] = np.asarray(shard.data)
                entry_pieces.append({"piece": piece_key, "offsets": offsets})
                n += 1
            global_shape = list(leaf.shape)
            dtype = str(leaf.dtype)
        else:
            value = np.asarray(jax.device_get(leaf))
            piece_key = f"{key}::p0"
            pieces[piece_key] = value
            entry_pieces.append(
                {"piece": piece_key, "offsets": _normalize_index((slice(None),) * value.ndim, value.shape)}
            )
            global_shape = list(value.shape)
            dtype = str(value.dtype)
        table[key] = {
            "global_shape": global_shape,
            "dtype": dtype,
            "spec": _spec_repr(leaf),
            "pieces": entry_pieces,
        }
    return pieces, table


def merge_piece_tables(tables: list[dict[str, Any]]) -> dict[str, Any]:
    """Union of per-host piece tables into one manifest entry per key
    (hosts contribute disjoint pieces of the same global arrays)."""
    merged: dict[str, Any] = {}
    for table in tables:
        for key, entry in table.items():
            if key not in merged:
                merged[key] = {k: v for k, v in entry.items() if k != "pieces"}
                merged[key]["pieces"] = []
            merged[key]["pieces"].extend(entry["pieces"])
    return merged


def _assemble_full(entry: dict[str, Any], load_piece: Callable[[dict], np.ndarray]) -> np.ndarray:
    """Gather-from-manifest fallback: rebuild the full global array on host
    from every piece's offsets."""
    shape = tuple(entry["global_shape"])
    pieces = entry["pieces"]
    if not pieces:
        raise ValueError("manifest entry has no pieces")
    first = load_piece(pieces[0])
    if not shape:  # scalar
        return np.asarray(first)
    out = np.empty(shape, dtype=first.dtype)
    # coverage must be PROVEN before handing back np.empty contents — a
    # single partial piece (torn multi-host checkpoint) is as dangerous as
    # a gap between several
    full_cover = len(pieces) == 1 and pieces[0]["offsets"] == [[0, d] for d in shape]
    filled = None if full_cover else np.zeros(shape, dtype=bool)
    for piece in pieces:
        data = np.asarray(load_piece(piece))
        idx = tuple(slice(start, stop) for start, stop in piece["offsets"])
        out[idx] = data
        if filled is not None:
            filled[idx] = True
    if filled is not None and not filled.all():
        raise ValueError("checkpoint pieces do not cover the full array")
    return out


def _restore_leaf(key: str, leaf, entry: dict[str, Any], load_piece) -> Any:
    if tuple(entry["global_shape"]) != tuple(np.shape(leaf)):
        raise ValueError(
            f"shape mismatch for {key}: checkpoint {entry['global_shape']} "
            f"vs live {np.shape(leaf)}"
        )
    if isinstance(leaf, jax.Array) and getattr(leaf, "sharding", None) is not None:
        by_offsets = {
            tuple(tuple(p) for p in piece["offsets"]): piece for piece in entry["pieces"]
        }
        shards = leaf.addressable_shards
        wanted = [
            (shard.device, tuple(tuple(p) for p in _normalize_index(shard.index, leaf.shape)))
            for shard in shards
        ]
        if shards and all(offsets in by_offsets for _, offsets in wanted):
            # same-sharding fast path: one local piece per device shard
            arrays = [
                jax.device_put(
                    np.asarray(load_piece(by_offsets[offsets])).astype(leaf.dtype),
                    device,
                )
                for device, offsets in wanted
            ]
            return jax.make_array_from_single_device_arrays(
                leaf.shape, leaf.sharding, arrays
            )
        full = _assemble_full(entry, load_piece)
        return jax.device_put(full.astype(leaf.dtype), leaf.sharding)
    value = _assemble_full(entry, load_piece)
    return value.astype(np.asarray(leaf).dtype) if hasattr(leaf, "dtype") else value


def restore_tree_from_pieces(
    live_tree,
    arrays_meta: dict[str, Any],
    load_piece: Callable[[dict], np.ndarray],
):
    """Rebuild a pytree with the structure + shardings of ``live_tree`` from
    a manifest piece table. ``load_piece(piece_entry) → np.ndarray`` hands
    back one piece's data (the caller owns file access + caching)."""
    leaves = []
    for key, leaf in _tree_items(live_tree):
        if key not in arrays_meta:
            raise KeyError(f"checkpoint manifest is missing tensor {key!r}")
        leaves.append(_restore_leaf(key, leaf, arrays_meta[key], load_piece))
    return jax.tree.unflatten(jax.tree.structure(live_tree), leaves)
