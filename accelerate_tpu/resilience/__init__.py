"""Fault-tolerance subsystem: preemption-safe distributed checkpointing.

Four parts (ISSUE 2 / SURVEY §5 — the native layer the reference delegates
to FSDP/DeepSpeed sharded state dicts, built here on JAX addressable shards
in the spirit of Orbax async sharded checkpointing):

* :mod:`.manifest` — the checkpoint manifest (per-file sizes + CRCs, global
  shapes, sharding specs, host count) and validation: a checkpoint either
  validates completely or is skipped by auto-resume.
* :mod:`.distributed` — per-host sharded array IO: each host writes only its
  addressable shards into ``shard_<host>/``; load reassembles them (same
  sharding fast path) or gathers from the manifest (cross-mesh restore).
* :mod:`.preemption` — SIGTERM/SIGINT handlers + optional GCE
  maintenance-event poller; ``Accelerator`` checks the flag at step
  boundaries, reaches cross-host consensus, emergency-saves once, and exits
  cleanly with a sentinel file.
* :mod:`.retry` — bounded exponential-backoff retries around checkpoint IO
  so flaky GCS-fuse/NFS writes don't kill a run.

Atomic commit lives in :mod:`accelerate_tpu.checkpointing`: every save
lands in ``<dir>.tmp`` and is ``os.rename``'d into place after a cross-host
barrier, so a checkpoint directory either exists completely or not at all.
"""

from .manifest import (
    MANIFEST_NAME,
    SENTINEL_NAME,
    build_manifest,
    find_latest_valid_checkpoint,
    read_manifest,
    validate_checkpoint,
    write_manifest,
)
from .distributed import (
    collect_addressable_pieces,
    restore_tree_from_pieces,
)
from .preemption import PreemptionHandler, get_active_handler
from .retry import run_with_retries

__all__ = [
    "MANIFEST_NAME",
    "SENTINEL_NAME",
    "build_manifest",
    "collect_addressable_pieces",
    "find_latest_valid_checkpoint",
    "get_active_handler",
    "PreemptionHandler",
    "read_manifest",
    "restore_tree_from_pieces",
    "run_with_retries",
    "validate_checkpoint",
    "write_manifest",
]
