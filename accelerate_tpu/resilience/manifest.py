"""Checkpoint manifest: the completeness certificate of a checkpoint dir.

``manifest.json`` is written LAST (immediately before the atomic
``os.rename`` of ``checkpoint_N.tmp`` → ``checkpoint_N``), so its presence
plus a passing validation means every byte of the checkpoint landed:

```json
{
  "format_version": 1,
  "kind": "sharded" | "gathered",
  "step": 120, "iteration": 4,
  "host_count": 2, "timestamp": 1754200000.0,
  "files": {"shard_00000/model.safetensors": {"bytes": 4096, "crc32": 123}},
  "arrays": {            // sharded kind only: piece table per component
    "model_0": {
      "layer.w": {
        "global_shape": [64, 64], "dtype": "float32", "spec": "('fsdp',)",
        "pieces": [{"file": "shard_00000/model_0.safetensors",
                    "piece": "layer.w::p0", "offsets": [[0, 32], [0, 64]]}]
      }
    }
  }
}
```

Validation re-checks existence, size, and CRC32 of every listed file —
a truncated or bit-rotted checkpoint fails closed and auto-resume falls
back to the previous valid one. Legacy (pre-manifest) checkpoint dirs are
accepted when their ``accelerator_state.json`` is present, so old runs
stay resumable.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any

from ..logging import get_logger
from .retry import run_with_retries

logger = get_logger(__name__)

MANIFEST_NAME = "manifest.json"
#: written next to the checkpoints on a preemption-triggered emergency save
SENTINEL_NAME = "PREEMPTED.json"
FORMAT_VERSION = 1

_CRC_CHUNK = 1 << 20


def file_crc32(path: str) -> int:
    """Streaming CRC32 of a file (reads back what was written — on a flaky
    mount this doubles as write verification)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CRC_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _walk_files(root: str) -> list[str]:
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            if name == MANIFEST_NAME:
                continue
            full = os.path.join(dirpath, name)
            out.append(os.path.relpath(full, root))
    return sorted(out)


def build_manifest(
    checkpoint_dir: str,
    kind: str = "gathered",
    step: int | None = None,
    iteration: int | None = None,
    host_count: int = 1,
    arrays: dict[str, Any] | None = None,
    extra: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """Walk ``checkpoint_dir`` and produce the manifest dict (sizes + CRCs
    of every file currently in it)."""
    import time

    files = {}
    for rel in _walk_files(checkpoint_dir):
        full = os.path.join(checkpoint_dir, rel)
        files[rel.replace(os.sep, "/")] = {
            "bytes": os.path.getsize(full),
            "crc32": file_crc32(full),
        }
    manifest: dict[str, Any] = {
        "format_version": FORMAT_VERSION,
        "kind": kind,
        "host_count": int(host_count),
        "timestamp": time.time(),
        "files": files,
    }
    if step is not None:
        manifest["step"] = int(step)
    if iteration is not None:
        manifest["iteration"] = int(iteration)
    if arrays:
        manifest["arrays"] = arrays
    if extra:
        manifest.update(extra)
    return manifest


def write_manifest(checkpoint_dir: str, manifest: dict[str, Any]) -> str:
    """Durably write ``manifest.json`` (write → flush → fsync) — the commit
    rename that follows must never promote a dir whose certificate is
    itself torn."""
    path = os.path.join(checkpoint_dir, MANIFEST_NAME)

    def _write():
        with open(path, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())

    run_with_retries(_write, what=f"write {path}")
    return path


def read_manifest(checkpoint_dir: str) -> dict[str, Any] | None:
    path = os.path.join(checkpoint_dir, MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def validate_checkpoint(
    checkpoint_dir: str, check_crc: bool = True
) -> tuple[bool, str]:
    """Is ``checkpoint_dir`` a complete, uncorrupted checkpoint?

    Returns ``(ok, reason)``. A ``.tmp`` dir (interrupted, uncommitted
    save) is always invalid. A dir with a manifest must have every listed
    file present with the recorded size (and CRC32 when ``check_crc``).
    A legacy dir without a manifest passes when its
    ``accelerator_state.json`` exists — pre-manifest saves wrote no
    certificate, and rejecting them all would strand old runs.
    """
    if not os.path.isdir(checkpoint_dir):
        return False, "not a directory"
    if checkpoint_dir.rstrip("/").endswith(".tmp"):
        return False, "uncommitted .tmp directory"
    manifest = read_manifest(checkpoint_dir)
    if manifest is None:
        if os.path.exists(os.path.join(checkpoint_dir, "accelerator_state.json")):
            return True, "legacy checkpoint (no manifest)"
        return False, "no manifest and no accelerator_state.json"
    for rel, meta in manifest.get("files", {}).items():
        full = os.path.join(checkpoint_dir, rel)
        if not os.path.exists(full):
            return False, f"missing file {rel}"
        size = os.path.getsize(full)
        if size != meta.get("bytes"):
            return False, f"size mismatch for {rel}: {size} != {meta.get('bytes')}"
        if check_crc and meta.get("crc32") is not None:
            if file_crc32(full) != meta["crc32"]:
                return False, f"checksum mismatch for {rel}"
    return True, "ok"


def find_latest_valid_checkpoint(
    checkpoints_dir: str, check_crc: bool = True
) -> str | None:
    """Newest ``checkpoint_<i>`` under ``checkpoints_dir`` that validates;
    corrupt/partial ones are skipped with a warning (the auto-resume
    contract: never select a ``.tmp`` or torn checkpoint)."""
    from ..checkpointing import _sorted_checkpoints

    for candidate in reversed(_sorted_checkpoints(checkpoints_dir)):
        ok, reason = validate_checkpoint(candidate, check_crc=check_crc)
        if ok:
            return candidate
        logger.warning("skipping invalid checkpoint %s: %s", candidate, reason)
    return None
